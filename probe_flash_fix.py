"""Hardware validation of the flash-backward fix (loop impl) — paired with
the scratch impl so one window yields both verdicts:

  - scratch (r3 probe_flash: dq/dk/dbias NaN on Mosaic) — expected FAIL,
    confirming the diagnosis is stable;
  - loop (fori_loop per output block, no cross-grid-step scratch, the new
    FLASH_BWD_IMPL default) — the fix verdict;
  - timing: fwd+bwd at GPT-2s 2k shapes for both impls vs the XLA
    blockwise fallback (the loop impl must not give back the 1.34x win).
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback

WATCHDOG_S = 480.0
_last = [time.monotonic()]


def _pet():
    _last[0] = time.monotonic()


def _watchdog():
    while True:
        time.sleep(5.0)
        if time.monotonic() - _last[0] > WATCHDOG_S:
            print(f"RESULT watchdog=hang idle_s={WATCHDOG_S}", flush=True)
            os._exit(3)


threading.Thread(target=_watchdog, daemon=True).start()


def main() -> None:
    import jax

    if os.environ.get("KFT_BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["KFT_BENCH_PLATFORM"])
    import jax.numpy as jnp

    from kubeflow_tpu.parallel.ring_attention import (
        _flash_backward,
        _flash_forward,
        blockwise_attention,
    )

    dev = jax.devices()[0]
    print(f"RESULT device_kind={dev.device_kind!r} platform={dev.platform}",
          flush=True)
    float((jnp.ones((8, 8)) @ jnp.ones((8, 8))).sum())
    _pet()

    def born(*shape, key, dtype=jnp.bfloat16):
        x = jax.random.normal(jax.random.PRNGKey(key), shape, jnp.float32)
        return jax.jit(lambda v: (v * 0.125).astype(dtype))(x)

    # ---- correctness: both impls vs blockwise reference grads ------------
    b, l, h, d = 2, 1024, 12, 64
    q = born(b, l, h, d, key=0)
    k = born(b, l, h, d, key=1)
    v = born(b, l, h, d, key=2)
    bias = jnp.zeros((b, 1, 1, l), jnp.bfloat16)
    ct = born(b, l, h, d, key=3)

    for causal in (False, True):
        tag = "causal" if causal else "full"

        def loss_ref(q, k, v, bias):
            return (blockwise_attention(q, k, v, bias, block=256,
                                        causal=causal).astype(jnp.float32)
                    * ct.astype(jnp.float32)).sum()

        try:
            ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2, 3)))(
                q, k, v, bias)
            out, lse = jax.jit(
                lambda q, k, v, bias, c=causal: _flash_forward(
                    q, k, v, bias, 256, 256, c, want_lse=True)
            )(q, k, v, bias)
            _pet()
            for impl in ("loop", "scratch"):
                try:
                    got = jax.jit(
                        lambda q, k, v, bias, out, lse, g, c=causal,
                               i=impl: _flash_backward(
                            q, k, v, bias, out, lse, g, 256, 256, c, impl=i)
                    )(q, k, v, bias, out, lse, ct)
                    errs = [
                        float(jnp.max(jnp.abs(
                            a.astype(jnp.float32) - r.astype(jnp.float32))))
                        for a, r in zip(got, ref)
                    ]
                    ok = max(errs[:3]) < 0.25 and errs[3] < 2.0
                    print(f"RESULT {impl}_{tag}="
                          f"{'PASS' if ok else 'FAIL'} dq={errs[0]:.4g} "
                          f"dk={errs[1]:.4g} dv={errs[2]:.4g} "
                          f"dbias={errs[3]:.4g}", flush=True)
                except Exception as exc:  # noqa: BLE001 — verdict, not crash
                    print(f"RESULT {impl}_{tag}=ERROR {type(exc).__name__}",
                          flush=True)
                _pet()
        except Exception as exc:  # noqa: BLE001
            print(f"RESULT setup_{tag}=ERROR {type(exc).__name__}", flush=True)
            traceback.print_exc(file=sys.stderr)
            _pet()

    # ---- timing: fwd+bwd at GPT-2s 2k shapes -----------------------------
    from kubeflow_tpu.parallel import ring_attention as ra

    b, l, h, d = 4, 2048, 12, 64
    q = born(b, l, h, d, key=10)
    k = born(b, l, h, d, key=11)
    v = born(b, l, h, d, key=12)
    bias = jnp.zeros((b, 1, 1, l), jnp.bfloat16)
    ct = born(b, l, h, d, key=13)
    fwd_flops = 2 * 2 * b * h * l * l * d * 0.5
    total_flops = fwd_flops * 3.5

    def timed(fn, *args, iters=8):
        val = fn(*args)
        val = fn(*args)
        jax.tree_util.tree_map(
            lambda x: float(x.astype(jnp.float32).sum()), val)
        _pet()
        t0 = time.perf_counter()
        for _ in range(iters):
            val = fn(*args)
        jax.tree_util.tree_map(
            lambda x: float(x.astype(jnp.float32).sum()), val)
        return (time.perf_counter() - t0) / iters

    from kubeflow_tpu.parallel.ring_attention import flash_attention

    for impl in ("loop", "scratch"):
        ra.FLASH_BWD_IMPL = impl

        def loss(q, k, v, bias):
            return (flash_attention(q, k, v, bias, block=256, causal=True)
                    .astype(jnp.float32) * ct.astype(jnp.float32)).sum()

        try:
            fn = jax.jit(jax.grad(loss, argnums=(0, 1, 2, 3)))
            dt = timed(fn, q, k, v, bias)
            print(f"RESULT flash_{impl}_fwdbwd_ms={dt * 1e3:.2f} "
                  f"tflops={total_flops / dt / 1e12:.2f}", flush=True)
        except Exception as exc:  # noqa: BLE001
            print(f"RESULT flash_{impl}_timing=ERROR {type(exc).__name__}",
                  flush=True)
        _pet()
    ra.FLASH_BWD_IMPL = "loop"

    def loss_bw(q, k, v, bias):
        return (blockwise_attention(q, k, v, bias, block=256, causal=True)
                .astype(jnp.float32) * ct.astype(jnp.float32)).sum()

    try:
        dt = timed(jax.jit(jax.grad(loss_bw, argnums=(0, 1, 2, 3))),
                   q, k, v, bias)
        print(f"RESULT xla_blockwise_fwdbwd_ms={dt * 1e3:.2f} "
              f"tflops={total_flops / dt / 1e12:.2f}", flush=True)
    except Exception as exc:  # noqa: BLE001
        print(f"RESULT xla_timing=ERROR {type(exc).__name__}", flush=True)

    print("RESULT probe_flash_fix=complete", flush=True)


if __name__ == "__main__":
    main()
