"""Minimal Mosaic flash-backward NaN bisect — term isolation ONLY.

Both backward impls (scratch accumulators AND fori-loop) NaN identically on
hardware (probe_flash_fix r3: dq/dk/dbias NaN, dv clean, interpret passes),
so the bug is in the shared ds = p*(dp - dd) term path, not the grid-revisit
machinery. This probe emits each intermediate from a grid=(1,) kernel so a
single short tunnel window localizes the NaN-producing term. Variants cover
the remaining deltas to the real kernel: the bias-row operand/add and a
multi-(batch*head) grid.

Every term prints its own RESULT line immediately — a partial window still
bisects. CPU interpret mode passes all terms (verified before queueing).
"""

from __future__ import annotations

import functools
import os
import threading
import time

WATCHDOG_S = 300.0
_last = [time.monotonic()]


def _pet():
    _last[0] = time.monotonic()


def _watchdog():
    while True:
        time.sleep(5.0)
        if time.monotonic() - _last[0] > WATCHDOG_S:
            print("RESULT watchdog=hang", flush=True)
            os._exit(3)


threading.Thread(target=_watchdog, daemon=True).start()


def main() -> None:
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if os.environ.get("KFT_BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["KFT_BENCH_PLATFORM"])

    interpret = jax.default_backend() == "cpu"
    print(f"RESULT backend={jax.default_backend()} interpret={interpret}",
          flush=True)
    float((jnp.ones((8, 8)) @ jnp.ones((8, 8))).sum())
    _pet()

    block = 256
    d = 64
    scale = 1.0 / (d ** 0.5)

    def born(*shape, key, dtype=jnp.bfloat16):
        x = jax.random.normal(jax.random.PRNGKey(key), shape, jnp.float32)
        return jax.jit(lambda v: (v * 0.125).astype(dtype))(x)

    q = born(1, block, d, key=0)
    k = born(1, block, d, key=1)
    v = born(1, block, d, key=2)
    do = born(1, block, d, key=3)
    bias = jnp.zeros((1, 1, 1, block), jnp.bfloat16)
    s_full = (q[0].astype(jnp.float32) @ k[0].astype(jnp.float32).T) * scale
    lse_host = jax.nn.logsumexp(s_full, axis=-1, keepdims=True)
    p_host = jnp.exp(s_full - lse_host)
    o_host = p_host @ v[0].astype(jnp.float32)
    dd_host = (do[0].astype(jnp.float32) * o_host).sum(-1, keepdims=True)
    lse = jax.device_put(lse_host[None])        # (1, block, 1) f32
    dd = jax.device_put(dd_host[None])          # (1, block, 1) f32

    def nan_count(x):
        return int(jnp.isnan(x.astype(jnp.float32)).sum())

    # Each term is its own kernel; dead inputs get DCE'd so each RESULT line
    # isolates exactly the live dataflow for that term.
    def term_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref, bias_ref,
                    out_ref, *, term: str):
        qb = q_ref[0]
        kb = k_ref[0]
        s = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if term.endswith("_bias"):
            s = s + bias_ref[0, 0, 0, :].astype(jnp.float32)[None, :]
        p = jnp.exp(s - lse_ref[0])
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        base = term.replace("_bias", "")
        if base == "p":
            out_ref[0] = p
        elif base == "dp":
            out_ref[0] = dp
        elif base == "ddb":
            out_ref[0] = jnp.broadcast_to(dd_ref[0], (block, block))
        elif base == "dpmdd":
            out_ref[0] = dp - dd_ref[0]
        elif base == "ds":
            out_ref[0] = p * (dp - dd_ref[0])
        elif base == "dq":
            ds = p * (dp - dd_ref[0])
            out_ref[0] = jax.lax.dot_general(
                ds.astype(kb.dtype), kb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    terms = ("p", "dp", "ddb", "dpmdd", "ds", "dq", "ds_bias", "dq_bias")
    for term in terms:
        out_last = d if term.replace("_bias", "") == "dq" else block
        try:
            out = pl.pallas_call(
                functools.partial(term_kernel, term=term),
                grid=(1,),
                in_specs=[
                    pl.BlockSpec((1, block, d), lambda i: (0, 0, 0)),
                    pl.BlockSpec((1, block, d), lambda i: (0, 0, 0)),
                    pl.BlockSpec((1, block, d), lambda i: (0, 0, 0)),
                    pl.BlockSpec((1, block, d), lambda i: (0, 0, 0)),
                    pl.BlockSpec((1, block, 1), lambda i: (0, 0, 0)),
                    pl.BlockSpec((1, block, 1), lambda i: (0, 0, 0)),
                    pl.BlockSpec((1, 1, 1, block), lambda i: (0, 0, 0, 0)),
                ],
                out_specs=pl.BlockSpec((1, block, out_last),
                                       lambda i: (0, 0, 0)),
                out_shape=jax.ShapeDtypeStruct((1, block, out_last),
                                               jnp.float32),
                interpret=interpret,
            )(q, k, v, do, lse, dd, bias)
            print(f"RESULT stage1_{term}_nan={nan_count(out)}"
                  f" max={float(jnp.nanmax(jnp.abs(out))):.4g}", flush=True)
        except Exception as exc:  # noqa: BLE001 — verdict line, keep going
            print(f"RESULT stage1_{term}=ERROR {type(exc).__name__}",
                  flush=True)
        _pet()

    # multi-bh grid over the full ds term (bias in): the shape the real dq
    # kernel runs at minus the kv-block axis
    bh = 4
    qm = born(bh, block, d, key=20)
    km = born(bh, block, d, key=21)
    vm = born(bh, block, d, key=22)
    dom = born(bh, block, d, key=23)
    biasm = jnp.zeros((bh, 1, 1, block), jnp.bfloat16)
    sm = jnp.einsum("bqd,bkd->bqk", qm.astype(jnp.float32),
                    km.astype(jnp.float32)) * scale
    lsem_h = jax.nn.logsumexp(sm, axis=-1, keepdims=True)
    pm = jnp.exp(sm - lsem_h)
    om = jnp.einsum("bqk,bkd->bqd", pm, vm.astype(jnp.float32))
    ddm_h = (dom.astype(jnp.float32) * om).sum(-1, keepdims=True)
    lsem = jax.device_put(lsem_h)
    ddm = jax.device_put(ddm_h)

    try:
        out = pl.pallas_call(
            functools.partial(term_kernel, term="dq_bias"),
            grid=(bh,),
            in_specs=[
                pl.BlockSpec((1, block, d), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, block, d), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, block, d), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, block, d), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, block, 1), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, block, 1), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, 1, 1, block), lambda i: (i, 0, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, block, d), lambda i: (i, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((bh, block, d), jnp.float32),
            interpret=interpret,
        )(qm, km, vm, dom, lsem, ddm, biasm)
        print(f"RESULT stage1_dq_bhgrid_nan={nan_count(out)}"
              f" max={float(jnp.nanmax(jnp.abs(out))):.4g}", flush=True)
    except Exception as exc:  # noqa: BLE001
        print(f"RESULT stage1_dq_bhgrid=ERROR {type(exc).__name__}",
              flush=True)
    _pet()

    print("RESULT probe_flash_stage1=complete", flush=True)


if __name__ == "__main__":
    main()
