"""Round-5 consolidated flash-backward hardware probe — ONE tunnel window
is decisive in BOTH bisect branches (VERDICT r4 weak #2: one window, one
fix candidate):

  A. candidate verdicts at production shapes, causal + full:
     - loop2 (candidate A): D = Σ dO∘O recomputed in-kernel from (dO, O)
       tiles; no lane-dim-1 dd operand at all.
     - ddpre (candidate B): the SAME loop kernels as r3, but dd produced
       by a trivial pallas pre-kernel instead of an XLA reduction — the
       single-variable producer-layout experiment.
  B. term bisect, host-fed: each backward intermediate (p, dp, dd-bcast,
     dp−dd, ds, dq-tile) from a grid=(1,) kernel with HOST-computed
     lse/dd — if ds NaNs even here, the operand-producer-layout theory
     is wrong.
  C. term bisect, device-fed: same kernels with the DEVICE pallas
     forward's lse and an XLA-computed dd — the real pipeline. B clean +
     C NaN pins the producer layout as the root cause.
  C2. term bisect, prekernel-fed: same kernels with dd from the pallas
     pre-kernel — C NaN + C2 clean confirms candidate B at term level;
     C NaN + C2 NaN means the lane-dim-1 CONSUMER BlockSpec is the bug
     (loop2 remains the fix either way).
  D. loop control: the r3 impl, expected FAIL (confirms the diagnosis is
     stable, not a flaky window).
  E. xla-impl verdict: numerics of the current default backward on
     hardware (folds probe_flash_xlabwd's correctness half in).
  F. timings at GPT-2s 2k causal shapes: loop2 vs ddpre vs xla backward
     fwd+bwd — the FLASH_BWD_IMPL decision number (tunnel_watch3.sh
     flips the bench onto the fastest PASSing candidate).

Every RESULT prints immediately so a partial window still informs; all
sections are try/except'd; watchdog exits 3 on a hung tunnel so
tunnel_watch retries. CPU interpret mode passes all sections (verified
before queueing).
"""

from __future__ import annotations

import functools
import os
import sys
import threading
import time
import traceback

WATCHDOG_S = 300.0
_last = [time.monotonic()]


def _pet():
    _last[0] = time.monotonic()


def _watchdog():
    while True:
        time.sleep(5.0)
        if time.monotonic() - _last[0] > WATCHDOG_S:
            print(f"RESULT watchdog=hang idle_s={WATCHDOG_S}", flush=True)
            os._exit(3)


threading.Thread(target=_watchdog, daemon=True).start()


import probe_common


def _banked_keys() -> set[str]:
    """Cross-window resume: sections whose RESULT keys are banked are
    SKIPPED on re-run (probe_common; ERROR values never bank — the probe
    exits nonzero on any ERROR so the stage stays retryable)."""
    return probe_common.banked_keys("probe_flash_r5.txt")


def main() -> None:
    import jax

    if os.environ.get("KFT_BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["KFT_BENCH_PLATFORM"])
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from kubeflow_tpu.parallel import ring_attention as ra
    from kubeflow_tpu.parallel.ring_attention import (
        _flash_backward,
        _flash_forward,
        blockwise_attention,
        flash_attention,
    )

    banked = _banked_keys()
    interpret = jax.default_backend() == "cpu"
    dev = jax.devices()[0]
    print(f"RESULT device_kind={dev.device_kind!r} platform={dev.platform} "
          f"interpret={interpret}", flush=True)
    float((jnp.ones((8, 8)) @ jnp.ones((8, 8))).sum())
    _pet()

    def born(*shape, key, dtype=jnp.bfloat16):
        x = jax.random.normal(jax.random.PRNGKey(key), shape, jnp.float32)
        return jax.jit(lambda v: (v * 0.125).astype(dtype))(x)

    def nan_count(x):
        return int(jnp.isnan(jnp.asarray(x, jnp.float32)).sum())

    # ---------------- A: loop2 verdict / D: loop control / E: xla --------
    # interpret mode runs grid steps in Python: shrink shapes on CPU (the
    # CPU pass only validates code paths; hardware runs production shapes)
    if interpret:
        b, l, h, d = 1, 256, 2, 64
    else:
        b, l, h, d = 2, 1024, 12, 64
    q = born(b, l, h, d, key=0)
    k = born(b, l, h, d, key=1)
    v = born(b, l, h, d, key=2)
    bias = jnp.zeros((b, 1, 1, l), jnp.bfloat16)
    ct = born(b, l, h, d, key=3)

    for causal in (False, True):
        tag = "causal" if causal else "full"
        impls_todo = [i for i in ("loop2", "ddpre", "loop", "xla")
                      if f"{i}_{tag}" not in banked]
        if not impls_todo:
            continue  # whole flavor banked by an earlier window

        def loss_ref(q, k, v, bias, c=causal):
            # vjp="autodiff" pins the HISTORIC reference: every r3/r4/r5
            # artifact compared against the scan-autodiff grads, and the
            # r5 keys are classified as the suspect-autodiff tier by
            # tunnel_watch3.pick_flash_bwd — a re-run must not silently
            # switch to the r5 custom-VJP default (r5b owns that tier)
            return (blockwise_attention(q, k, v, bias, block=256,
                                        causal=c, vjp="autodiff"
                                        ).astype(jnp.float32)
                    * ct.astype(jnp.float32)).sum()

        try:
            ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2, 3)))(
                q, k, v, bias)
            out, lse = jax.jit(
                lambda q, k, v, bias, c=causal: _flash_forward(
                    q, k, v, bias, 256, 256, c, want_lse=True)
            )(q, k, v, bias)
            print(f"RESULT fwd_{tag}_out_nan={nan_count(out)} "
                  f"lse_nan={nan_count(lse)}", flush=True)
            _pet()
            for impl in impls_todo:
                try:
                    got = jax.jit(
                        lambda q, k, v, bias, out, lse, g, c=causal,
                               i=impl: _flash_backward(
                            q, k, v, bias, out, lse, g, 256, 256, c, impl=i)
                    )(q, k, v, bias, out, lse, ct)
                    errs = [
                        float(jnp.max(jnp.abs(
                            a.astype(jnp.float32) - r.astype(jnp.float32))))
                        for a, r in zip(got, ref)
                    ]
                    ok = max(errs[:3]) < 0.25 and errs[3] < 2.0
                    print(f"RESULT {impl}_{tag}="
                          f"{'PASS' if ok else 'FAIL'} dq={errs[0]:.4g} "
                          f"dk={errs[1]:.4g} dv={errs[2]:.4g} "
                          f"dbias={errs[3]:.4g}", flush=True)
                except Exception as exc:  # noqa: BLE001 — verdict, not crash
                    print(f"RESULT {impl}_{tag}=ERROR {type(exc).__name__}",
                          flush=True)
                    probe_common.record_error(f"{impl}_{tag}")
                _pet()
        except Exception as exc:  # noqa: BLE001
            print(f"RESULT setup_{tag}=ERROR {type(exc).__name__}", flush=True)
            probe_common.record_error(f"setup_{tag}")
            traceback.print_exc(file=sys.stderr)
            _pet()

    # ---------------- A2: sliding-window kernels on Mosaic ---------------
    # window=256 at the same production shape: fwd + loop2/xla backwards
    # vs the blockwise windowed reference (the r4 O(L·W) kernels are
    # interpret-validated only until this line records PASS)
    swa_todo = [i for i in ("loop2", "ddpre", "xla")
                if f"swa_{i}" not in banked]
    try:
        win = 64 if interpret else 256

        def loss_wref(q, k, v, bias):
            # vjp="autodiff": same historic-reference pin as loss_ref
            return (blockwise_attention(q, k, v, bias, block=256,
                                        causal=True, window=win,
                                        vjp="autodiff").astype(jnp.float32)
                    * ct.astype(jnp.float32)).sum()

        if not swa_todo and "swa_fwd" in banked:
            raise StopIteration  # whole section banked
        wref = jax.jit(jax.grad(loss_wref, argnums=(0, 1, 2, 3)))(
            q, k, v, bias)
        wout, wlse = jax.jit(
            lambda q, k, v, bias: _flash_forward(
                q, k, v, bias, 256, 256, True, want_lse=True, window=win)
        )(q, k, v, bias)
        ref_out = jax.jit(
            lambda q, k, v, bias: blockwise_attention(
                q, k, v, bias, block=256, causal=True, window=win)
        )(q, k, v, bias)
        fwd_err = float(jnp.max(jnp.abs(
            wout.astype(jnp.float32) - ref_out.astype(jnp.float32))))
        print(f"RESULT swa_fwd={'PASS' if fwd_err < 0.02 else 'FAIL'} "
              f"err={fwd_err:.4g} window={win}", flush=True)
        _pet()
        for impl in swa_todo:
            try:
                got = jax.jit(
                    lambda q, k, v, bias, out, lse, g, i=impl:
                    _flash_backward(q, k, v, bias, out, lse, g, 256, 256,
                                    True, impl=i, window=win)
                )(q, k, v, bias, wout, wlse, ct)
                errs = [float(jnp.max(jnp.abs(
                    a.astype(jnp.float32) - r.astype(jnp.float32))))
                    for a, r in zip(got, wref)]
                ok = max(errs[:3]) < 0.25 and errs[3] < 2.0
                print(f"RESULT swa_{impl}={'PASS' if ok else 'FAIL'} "
                      f"dq={errs[0]:.4g} dk={errs[1]:.4g} dv={errs[2]:.4g} "
                      f"dbias={errs[3]:.4g}", flush=True)
            except Exception as exc:  # noqa: BLE001
                print(f"RESULT swa_{impl}=ERROR {type(exc).__name__}",
                      flush=True)
                probe_common.record_error(f"swa_{impl}")
            _pet()
    except StopIteration:
        pass  # banked by an earlier window
    except Exception as exc:  # noqa: BLE001
        print(f"RESULT swa_setup=ERROR {type(exc).__name__}", flush=True)
        probe_common.record_error("swa_setup")
        _pet()

    # ---------------- B/C: term bisect, host-fed then device-fed ---------
    block = 128 if interpret else 256
    dd_ = 64
    scale = 1.0 / (dd_ ** 0.5)
    q1 = born(1, block, dd_, key=10)
    k1 = born(1, block, dd_, key=11)
    v1 = born(1, block, dd_, key=12)
    do1 = born(1, block, dd_, key=13)
    bias1 = jnp.zeros((1, 1, 1, block), jnp.bfloat16)

    def term_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref, out_ref,
                    *, term: str):
        qb = q_ref[0]
        kb = k_ref[0]
        s = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse_ref[0])
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if term == "p":
            out_ref[0] = p
        elif term == "dp":
            out_ref[0] = dp
        elif term == "ddb":
            out_ref[0] = jnp.broadcast_to(dd_ref[0], (block, block))
        elif term == "dpmdd":
            out_ref[0] = dp - dd_ref[0]
        elif term == "ds":
            out_ref[0] = p * (dp - dd_ref[0])
        elif term == "dq":
            ds = p * (dp - dd_ref[0])
            out_ref[0] = jax.lax.dot_general(
                ds.astype(kb.dtype), kb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    def run_terms(label, lse_a, dd_a):
        for term in ("p", "dp", "ddb", "dpmdd", "ds", "dq"):
            if f"{label}_{term}_nan" in banked:
                continue
            out_last = dd_ if term == "dq" else block
            try:
                out = pl.pallas_call(
                    functools.partial(term_kernel, term=term),
                    grid=(1,),
                    in_specs=[
                        pl.BlockSpec((1, block, dd_), lambda i: (0, 0, 0)),
                        pl.BlockSpec((1, block, dd_), lambda i: (0, 0, 0)),
                        pl.BlockSpec((1, block, dd_), lambda i: (0, 0, 0)),
                        pl.BlockSpec((1, block, dd_), lambda i: (0, 0, 0)),
                        pl.BlockSpec((1, block, 1), lambda i: (0, 0, 0)),
                        pl.BlockSpec((1, block, 1), lambda i: (0, 0, 0)),
                    ],
                    out_specs=pl.BlockSpec((1, block, out_last),
                                           lambda i: (0, 0, 0)),
                    out_shape=jax.ShapeDtypeStruct((1, block, out_last),
                                                   jnp.float32),
                    interpret=interpret,
                )(q1, k1, v1, do1, lse_a, dd_a)
                print(f"RESULT {label}_{term}_nan={nan_count(out)}"
                      f" max={float(jnp.nanmax(jnp.abs(out))):.4g}",
                      flush=True)
            except Exception as exc:  # noqa: BLE001
                print(f"RESULT {label}_{term}=ERROR {type(exc).__name__}",
                      flush=True)
                probe_common.record_error(f"{label}_{term}")
            _pet()

    try:
        # host-fed: lse/dd from f32 host math, device_put as plain arrays
        s_full = (q1[0].astype(jnp.float32) @ k1[0].astype(jnp.float32).T
                  ) * scale
        lse_host = jax.nn.logsumexp(s_full, axis=-1, keepdims=True)
        p_host = jnp.exp(s_full - lse_host)
        o_host = p_host @ v1[0].astype(jnp.float32)
        dd_host = (do1[0].astype(jnp.float32) * o_host).sum(-1, keepdims=True)
        run_terms("host", jax.device_put(lse_host[None]),
                  jax.device_put(dd_host[None]))
    except Exception as exc:  # noqa: BLE001
        print(f"RESULT host_terms=ERROR {type(exc).__name__}", flush=True)
        probe_common.record_error("host_terms")
        _pet()

    try:
        # device-fed: the real pipeline — pallas forward lse, XLA-reduce dd
        q4 = q1.reshape(1, block, 1, dd_)
        k4 = k1.reshape(1, block, 1, dd_)
        v4 = v1.reshape(1, block, 1, dd_)
        out_dev, lse_dev = jax.jit(
            lambda q, k, v, bias: _flash_forward(
                q, k, v, bias, block, block, False, want_lse=True)
        )(q4, k4, v4, bias1)
        of_dev = out_dev.transpose(0, 2, 1, 3).reshape(1, block, dd_)
        dd_dev = jax.jit(
            lambda g, o: (g.astype(jnp.float32) * o.astype(jnp.float32)
                          ).sum(-1, keepdims=True)
        )(do1, of_dev)
        print(f"RESULT dev_lse_nan={nan_count(lse_dev)} "
              f"dev_dd_nan={nan_count(dd_dev)}", flush=True)
        _pet()
        run_terms("dev", lse_dev, dd_dev)
    except Exception as exc:  # noqa: BLE001
        print(f"RESULT dev_terms=ERROR {type(exc).__name__}", flush=True)
        probe_common.record_error("dev_terms")
        traceback.print_exc(file=sys.stderr)
        _pet()
        of_dev = None

    # C2: same consumer kernels, dd from the pallas PRE-KERNEL — the
    # candidate-B experiment at term granularity. dev NaN + pre clean =>
    # producer layout confirmed, ddpre is a valid fix; dev NaN + pre NaN
    # => the lane-dim-1 consumer BlockSpec itself. Own try/except: a
    # Mosaic compile failure of the pre-kernel (the hypothesis under
    # test) must record as pre_terms=ERROR, not mislabel section C.
    try:
        if of_dev is None:
            raise RuntimeError("dev forward unavailable")
        dd_pre = jax.jit(
            lambda g, o: ra._dd_prekernel(
                g, o, b=1, h=1, lq=block, d=dd_, block_q=block, n_q=1,
                interpret=interpret)
        )(do1, of_dev)
        print(f"RESULT pre_dd_nan={nan_count(dd_pre)}", flush=True)
        _pet()
        run_terms("pre", lse_dev, dd_pre)
    except Exception as exc:  # noqa: BLE001
        print(f"RESULT pre_terms=ERROR {type(exc).__name__}", flush=True)
        probe_common.record_error("pre_terms")
        traceback.print_exc(file=sys.stderr)
        _pet()

    # ---------------- F: timings at GPT-2s 2k causal ---------------------
    if interpret:
        b, l, h, d = 1, 256, 2, 64
    else:
        b, l, h, d = 4, 2048, 12, 64
    q = born(b, l, h, d, key=20)
    k = born(b, l, h, d, key=21)
    v = born(b, l, h, d, key=22)
    bias = jnp.zeros((b, 1, 1, l), jnp.bfloat16)
    ct = born(b, l, h, d, key=23)
    fwd_flops = 2 * 2 * b * h * l * l * d * 0.5
    total_flops = fwd_flops * 3.5

    def timed(fn, *args, iters=8):
        val = fn(*args)
        val = fn(*args)
        jax.tree_util.tree_map(
            lambda x: float(x.astype(jnp.float32).sum()), val)
        _pet()
        t0 = time.perf_counter()
        for _ in range(iters):
            val = fn(*args)
        jax.tree_util.tree_map(
            lambda x: float(x.astype(jnp.float32).sum()), val)
        return (time.perf_counter() - t0) / iters

    for impl in ("loop2", "ddpre", "xla"):
        if f"flash_{impl}_fwdbwd_ms" in banked:
            continue
        ra.FLASH_BWD_IMPL = impl

        def loss(q, k, v, bias):
            return (flash_attention(q, k, v, bias, block=256, causal=True)
                    .astype(jnp.float32) * ct.astype(jnp.float32)).sum()

        try:
            fn = jax.jit(jax.grad(loss, argnums=(0, 1, 2, 3)))
            dt = timed(fn, q, k, v, bias)
            print(f"RESULT flash_{impl}_fwdbwd_ms={dt * 1e3:.2f} "
                  f"tflops={total_flops / dt / 1e12:.2f}", flush=True)
        except Exception as exc:  # noqa: BLE001
            print(f"RESULT flash_{impl}_timing=ERROR {type(exc).__name__}",
                  flush=True)
            probe_common.record_error(f"flash_{impl}_timing")
        _pet()
    ra.FLASH_BWD_IMPL = "xla"

    print("RESULT probe_flash_r5=complete", flush=True)


if __name__ == "__main__":
    main()
    sys.exit(probe_common.exit_code())
