#!/bin/bash
# Round-3 second watcher: capture the flash-backward NaN bisection at the
# next tunnel window (probe_flash_debug + probe_flash_debug2). Same stage
# discipline as tunnel_watch.sh.
cd /root/repo
MAX_HOURS=${MAX_HOURS:-10}
max_iters=$(( MAX_HOURS * 20 ))
iters=0

stage() {  # stage <artifact> <timeout_s> <cmd...>
  local artifact="$1" tmo="$2"; shift 2
  [ -f "$artifact.done" ] && return 0
  timeout "$tmo" "$@" > "$artifact.tmp" 2> "$artifact.stderr"
  local rc=$?
  echo "stage $artifact rc=$rc at $(date -u +%H:%M:%S)" >> tunnel_watch2.log
  if [ "$rc" -eq 0 ]; then
    mv "$artifact.tmp" "$artifact"
    touch "$artifact.done"
    return 0
  fi
  cat "$artifact.tmp" >> "$artifact" 2>/dev/null
  rm -f "$artifact.tmp"
  return 1
}

while :; do
  if [ -f probe_flash_stage1.txt.done ] && [ -f probe_flash_fix.txt.done ] \
     && [ -f probe_flash_xlabwd.txt.done ] \
     && [ -f bench_r3_suite2.jsonl.done ] \
     && [ -f probe_flash_debug2.txt.done ] \
     && [ -f probe_flash_debug.txt.done ]; then
    echo "all stages captured at $(date -u +%H:%M:%S)" >> tunnel_watch2.log
    exit 0
  fi
  iters=$(( iters + 1 ))
  if [ "$iters" -gt "$max_iters" ]; then
    echo "tunnel_watch2: iteration budget reached" >> tunnel_watch2.log
    exit 1
  fi
  if timeout 90 python -c "
import jax, jax.numpy as jnp
float((jnp.ones((8,8)) @ jnp.ones((8,8))).sum())
" >/dev/null 2>&1; then
    echo "=== tunnel alive at $(date -u +%H:%M:%S) ===" >> tunnel_watch2.log
    { stage probe_flash_stage1.txt 600 python -u probe_flash_stage1.py \
        && stage probe_flash_xlabwd.txt 900 python -u probe_flash_xlabwd.py \
        && stage bench_r3_suite2.jsonl 2400 \
             env KFT_BENCH_DEADLINE_S=2300 python bench.py --suite \
        && stage probe_flash_debug2.txt 900 python -u probe_flash_debug2.py \
        && stage probe_flash_fix.txt 1200 python -u probe_flash_fix.py \
        && stage probe_flash_debug.txt 900 python -u probe_flash_debug.py; } \
      || sleep 180
  else
    sleep 180
  fi
done
