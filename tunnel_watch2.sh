#!/bin/bash
# Round-4 tunnel watcher: at the next live TPU window capture, in order,
#   1. probe_flash_r4.txt   — consolidated flash-backward verdict (loop2
#                             fix, term bisect host/dev-fed, xla numerics,
#                             timing) — short and decisive, runs first;
#   2. bench_r4_suite.jsonl — full fixed-protocol bench suite (fresh
#                             baseline capture for everything shipped
#                             after the r3-fixed window);
#   3. probe_resnet.txt     — conv-ceiling / ResNet MFU probe (VERDICT #5),
#                             skipped until probe_resnet.py exists;
#   4. probe_flash_xlabwd.txt — xla-backward timing/numerics detail.
# Same stage discipline as r3: .done marks success; partial output is
# appended on failure and the stage retries at the next window.
cd /root/repo
MAX_HOURS=${MAX_HOURS:-12}
max_iters=$(( MAX_HOURS * 20 ))
iters=0

stage() {  # stage <artifact> <timeout_s> <cmd...>
  local artifact="$1" tmo="$2"; shift 2
  [ -f "$artifact.done" ] && return 0
  timeout "$tmo" "$@" > "$artifact.tmp" 2> "$artifact.stderr"
  local rc=$?
  echo "stage $artifact rc=$rc at $(date -u +%H:%M:%S)" >> tunnel_watch2.log
  if [ "$rc" -eq 0 ]; then
    mv "$artifact.tmp" "$artifact"
    touch "$artifact.done"
    return 0
  fi
  cat "$artifact.tmp" >> "$artifact" 2>/dev/null
  rm -f "$artifact.tmp"
  return 1
}

while :; do
  if [ -f probe_flash_r4.txt.done ] && [ -f bench_r4_suite.jsonl.done ] \
     && { [ ! -f probe_resnet.py ] || [ -f probe_resnet.txt.done ]; } \
     && [ -f probe_flash_xlabwd.txt.done ]; then
    echo "all stages captured at $(date -u +%H:%M:%S)" >> tunnel_watch2.log
    exit 0
  fi
  iters=$(( iters + 1 ))
  if [ "$iters" -gt "$max_iters" ]; then
    echo "tunnel_watch2: iteration budget reached" >> tunnel_watch2.log
    exit 1
  fi
  if timeout 90 python -c "
import jax, jax.numpy as jnp
float((jnp.ones((8,8)) @ jnp.ones((8,8))).sum())
" >/dev/null 2>&1; then
    echo "=== tunnel alive at $(date -u +%H:%M:%S) ===" >> tunnel_watch2.log
    { stage probe_flash_r4.txt 1500 python -u probe_flash_r4.py \
        && { # flip the training benches onto the pallas backward iff the
             # probe recorded it Mosaic-PASS and >= as fast as the xla one
             BWD=xla
             if grep -q "loop2_causal=PASS" probe_flash_r4.txt 2>/dev/null \
                && grep -q "loop2_full=PASS" probe_flash_r4.txt; then
               L2=$(grep -o "flash_loop2_fwdbwd_ms=[0-9.]*" probe_flash_r4.txt | tail -1 | cut -d= -f2)
               XL=$(grep -o "flash_xla_fwdbwd_ms=[0-9.]*" probe_flash_r4.txt | tail -1 | cut -d= -f2)
               if [ -n "$L2" ] && [ -n "$XL" ] \
                  && awk "BEGIN{exit !($L2 <= $XL)}"; then BWD=loop2; fi
             fi
             echo "bench KFT_FLASH_BWD_IMPL=$BWD" >> tunnel_watch2.log
             # 10-bench suite: ~30-40 min through the tunnel
             stage bench_r4_suite.jsonl 3600 \
               env KFT_BENCH_DEADLINE_S=3500 KFT_FLASH_BWD_IMPL=$BWD \
               python bench.py --suite; } \
        && { [ ! -f probe_resnet.py ] \
             || stage probe_resnet.txt 1200 python -u probe_resnet.py; } \
        && stage probe_flash_xlabwd.txt 900 python -u probe_flash_xlabwd.py; } \
      || sleep 180
  else
    sleep 180
  fi
done
