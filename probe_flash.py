"""Mosaic validation of the pallas flash kernels on real TPU hardware.

VERDICT r2 weak #3 / next #3: the flash fwd+bwd kernels had only been
validated in CPU interpret mode. This probe, run by tunnel_watch.sh at the
next live window, produces the hardware pass/fail record:

  1. correctness: flash fwd+bwd vs blockwise_attention (the XLA online-
     softmax reference) at production shapes, causal and non-causal, bf16;
  2. the VMEM block-size sweep (block in 128/256/512) timing fwd+bwd at
     GPT-2-small 2k-context shapes, vs the XLA blockwise fallback.

Timing protocol per docs/perf.md: device-born args, warmup dispatches, and a
final device->host read as the only true sync (block_until_ready returns
early through the axon tunnel). Mosaic COMPILE failures are recorded as
FAIL lines and the probe still exits 0 (the verdict was captured; a retry
would not change it). Tunnel hangs exit nonzero via the watchdog so
tunnel_watch retries at a later window.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback

WATCHDOG_S = 300.0
_last = [time.monotonic()]


def _pet():
    _last[0] = time.monotonic()


def _watchdog():
    while True:
        time.sleep(5.0)
        if time.monotonic() - _last[0] > WATCHDOG_S:
            print(f"RESULT watchdog=hang idle_s={WATCHDOG_S}", flush=True)
            os._exit(3)


threading.Thread(target=_watchdog, daemon=True).start()


def main() -> None:
    import jax

    if os.environ.get("KFT_BENCH_PLATFORM"):
        # debugging escape hatch (the axon sitecustomize force-registers the
        # TPU plugin; a config update wins over JAX_PLATFORMS env)
        jax.config.update("jax_platforms", os.environ["KFT_BENCH_PLATFORM"])
    import jax.numpy as jnp

    from kubeflow_tpu.parallel.ring_attention import (
        blockwise_attention,
        flash_attention,
    )

    dev = jax.devices()[0]
    print(f"RESULT device_kind={dev.device_kind!r} platform={dev.platform}",
          flush=True)
    # tiny op proves the tunnel moves data before we queue compiles
    float((jnp.ones((8, 8)) @ jnp.ones((8, 8))).sum())
    _pet()

    def born(*shape, key, dtype=jnp.bfloat16):
        # device-born: output of an on-device op, so later dispatches don't
        # re-upload host buffers every call (axon quirk, docs/perf.md)
        x = jax.random.normal(jax.random.PRNGKey(key), shape, jnp.float32)
        return jax.jit(lambda v: (v * 0.125).astype(dtype))(x)

    # ---- 1. correctness at production shape (GPT-2s heads) ----------------
    b, l, h, d = 2, 1024, 12, 64
    q = born(b, l, h, d, key=0)
    k = born(b, l, h, d, key=1)
    v = born(b, l, h, d, key=2)
    bias = jnp.zeros((b, 1, 1, l), jnp.bfloat16)
    ct = born(b, l, h, d, key=3)

    for causal in (False, True):
        tag = "causal" if causal else "full"

        def loss_flash(q, k, v, bias):
            return (flash_attention(q, k, v, bias, block=256,
                                    causal=causal).astype(jnp.float32)
                    * ct.astype(jnp.float32)).sum()

        def loss_ref(q, k, v, bias):
            return (blockwise_attention(q, k, v, bias, block=256,
                                        causal=causal).astype(jnp.float32)
                    * ct.astype(jnp.float32)).sum()

        try:
            gf = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2, 3)))
            gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2, 3)))
            of = jax.jit(lambda *a: flash_attention(*a, block=256,
                                                    causal=causal))
            orf = jax.jit(lambda *a: blockwise_attention(*a, block=256,
                                                         causal=causal))
            out_err = float(jnp.max(jnp.abs(
                of(q, k, v, bias).astype(jnp.float32)
                - orf(q, k, v, bias).astype(jnp.float32))))
            _pet()
            errs = []
            for a, b_ in zip(gf(q, k, v, bias), gr(q, k, v, bias)):
                errs.append(float(jnp.max(jnp.abs(
                    a.astype(jnp.float32) - b_.astype(jnp.float32)))))
            _pet()
            # bf16 tolerances: one ulp at these magnitudes is ~0.03; grads
            # accumulate over 1024 keys in f32 then round once
            ok = out_err < 0.05 and max(errs[:3]) < 0.25 and errs[3] < 2.0
            print(f"RESULT mosaic_correctness_{tag}="
                  f"{'PASS' if ok else 'FAIL'} out_err={out_err:.4g} "
                  f"dq_err={errs[0]:.4g} dk_err={errs[1]:.4g} "
                  f"dv_err={errs[2]:.4g} dbias_err={errs[3]:.4g}", flush=True)
        except Exception as exc:  # noqa: BLE001 — record the Mosaic verdict
            print(f"RESULT mosaic_correctness_{tag}=FAIL "
                  f"error={type(exc).__name__}", flush=True)
            traceback.print_exc(file=sys.stderr)

    # ---- 2. block-size sweep at GPT-2s 2k shapes --------------------------
    b, l, h, d = 4, 2048, 12, 64
    q = born(b, l, h, d, key=10)
    k = born(b, l, h, d, key=11)
    v = born(b, l, h, d, key=12)
    bias = jnp.zeros((b, 1, 1, l), jnp.bfloat16)
    ct = born(b, l, h, d, key=13)
    # causal attention: QK^T + PV are 2·b·h·l²·d each, halved by the mask;
    # backward recomputes scores and forms dq/dk/dv/ds ≈ 2.5x forward
    fwd_flops = 2 * 2 * b * h * l * l * d * 0.5
    total_flops = fwd_flops * 3.5

    def timed(fn, *args, iters=8):
        val = fn(*args)
        val = fn(*args)
        jax.tree_util.tree_map(
            lambda x: float(x.astype(jnp.float32).sum()), val)
        _pet()
        t0 = time.perf_counter()
        for _ in range(iters):
            val = fn(*args)
        jax.tree_util.tree_map(
            lambda x: float(x.astype(jnp.float32).sum()), val)
        return (time.perf_counter() - t0) / iters

    def fwd_bwd(attn, **kw):
        def loss(q, k, v, bias):
            return (attn(q, k, v, bias, **kw).astype(jnp.float32)
                    * ct.astype(jnp.float32)).sum()

        return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    for block in (128, 256, 512):
        try:
            dt = timed(fwd_bwd(flash_attention, block=block, causal=True),
                       q, k, v, bias)
            print(f"RESULT flash_block{block}_ms={dt * 1e3:.2f} "
                  f"tflops={total_flops / dt / 1e12:.2f}", flush=True)
        except Exception as exc:  # noqa: BLE001
            print(f"RESULT flash_block{block}_ms=FAIL "
                  f"error={type(exc).__name__}", flush=True)
            traceback.print_exc(file=sys.stderr)
        _pet()
    try:
        dt = timed(fwd_bwd(blockwise_attention, block=256, causal=True),
                   q, k, v, bias)
        print(f"RESULT xla_blockwise_ms={dt * 1e3:.2f} "
              f"tflops={total_flops / dt / 1e12:.2f}", flush=True)
    except Exception as exc:  # noqa: BLE001
        print(f"RESULT xla_blockwise_ms=FAIL error={type(exc).__name__}",
              flush=True)
    print("RESULT probe_flash=complete", flush=True)


if __name__ == "__main__":
    main()
