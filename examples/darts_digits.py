"""DARTS one-shot architecture search on the digits task.

Reference parity: katib's DARTS suggestion service runs the whole
differentiable search inside ONE trial container and reports the derived
architecture + its accuracy (SURVEY.md §2.4 NAS row). This is that trial
workload: supernet search -> derive -> retrain -> katib-format metrics on
stdout (`accuracy=... architecture=...`), so an Experiment's metrics
collector picks both up.

  python -m examples.darts_digits --device=cpu --search-steps=300
"""

from __future__ import annotations

import argparse


def main(argv: list[str] | None = None) -> float:
    p = argparse.ArgumentParser()
    p.add_argument("--device", default="auto", choices=["tpu", "cpu", "auto"])
    p.add_argument("--search-steps", type=int, default=400)
    p.add_argument("--retrain-steps", type=int, default=400)
    p.add_argument("--num-cells", type=int, default=3)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    from kubeflow_tpu.utils import select_device

    select_device(args.device)

    from kubeflow_tpu.train.data import load_digits_dataset
    from kubeflow_tpu.train.oneshot import (
        OneShotConfig,
        darts_search,
        train_arch,
    )

    ds = load_digits_dataset(seed=args.seed)
    cfg = OneShotConfig(
        num_cells=args.num_cells, hidden=args.hidden,
        search_steps=args.search_steps, seed=args.seed,
    )
    result = darts_search(ds.x_train, ds.y_train, ds.x_test, ds.y_test, cfg)
    acc = train_arch(result.arch, ds.x_train, ds.y_train,
                     ds.x_test, ds.y_test, cfg,
                     steps=args.retrain_steps, seed=args.seed)
    print(f"architecture={'-'.join(result.arch)}")
    print(f"accuracy={acc:.4f}")
    return acc


if __name__ == "__main__":
    import sys

    sys.exit(0 if main() > 0.9 else 1)
