"""North-star config #2: ResNet distributed data-parallel training.

Reference parity: the reference runs torchvision ResNet-50 DDP under a
PyTorchJob (SURVEY.md §2.2 data-parallel row); here the in-tree flax ResNet
trains under the same Trainer on any mesh. Offline environment => synthetic
ImageNet-shaped data for throughput, digits for a real-accuracy smoke run.

  python -m examples.resnet --device=tpu --variant=50 --steps=100
  python -m examples.resnet --device=cpu --variant=18 --small --steps=20
"""

from __future__ import annotations

import argparse


def main(argv: list[str] | None = None) -> float:
    p = argparse.ArgumentParser()
    p.add_argument("--device", default="auto", choices=["tpu", "cpu", "auto"])
    p.add_argument("--variant", default="50", choices=["18", "34", "50", "101", "152"])
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--fused-steps", type=int, default=1,
                   help="optimizer steps per jit dispatch (lax.scan chunks)")
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--small", action="store_true", help="3x3 stem for small images")
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--bf16", action="store_true", default=True)
    p.add_argument("--no-bf16", dest="bf16", action="store_false")
    p.add_argument("--data-parallel", type=int, default=-1)
    p.add_argument("--fsdp", type=int, default=1)
    p.add_argument("--checkpoint-dir", default=None)
    args = p.parse_args(argv)

    from kubeflow_tpu.utils import select_device

    select_device(args.device)

    import jax.numpy as jnp

    import kubeflow_tpu.models as models
    from kubeflow_tpu.parallel import MeshConfig
    from kubeflow_tpu.train import Trainer, TrainerConfig
    from kubeflow_tpu.train.data import synthetic_image_dataset

    dtype = jnp.bfloat16 if args.bf16 else jnp.float32
    model = getattr(models, f"ResNet{args.variant}")(
        num_classes=args.num_classes, dtype=dtype, small_inputs=args.small
    )
    size = args.image_size if not args.small else 32
    dataset = synthetic_image_dataset(
        n_train=args.batch_size * 8,
        n_test=args.batch_size * 2,
        shape=(size, size, 3),
        num_classes=args.num_classes,
    )
    trainer = Trainer(
        model,
        TrainerConfig(
            fused_steps=args.fused_steps,
            batch_size=args.batch_size,
            steps=args.steps,
            learning_rate=args.lr,
            compute_dtype=dtype,
            checkpoint_dir=args.checkpoint_dir,
            mesh=MeshConfig(data=args.data_parallel, fsdp=args.fsdp),
            log_every_steps=10,
        ),
    )
    _, metrics = trainer.fit(dataset)
    return metrics.get("final_accuracy", 0.0)


if __name__ == "__main__":
    main()
