"""One sweep trial: mnist training at the assigned hyperparameters.

Run by examples.sweep_mnist's trial template; prints the `name=value`
metrics the collector parses (the trainer emits them natively).
"""

from __future__ import annotations

import argparse


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--device", default="cpu", choices=["tpu", "cpu"])
    p.add_argument("--steps", type=int, default=120)
    p.add_argument("--lr", type=float, required=True)
    p.add_argument("--batch-size", type=int, required=True)
    args = p.parse_args(argv)

    from kubeflow_tpu.utils import select_device

    select_device(args.device)

    from kubeflow_tpu.models import MnistMLP
    from kubeflow_tpu.train import Trainer, TrainerConfig
    from kubeflow_tpu.train.data import load_digits_dataset

    trainer = Trainer(
        MnistMLP(),
        TrainerConfig(
            batch_size=args.batch_size, steps=args.steps,
            learning_rate=args.lr, log_every_steps=50,
        ),
    )
    trainer.fit(load_digits_dataset())


if __name__ == "__main__":
    main()
