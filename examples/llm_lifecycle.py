"""The full LLM lifecycle on one platform, exit-code asserted:

  tokenize corpus -> pretrain tiny GPT -> LoRA fine-tune on a downstream
  task -> quantized + AOT serving artifact -> serve -> generate text.

Every stage uses the in-tree machinery (train/tokenizer.py BPE,
Trainer + causal_lm_loss, train/lora.py adapters, serving/quant.py int8,
serving/aot.py export, serving server + KV-cache decode), so this doubles
as the integration gate for the round-3 LLM surface.

  JAX_PLATFORMS=cpu python -m examples.llm_lifecycle     # ~2-4 min on CPU
"""

from __future__ import annotations

import json
import sys
import tempfile
import time
from pathlib import Path

CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    "the lazy dog sleeps while the quick fox runs",
    "a quick brown dog jumps over a lazy fox",
    "the brown fox and the lazy dog run over the hill",
] * 8


def main() -> int:
    from kubeflow_tpu.utils import select_device

    select_device("cpu" if "--device=tpu" not in sys.argv else "tpu")

    import jax
    import numpy as np

    t0 = time.time()
    work = Path(tempfile.mkdtemp(prefix="kftpu-llm-"))

    def ok(step, detail=""):
        print(f"[{time.time() - t0:6.1f}s] {step}: OK"
              + (f" ({detail})" if detail else ""), flush=True)

    # ---- 1. tokenize
    from kubeflow_tpu.train.tokenizer import Tokenizer

    tok = Tokenizer.train(CORPUS, vocab_size=160)
    tok.save(work / "tokenizer.json")
    seq_len = 32
    x = tok.encode_batch(CORPUS, seq_len)
    assert tok.decode(tok.encode(CORPUS[0])) == CORPUS[0]
    ok("1 tokenize", f"vocab={tok.vocab_size}")

    # ---- 2. pretrain tiny GPT (causal LM)
    from kubeflow_tpu.models import causal_lm_eval_metrics, causal_lm_loss
    from kubeflow_tpu.models.gpt import GPTConfig, GPTLM, generate
    from kubeflow_tpu.train import Trainer, TrainerConfig
    from kubeflow_tpu.train.data import Dataset

    cfg = GPTConfig.tiny(vocab_size=max(tok.vocab_size, 8), max_len=64,
                         dropout_rate=0.0)
    model = GPTLM(cfg)
    ds = Dataset(x, x, x[:8], x[:8], num_classes=tok.vocab_size)
    trainer = Trainer(
        model,
        TrainerConfig(batch_size=8, steps=60, learning_rate=3e-3,
                      log_every_steps=10**9),
        loss_fn=causal_lm_loss,
        eval_metrics_fn=causal_lm_eval_metrics,
    )
    state, metrics = trainer.fit(ds)
    assert metrics["final_loss"] < 3.0, metrics
    pretrained = jax.tree.map(np.asarray, state.params)
    ok("2 pretrain", f"loss={metrics['final_loss']:.3f}")

    # ---- 3. LoRA fine-tune (adapters only; base provably frozen)
    from kubeflow_tpu.train import LoraModel, lora_tx

    lora = LoraModel(model, rank=4)
    ft = Trainer(
        lora,
        TrainerConfig(batch_size=8, steps=5, learning_rate=5e-3,
                      log_every_steps=10**9),
        loss_fn=causal_lm_loss,
        eval_metrics_fn=causal_lm_eval_metrics,
        tx=lora_tx,
    )
    fstate = ft.init_state(ds.x_train[:8])
    fstate = fstate.replace(
        params={**fstate.params, "base": pretrained}
    )
    before = jax.tree.leaves(jax.tree.map(np.asarray,
                                          fstate.params["base"]))
    for _ in range(ft.config.steps):
        fstate, fm = ft.train_step(fstate, (ds.x_train[:8], ds.y_train[:8]))
    for a, b in zip(before, jax.tree.leaves(fstate.params["base"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    from kubeflow_tpu.train.lora import lora_merge

    merged = lora_merge(
        jax.tree.map(np.asarray, fstate.params["base"]),
        jax.tree.map(np.asarray, fstate.params["lora"]), lora.alpha,
    )
    ok("3 lora fine-tune", f"loss={float(fm['loss']):.3f}, base frozen")

    # ---- 4. quantized + AOT serving artifact
    from kubeflow_tpu.serving.aot import export_predictor
    from kubeflow_tpu.serving.model import save_predictor

    prompt = np.asarray([tok.encode("the quick", eos=False)], np.int32)
    d = save_predictor(
        work / "model", "gpt-lm", {"params": merged}, prompt,
        generate={"max_new_tokens": 10}, quantize=True, size="tiny",
        config={"dropout_rate": 0.0, "max_len": cfg.max_len,
                "vocab_size": cfg.vocab_size, "hidden_size": cfg.hidden_size,
                "num_layers": cfg.num_layers, "num_heads": cfg.num_heads,
                "mlp_dim": cfg.mlp_dim},
    )
    export_predictor(d)
    assert (d / "predictor.jaxexport").exists()
    ok("4 artifact", "int8 + AOT decode loop")

    # ---- 5. serve + generate
    from kubeflow_tpu.serving.model import JaxModel

    jm = JaxModel("llm", d)
    jm.load()
    out = jm(prompt)
    text = tok.decode(np.asarray(out["predictions"])[0])
    assert any(w in text for w in
               ("dog", "fox", "lazy", "quick", "brown", "the", "run")), text
    ok("5 serve+generate", f"text={text!r}")

    print(json.dumps({"llm_lifecycle": "complete",
                      "seconds": round(time.time() - t0, 1),
                      "generated": text}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
