"""North-star config #4: hyperparameter sweep launching trial jobs.

Reference parity: a Katib Experiment tuning the mnist example
(SURVEY.md §3.3), rebuilt on the in-process platform — trials are real
JAXJob subprocesses running examples.mnist, metrics are collected from the
`name=value` stdout contract, and TPE proposes the next points.

  python -m examples.sweep_mnist --device=cpu --max-trials=6
"""

from __future__ import annotations

import argparse
import json
import sys
import textwrap


def main(argv: list[str] | None = None) -> dict:
    p = argparse.ArgumentParser()
    p.add_argument("--device", default="cpu", choices=["tpu", "cpu"])
    p.add_argument("--max-trials", type=int, default=6)
    p.add_argument("--parallel", type=int, default=2)
    p.add_argument("--steps", type=int, default=120)
    p.add_argument("--algorithm", default="tpe",
                   choices=["random", "grid", "tpe", "cmaes"])
    args = p.parse_args(argv)

    from kubeflow_tpu.client import Platform
    from kubeflow_tpu.sweep import (
        AlgorithmSpec,
        Experiment,
        ExperimentSpec,
        FeasibleSpace,
        Objective,
        ObjectiveType,
        ParameterSpec,
        ParameterType,
        SweepClient,
        TrialParameterSpec,
        TrialTemplate,
    )
    from kubeflow_tpu.api.common import ObjectMeta

    trial_spec = textwrap.dedent(f"""
        apiVersion: kubeflow-tpu.org/v1
        kind: JAXJob
        spec:
          replicaSpecs:
            worker:
              replicas: 1
              template:
                container:
                  command:
                    - {sys.executable}
                    - -m
                    - examples.sweep_mnist_trial
                    - --device={args.device}
                    - --steps={args.steps}
                    - --lr=${{trialParameters.lr}}
                    - --batch-size=${{trialParameters.batchSize}}
        """)
    exp = Experiment(
        metadata=ObjectMeta(name="mnist-sweep"),
        spec=ExperimentSpec(
            parameters=[
                ParameterSpec(
                    name="lr", parameter_type=ParameterType.DOUBLE,
                    feasible_space=FeasibleSpace(min="0.0003", max="0.03"),
                ),
                ParameterSpec(
                    name="batchSize", parameter_type=ParameterType.CATEGORICAL,
                    feasible_space=FeasibleSpace(list=["64", "128", "256"]),
                ),
            ],
            objective=Objective(
                type=ObjectiveType.MAXIMIZE,
                objective_metric_name="final_accuracy",
            ),
            algorithm=AlgorithmSpec(algorithm_name=args.algorithm),
            trial_template=TrialTemplate(
                trial_spec=trial_spec,
                trial_parameters=[
                    TrialParameterSpec(name="lr", reference="lr"),
                    TrialParameterSpec(name="batchSize", reference="batchSize"),
                ],
            ),
            max_trial_count=args.max_trials,
            parallel_trial_count=args.parallel,
        ),
    )
    with Platform() as platform:
        sweep = SweepClient(platform)
        sweep.create_experiment(exp)
        done = sweep.wait_for_experiment("mnist-sweep", timeout_s=3600)
        best = done.status.current_optimal_trial
        result = {
            "condition": done.status.condition.value,
            "trials": done.status.trials,
            "best_params": sweep.get_optimal_hyperparameters("mnist-sweep"),
            "best_accuracy": (
                best.observation.metric("final_accuracy").latest if best else None
            ),
        }
        print(json.dumps(result, indent=2))
        return result


if __name__ == "__main__":
    main()
