"""Pipelines demo: prep -> train (JAXJob) -> report.

Reference parity: a KFP pipeline whose middle step launches a training job
CR (SURVEY.md §3.4 recursing into §3.1), rebuilt on the local runner and
the in-process platform.

  python -m examples.pipeline_mnist --device=cpu --steps=150
"""

from __future__ import annotations

import argparse
import json
import sys
import textwrap

from kubeflow_tpu.pipelines import component, pipeline, train_job


@component
def choose_lr(base: float, scale: float) -> float:
    return base * scale


@component
def report(job: dict, lr: float) -> str:
    status = "succeeded" if job["succeeded"] else "FAILED"
    return f"training {status} (job={job['jobName']}, lr={lr}, restarts={job['restartCount']})"


def build_pipeline(device: str, steps: int):
    manifest = textwrap.dedent(f"""
        apiVersion: kubeflow-tpu.org/v1
        kind: JAXJob
        metadata: {{name: pipeline-mnist}}
        spec:
          replicaSpecs:
            worker:
              replicas: 1
              template:
                container:
                  command:
                    - {sys.executable}
                    - -m
                    - examples.sweep_mnist_trial
                    - --device={device}
                    - --steps={steps}
                    - --lr=${{lr}}
                    - --batch-size=128
        """)

    @pipeline(name="mnist-train-pipe", description="prep -> train -> report")
    def mnist_pipe(base_lr: float = 1e-3, scale: float = 2.0):
        lr = choose_lr(base=base_lr, scale=scale)
        job = train_job("launch-training", manifest)(lr=lr)
        return report(job=job, lr=lr)

    return mnist_pipe


def main(argv: list[str] | None = None) -> dict:
    p = argparse.ArgumentParser()
    p.add_argument("--device", default="cpu", choices=["tpu", "cpu"])
    p.add_argument("--steps", type=int, default=150)
    p.add_argument("--work-dir", default=".kubeflow_tpu/pipeline-mnist")
    args = p.parse_args(argv)

    from kubeflow_tpu.client import Platform
    from kubeflow_tpu.native import MetadataStore
    from kubeflow_tpu.pipelines import LocalPipelineRunner, compile_pipeline

    ir = compile_pipeline(build_pipeline(args.device, args.steps)())
    ms = MetadataStore(f"{args.work_dir}/mlmd.db")
    with Platform() as platform:
        runner = LocalPipelineRunner(
            work_dir=args.work_dir, metadata_store=ms, platform=platform
        )
        run = runner.run(ir)
        result = {
            "run_id": run.run_id,
            "state": run.state.value,
            "tasks": {t: r.state.value for t, r in run.tasks.items()},
            "report": run.output,
            "lineage_executions": len(ms.list_executions("pipeline_task")),
        }
        print(json.dumps(result, indent=2))
    ms.close()
    return result


if __name__ == "__main__":
    main()
