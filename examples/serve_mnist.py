"""North-star config #5: serve a trained predictor.

Reference parity: train-then-serve through the platform (SURVEY.md §3.5) —
train mnist briefly, save the jax-runtime model dir, stand up an
InferenceService, and query it over the v1 and v2 protocols.

  python -m examples.serve_mnist --device=cpu --steps=200
"""

from __future__ import annotations

import argparse
import json


def main(argv: list[str] | None = None) -> dict:
    p = argparse.ArgumentParser()
    p.add_argument("--device", default="cpu", choices=["tpu", "cpu"])
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--model-dir", default=".kubeflow_tpu/serve-mnist-model")
    args = p.parse_args(argv)

    from kubeflow_tpu.utils import select_device

    select_device(args.device)

    import numpy as np

    from kubeflow_tpu.api.common import ObjectMeta
    from kubeflow_tpu.client import Platform
    from kubeflow_tpu.models import MnistMLP
    from kubeflow_tpu.serving import (
        InferenceService,
        InferenceServiceSpec,
        PredictorRuntime,
        PredictorSpec,
        ServingClient,
        save_predictor,
    )
    from kubeflow_tpu.train import Trainer, TrainerConfig
    from kubeflow_tpu.train.data import load_digits_dataset

    # ---- train + export (the storage-initializer source)
    ds = load_digits_dataset()
    trainer = Trainer(
        MnistMLP(), TrainerConfig(batch_size=128, steps=args.steps)
    )
    state, metrics = trainer.fit(ds)
    variables = {"params": state.params, **state.extra}
    save_predictor(
        args.model_dir, "mnist-mlp",
        {k: __import__("jax").device_get(v) for k, v in variables.items()},
        np.zeros((1, ds.x_train.shape[-1]), np.float32),
    )

    # ---- serve + query
    with Platform() as platform:
        serving = ServingClient(platform)
        serving.create(
            InferenceService(
                metadata=ObjectMeta(name="mnist"),
                spec=InferenceServiceSpec(
                    predictor=PredictorSpec(
                        runtime=PredictorRuntime.JAX,
                        storage_uri=f"file://{args.model_dir}",
                        device=args.device,
                    )
                ),
            )
        )
        isvc = serving.wait_ready("mnist", timeout_s=300)
        x = ds.x_test[:4].astype("float32")
        v1 = serving.predict("mnist", x.tolist())
        v2 = serving.infer("mnist", x.ravel().tolist(), shape=list(x.shape))
        result = {
            "url": isvc.status.url,
            "train_accuracy": metrics["final_accuracy"],
            "v1_predictions": v1["predictions"],
            "true_labels": ds.y_test[:4].tolist(),
            "v2_output_shape": v2["outputs"][0]["shape"],
        }
        print(json.dumps(result, indent=2))
        return result


if __name__ == "__main__":
    main()
