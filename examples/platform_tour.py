"""The north-star tour: all five BASELINE.md functional configs through ONE
platform instance (BASELINE.json configs #1-#5, zero GPU anywhere):

  1. single-replica MNIST JAXJob
  2. data-parallel training job (multi-replica gang)
  3. BERT gang fine-tune (2-process jax.distributed rendezvous)
  4. hyperparameter sweep launching trial jobs
  5. InferenceService predictor answering v1/v2

  JAX_PLATFORMS=cpu python -m examples.platform_tour   # ~2-10 min on CPU
"""

from __future__ import annotations

import json
import sys
import tempfile
import textwrap
import time
from pathlib import Path


def _job(name, script_path, replicas=1, env=None):
    from kubeflow_tpu.api import (
        ContainerSpec,
        JAXJob,
        JAXJobSpec,
        ObjectMeta,
        PodTemplateSpec,
        ReplicaSpec,
        RunPolicy,
        REPLICA_WORKER,
    )

    return JAXJob(
        metadata=ObjectMeta(name=name),
        spec=JAXJobSpec(
            replica_specs={
                REPLICA_WORKER: ReplicaSpec(
                    replicas=replicas,
                    template=PodTemplateSpec(container=ContainerSpec(
                        command=[sys.executable, str(script_path)],
                        env=env or {},
                    )),
                )
            },
            run_policy=RunPolicy(backoff_limit=1),
        ),
    )


def main() -> int:
    import kubeflow_tpu
    from kubeflow_tpu.utils import select_device

    # the tour's own jax use (predictor artifact init) runs on CPU; pods
    # pick their device from their own flags/env
    select_device("cpu")
    from kubeflow_tpu.client import Platform, TrainingClient

    repo = str(Path(kubeflow_tpu.__file__).resolve().parent.parent)
    work = Path(tempfile.mkdtemp(prefix="kftpu-tour-"))
    t0 = time.time()
    results: dict[str, str] = {}

    def ok(step: str, detail: str = ""):
        results[step] = "OK" + (f" ({detail})" if detail else "")
        print(f"[{time.time() - t0:6.1f}s] {step}: {results[step]}", flush=True)

    with Platform(log_dir=str(work / "pod-logs"), capacity_chips=16) as platform:
        client = TrainingClient(platform)

        # ---- 1. single-replica MNIST (north-star #1)
        mnist = work / "mnist.py"
        mnist.write_text(textwrap.dedent(f"""
            import sys; sys.path.insert(0, {repo!r})
            from examples.mnist import main
            acc = main(["--device=cpu", "--steps", "80"])
            # BASELINE.md config #1 criterion on the digits stand-in
            assert acc > 0.9, acc
        """))
        client.create_job(_job("tour-mnist", mnist))
        done = client.wait_for_job_conditions("tour-mnist", timeout_s=300)
        assert done.status.is_succeeded, done.status.conditions
        ok("1 mnist single-replica")

        # ---- 2+3. BERT data-parallel gang: 2 real processes rendezvous via
        # jax.distributed and run SPMD train steps (north-star #2/#3 shape)
        bert = work / "bert_gang.py"
        bert.write_text(textwrap.dedent(f"""
            import sys; sys.path.insert(0, {repo!r})
            from kubeflow_tpu.runtime.distributed import initialize_from_env
            ctx = initialize_from_env(platform="cpu", local_device_count=1)
            import numpy as np
            from kubeflow_tpu.models import BertConfig, BertForSequenceClassification
            from kubeflow_tpu.train import Trainer, TrainerConfig
            from kubeflow_tpu.train.data import synthetic_text_dataset
            cfg = BertConfig.tiny(dropout_rate=0.0)
            ds = synthetic_text_dataset(n_train=128, n_test=32, seq_len=32,
                                        vocab_size=cfg.vocab_size)
            tr = Trainer(BertForSequenceClassification(cfg, num_classes=2),
                         TrainerConfig(batch_size=16, steps=40,
                                       learning_rate=1e-3, log_every_steps=10))
            state, m = tr.fit(ds)
            assert np.isfinite(m["final_loss"])
            # outcome-asserted (BASELINE.md config #3 ledger): the separable
            # synthetic task must actually be learned, not just not-NaN
            assert m["final_accuracy"] > 0.75, m
            print(f"bert rank {{ctx.process_id}}/{{ctx.num_processes}} done")
        """))
        client.create_job(_job("tour-bert", bert, replicas=2,
                               env={"PYTHONPATH": repo}))
        done = client.wait_for_job_conditions("tour-bert", timeout_s=300)
        assert done.status.is_succeeded, done.status.conditions
        ok("2+3 bert 2-process gang", "jax.distributed rendezvous")

        # ---- 4. sweep (north-star #4)
        from kubeflow_tpu.sweep import SweepClient
        from kubeflow_tpu.sweep.api import ParameterSpec, ParameterType, FeasibleSpace

        sweep = SweepClient(platform, work_dir=str(work / "sweeps"))

        def objective(x: float):
            print(f"objective={-(x - 0.6) ** 2}")

        sweep.tune(
            "tour-sweep", objective,
            parameters=[ParameterSpec(
                name="x", parameter_type=ParameterType.DOUBLE,
                feasible_space=FeasibleSpace(min="0.0", max="0.9", step="0.3"),
            )],
            objective_metric="objective",
            algorithm="grid",
            max_trial_count=4,
            parallel_trial_count=3,
        )
        exp = sweep.wait_for_experiment("tour-sweep", timeout_s=300)
        assert exp.status.condition.value == "Succeeded", exp.status
        best = sweep.get_optimal_hyperparameters("tour-sweep")
        assert abs(float(best["x"]) - 0.6) < 1e-9, best  # grid point 0.6
        ok("4 sweep", f"optimal x={best['x']}")

        # ---- 5. serving (north-star #5): train-artifact -> ISVC -> predict
        import jax
        import numpy as np

        from kubeflow_tpu.models import MnistMLP
        from kubeflow_tpu.serving import ServingClient
        from kubeflow_tpu.serving.api import (
            InferenceService,
            InferenceServiceSpec,
            PredictorSpec,
            PredictorRuntime,
        )
        from kubeflow_tpu.serving.model import save_predictor
        from kubeflow_tpu.api.common import ObjectMeta

        model = MnistMLP(hidden=(16,))
        x0 = np.zeros((1, 28, 28, 1), np.float32)
        variables = model.init(jax.random.PRNGKey(0), x0)
        save_predictor(work / "model", "mnist-mlp", dict(variables), x0,
                       hidden=[16])
        serving = ServingClient(platform)
        serving.create(InferenceService(
            metadata=ObjectMeta(name="tour-svc"),
            spec=InferenceServiceSpec(predictor=PredictorSpec(
                runtime=PredictorRuntime.JAX,
                storage_uri=f"file://{work / 'model'}",
                device="cpu",
            )),
        ))
        serving.wait_ready("tour-svc", timeout_s=120)
        out = serving.predict(
            "tour-svc", np.zeros((2, 28, 28, 1), np.float32).tolist()
        )
        assert len(out["predictions"]) == 2
        ok("5 serving v1 predict")

    print(json.dumps({"tour": "complete", "results": results,
                      "seconds": round(time.time() - t0, 1)}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
