"""BERT MLM pretraining — the missing half of the BERT story.

Fine-tuning lives in examples/bert.py; this entry point runs the masked-LM
pretraining objective with tied input/output embeddings (BertForMaskedLM)
under the same Trainer/mesh machinery:

  python -m examples.bert_pretrain --device=tpu --size=base --steps=200
  python -m examples.bert_pretrain --size=tiny --fsdp=4 --model-parallel=2
"""

from __future__ import annotations

import argparse


def main(argv: list[str] | None = None) -> float:
    p = argparse.ArgumentParser()
    p.add_argument("--device", default="auto", choices=["tpu", "cpu", "auto"])
    p.add_argument("--size", default="base", choices=["tiny", "base"])
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--fused-steps", type=int, default=1,
                   help="optimizer steps per jit dispatch (lax.scan chunks)")
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--mask-prob", type=float, default=0.15)
    p.add_argument("--bf16", action="store_true", default=True)
    p.add_argument("--no-bf16", dest="bf16", action="store_false")
    p.add_argument("--data-parallel", type=int, default=-1)
    p.add_argument("--fsdp", type=int, default=1)
    p.add_argument("--model-parallel", type=int, default=1)
    p.add_argument("--checkpoint-dir", default=None)
    args = p.parse_args(argv)

    from kubeflow_tpu.utils import select_device

    select_device(args.device)

    import jax.numpy as jnp

    from kubeflow_tpu.models import BertConfig, BertForMaskedLM
    from kubeflow_tpu.models.bert import masked_lm_eval_metrics, masked_lm_loss
    from kubeflow_tpu.parallel import MeshConfig
    from kubeflow_tpu.train import Trainer, TrainerConfig
    from kubeflow_tpu.train.data import (
        Dataset,
        mask_tokens_for_mlm,
        synthetic_text_dataset,
    )

    dtype = jnp.bfloat16 if args.bf16 else jnp.float32
    mk = BertConfig.tiny if args.size == "tiny" else BertConfig.base
    cfg = mk(dtype=dtype, max_len=max(args.seq_len, 512))
    # the top id is TRULY reserved as [MASK]: data and random replacements
    # both draw from [1, vocab-1)
    mask_id = cfg.vocab_size - 1
    data_vocab = cfg.vocab_size - 1
    raw = synthetic_text_dataset(
        n_train=args.batch_size * 8,
        n_test=args.batch_size * 2,
        seq_len=args.seq_len,
        vocab_size=data_vocab,
    )
    x_tr, y_tr = mask_tokens_for_mlm(
        raw.x_train, data_vocab, mask_id, args.mask_prob
    )
    x_te, y_te = mask_tokens_for_mlm(
        raw.x_test, data_vocab, mask_id, args.mask_prob, seed=1
    )
    ds = Dataset(x_tr, y_tr, x_te, y_te, num_classes=cfg.vocab_size)

    trainer = Trainer(
        BertForMaskedLM(cfg),
        TrainerConfig(
            fused_steps=args.fused_steps,
            batch_size=args.batch_size,
            steps=args.steps,
            learning_rate=args.lr,
            warmup_steps=min(100, args.steps // 10),
            compute_dtype=dtype,
            checkpoint_dir=args.checkpoint_dir,
            mesh=MeshConfig(
                data=args.data_parallel,
                fsdp=args.fsdp,
                model=args.model_parallel,
            ),
            log_every_steps=10,
        ),
        loss_fn=masked_lm_loss,
        eval_metrics_fn=masked_lm_eval_metrics,
    )
    _, metrics = trainer.fit(ds)
    return metrics.get("final_loss", float("inf"))


if __name__ == "__main__":
    main()
