"""Train a tiny GPT on synthetic text, then generate with the KV cache —
the end-to-end LLM loop (train -> decode -> serve-ready artifact).

  python -m examples.gpt_generate --device=cpu --steps=60
  python -m examples.gpt_generate --device=tpu --temperature=0.8 --top-k=40

With --save-dir the trained model is written in the serving model-dir
contract with a generate config (+ optional --aot export), so
`python -m kubeflow_tpu.serving.server --model-dir <dir> ...` serves it.
"""

from __future__ import annotations

import argparse


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--device", default="auto", choices=["tpu", "cpu", "auto"])
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--seq-len", type=int, default=64)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--prompt-len", type=int, default=8)
    p.add_argument("--max-new-tokens", type=int, default=24)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--save-dir", default="")
    p.add_argument("--aot", action="store_true",
                   help="with --save-dir: also export the AOT decode loop")
    args = p.parse_args(argv)

    from kubeflow_tpu.utils import select_device

    select_device(args.device)

    import jax
    import numpy as np

    from kubeflow_tpu.models.gpt import GPTConfig, GPTLM, generate
    from kubeflow_tpu.models import causal_lm_loss, causal_lm_eval_metrics
    from kubeflow_tpu.train import Trainer, TrainerConfig
    from kubeflow_tpu.train.data import synthetic_lm_dataset

    cfg = GPTConfig.tiny(dropout_rate=0.0,
                         max_len=args.seq_len + args.max_new_tokens)
    ds = synthetic_lm_dataset(
        n_train=args.batch_size * 8, n_test=args.batch_size,
        seq_len=args.seq_len, vocab_size=cfg.vocab_size,
    )
    model = GPTLM(cfg)
    trainer = Trainer(
        model,
        TrainerConfig(batch_size=args.batch_size, steps=args.steps,
                      learning_rate=args.lr, log_every_steps=20),
        loss_fn=causal_lm_loss,
        eval_metrics_fn=causal_lm_eval_metrics,
    )
    state, metrics = trainer.fit(ds)

    prompt = np.asarray(ds.x_test[:4, :args.prompt_len], np.int32)
    rng = (jax.random.PRNGKey(0) if args.temperature > 0 else None)
    out = generate(model, {"params": state.params}, prompt,
                   max_new_tokens=args.max_new_tokens,
                   temperature=args.temperature, top_k=args.top_k, rng=rng)
    for i, (p_ids, g_ids) in enumerate(zip(prompt, np.asarray(out))):
        print(f"sample {i}: prompt={p_ids.tolist()} -> "
              f"generated={g_ids.tolist()}")

    if args.save_dir:
        from kubeflow_tpu.serving.model import save_predictor

        gen_cfg = {"max_new_tokens": args.max_new_tokens,
                   "temperature": args.temperature, "top_k": args.top_k}
        d = save_predictor(
            args.save_dir, "gpt-lm",
            {"params": jax.tree.map(np.asarray, state.params)},
            prompt, generate=gen_cfg, size="tiny",
            config={"dropout_rate": 0.0,
                    "max_len": cfg.max_len, "vocab_size": cfg.vocab_size,
                    "hidden_size": cfg.hidden_size,
                    "num_layers": cfg.num_layers,
                    "num_heads": cfg.num_heads, "mlp_dim": cfg.mlp_dim},
        )
        if args.aot and args.temperature > 0.0:
            raise SystemExit(
                "--aot requires greedy decode (--temperature=0): the "
                "exported artifact cannot receive a per-request sampling rng"
            )
        if args.aot:
            from kubeflow_tpu.serving.aot import export_predictor

            export_predictor(d)
            print(f"saved + AOT-exported predictor at {d}")
        else:
            print(f"saved predictor at {d}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
