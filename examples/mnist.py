"""North-star config #1: MNIST single-worker training.

Reference parity: kubeflow/examples mnist TFJob image (SURVEY.md L6),
rebuilt as the in-tree flax example. Device picked by one flag.

  python -m examples.mnist --device=cpu --epochs=8
  python -m examples.mnist --device=tpu --epochs=8
"""

from __future__ import annotations

import argparse


def main(argv: list[str] | None = None) -> float:
    p = argparse.ArgumentParser()
    p.add_argument("--device", default="auto", choices=["tpu", "cpu", "auto"])
    p.add_argument("--epochs", type=int, default=100)
    p.add_argument("--fused-steps", type=int, default=1,
                   help="optimizer steps per jit dispatch (lax.scan chunks)")
    p.add_argument("--steps", type=int, default=None)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--lr", type=float, default=2e-3)
    p.add_argument("--model", default="mlp", choices=["mlp", "cnn"])
    p.add_argument("--checkpoint-dir", default=None)
    args = p.parse_args(argv)

    from kubeflow_tpu.utils import select_device

    select_device(args.device)

    from kubeflow_tpu.models import MnistCNN, MnistMLP
    from kubeflow_tpu.train import Trainer, TrainerConfig
    from kubeflow_tpu.train.data import load_digits_dataset

    dataset = load_digits_dataset()
    model = MnistMLP() if args.model == "mlp" else MnistCNN()
    trainer = Trainer(
        model,
        TrainerConfig(
            fused_steps=args.fused_steps,
            batch_size=args.batch_size,
            epochs=args.epochs,
            steps=args.steps,
            learning_rate=args.lr,
            checkpoint_dir=args.checkpoint_dir,
        ),
    )
    _, metrics = trainer.fit(dataset)
    return metrics["final_accuracy"]


if __name__ == "__main__":
    acc = main()
    # exit code signals job verdict to the controller (ExitCode restart policy)
    raise SystemExit(0 if acc > 0.9 else 1)
