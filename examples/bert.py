"""North-star config #3: BERT gang fine-tune, long-context capable.

Reference parity: the reference runs BERT via Horovod/MPIJob user images
(SURVEY.md §3.2); here the in-tree encoder fine-tunes under the Trainer with
any mesh: dp/fsdp/tp axes plus `context` for ring/Ulysses sequence
parallelism at long sequence lengths (capability the reference platform
never had — SURVEY.md §5.7).

  python -m examples.bert --device=tpu --size=base --steps=100
  python -m examples.bert --size=tiny --seq-len=2048 --attention=ring --context=4
  python -m examples.bert --size=tiny --moe-experts=8 --expert-parallel=2
  python -m examples.bert --size=tiny --pipeline-stages=2 --data-parallel=4
"""

from __future__ import annotations

import argparse


def main(argv: list[str] | None = None) -> float:
    p = argparse.ArgumentParser()
    p.add_argument("--device", default="auto", choices=["tpu", "cpu", "auto"])
    p.add_argument("--size", default="base", choices=["tiny", "base"])
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--fused-steps", type=int, default=1,
                   help="optimizer steps per jit dispatch (lax.scan chunks)")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--num-classes", type=int, default=2)
    p.add_argument("--lr", type=float, default=5e-5)
    p.add_argument("--attention", default="dense", choices=["dense", "ring", "ulysses", "flash"])
    p.add_argument("--bf16", action="store_true", default=True)
    p.add_argument("--no-bf16", dest="bf16", action="store_false")
    p.add_argument("--data-parallel", type=int, default=-1)
    p.add_argument("--fsdp", type=int, default=1)
    p.add_argument("--model-parallel", type=int, default=1)
    p.add_argument("--context", type=int, default=1)
    # MoE: >0 swaps every MLP for a MoeMlp dispatched over `expert`
    p.add_argument("--moe-experts", type=int, default=0)
    # NAS surface (SURVEY.md §2.4 ENAS/DARTS row): architecture fields are
    # ordinary flags, so a sweep Experiment searches architecture space
    # through the same trial-template substitution as any hyperparameter
    # (samples/experiment_nas.yaml). 0 = keep the size preset's value.
    p.add_argument("--num-layers", type=int, default=0)
    p.add_argument("--num-heads", type=int, default=0)
    p.add_argument("--mlp-dim", type=int, default=0)
    # parameter-efficient fine-tune: freeze the base, train rank-r adapters
    # on the attention/MLP kernels (train/lora.py)
    p.add_argument("--lora", type=int, default=0, help="LoRA rank (0 = full)")
    p.add_argument("--remat", action="store_true",
                   help="rematerialize encoder blocks (long-context HBM lever)")
    p.add_argument("--expert-parallel", type=int, default=1)
    # PP: >1 pipelines the encoder stack over the `pipeline` axis
    p.add_argument("--pipeline-stages", type=int, default=1)
    p.add_argument("--checkpoint-dir", default=None)
    args = p.parse_args(argv)

    from kubeflow_tpu.utils import select_device

    select_device(args.device)

    import jax.numpy as jnp

    from kubeflow_tpu.models import BertConfig, BertForSequenceClassification
    from kubeflow_tpu.parallel import MeshConfig
    from kubeflow_tpu.train import Trainer, TrainerConfig
    from kubeflow_tpu.train.data import synthetic_text_dataset

    dtype = jnp.bfloat16 if args.bf16 else jnp.float32
    mk = BertConfig.tiny if args.size == "tiny" else BertConfig.base
    arch = {
        k: v
        for k, v in (
            ("num_layers", args.num_layers),
            ("num_heads", args.num_heads),
            ("mlp_dim", args.mlp_dim),
        )
        if v > 0
    }
    cfg = mk(
        dtype=dtype,
        attention=args.attention,
        max_len=max(args.seq_len, 512),
        dropout_rate=0.0 if args.attention != "dense" else 0.1,
        moe_experts=args.moe_experts,
        remat=args.remat,
        **arch,
    )
    ds = synthetic_text_dataset(
        n_train=args.batch_size * 8,
        n_test=args.batch_size * 2,
        seq_len=args.seq_len,
        vocab_size=cfg.vocab_size,
        num_classes=args.num_classes,
    )
    if args.pipeline_stages > 1:
        import jax

        from kubeflow_tpu.models import BertPipelineClassifier

        # microbatches must stay divisible by the data-like mesh extent;
        # resolve an auto (-1) data axis the same way build_mesh will
        dp = args.data_parallel
        if dp == -1:
            fixed = (args.fsdp * args.model_parallel * args.context
                     * args.expert_parallel * args.pipeline_stages)
            dp = max(jax.device_count() // fixed, 1)
        data_ways = dp * args.fsdp * args.expert_parallel
        n_micro = 2 * args.pipeline_stages
        while n_micro > 1 and (
            args.batch_size % n_micro
            or (args.batch_size // n_micro) % data_ways
        ):
            n_micro -= 1
        model = BertPipelineClassifier(
            cfg, num_classes=args.num_classes,
            num_stages=args.pipeline_stages, n_micro=n_micro,
        )
    else:
        model = BertForSequenceClassification(cfg, num_classes=args.num_classes)
    tx = None
    if args.lora > 0:
        from kubeflow_tpu.train import LoraModel, lora_tx

        # works for the pipelined model too: stacked stage kernels get
        # per-stage adapters, sharded over `pipeline` by the stages/ rule
        model = LoraModel(model, rank=args.lora)
        # factory form: wraps the Trainer's config-built schedule (warmup,
        # cosine, clipping) so only the trainable-set changes, not the
        # optimizer dynamics
        tx = lora_tx
    trainer = Trainer(
        model,
        tx=tx,
        config=TrainerConfig(
            fused_steps=args.fused_steps,
            batch_size=args.batch_size,
            steps=args.steps,
            learning_rate=args.lr,
            warmup_steps=min(100, args.steps // 10),
            compute_dtype=dtype,
            checkpoint_dir=args.checkpoint_dir,
            mesh=MeshConfig(
                data=args.data_parallel,
                fsdp=args.fsdp,
                model=args.model_parallel,
                context=args.context,
                expert=args.expert_parallel,
                pipeline=args.pipeline_stages if args.pipeline_stages > 1 else 1,
            ),
            log_every_steps=10,
        ),
    )
    _, metrics = trainer.fit(ds)
    return metrics.get("final_accuracy", 0.0)


if __name__ == "__main__":
    main()
