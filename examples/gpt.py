"""Decoder-only causal LM — the long-context flagship example.

The ring path shards the SEQUENCE over the `context` axis with causal
global-position masking (parallel/ring_attention.py), so sequences far
beyond one device's attention memory train with the same module:

  python -m examples.gpt --device=tpu --size=small --steps=100
  python -m examples.gpt --size=tiny --seq-len=4096 --attention=ring --context=4
"""

from __future__ import annotations

import argparse


def main(argv: list[str] | None = None) -> float:
    p = argparse.ArgumentParser()
    p.add_argument("--device", default="auto", choices=["tpu", "cpu", "auto"])
    p.add_argument("--size", default="small", choices=["tiny", "small"])
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--fused-steps", type=int, default=1,
                   help="optimizer steps per jit dispatch (lax.scan chunks)")
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=256)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--attention", default="dense",
                   choices=["dense", "ring", "ulysses", "flash"])
    p.add_argument("--bf16", action="store_true", default=True)
    p.add_argument("--no-bf16", dest="bf16", action="store_false")
    p.add_argument("--data-parallel", type=int, default=-1)
    p.add_argument("--fsdp", type=int, default=1)
    p.add_argument("--model-parallel", type=int, default=1)
    p.add_argument("--context", type=int, default=1)
    p.add_argument("--num-kv-heads", type=int, default=0,
                   help="GQA: KV heads (< num_heads shrinks the KV cache; "
                        "0 = MHA)")
    p.add_argument("--position-embedding", default="learned",
                   choices=["learned", "rope"])
    p.add_argument("--attention-window", type=int, default=0,
                   help="sliding-window attention (Mistral): 0 = full "
                        "causal; dense attention only")
    p.add_argument("--checkpoint-dir", default=None)
    args = p.parse_args(argv)

    from kubeflow_tpu.utils import select_device

    select_device(args.device)

    import jax.numpy as jnp

    from kubeflow_tpu.models import (GPTConfig, GPTLM, causal_lm_eval_metrics,
                                    causal_lm_loss)
    from kubeflow_tpu.parallel import MeshConfig
    from kubeflow_tpu.train import Trainer, TrainerConfig
    from kubeflow_tpu.train.data import synthetic_lm_dataset

    dtype = jnp.bfloat16 if args.bf16 else jnp.float32
    mk = GPTConfig.tiny if args.size == "tiny" else GPTConfig.small
    cfg = mk(
        dtype=dtype,
        attention=args.attention,
        max_len=max(args.seq_len, 256),
        dropout_rate=0.0 if args.attention != "dense" else 0.1,
        num_kv_heads=args.num_kv_heads,
        position_embedding=args.position_embedding,
        attention_window=args.attention_window,
    )
    if args.model_parallel > 1 and args.num_kv_heads and \
            args.num_kv_heads % args.model_parallel:
        raise SystemExit(
            f"--num-kv-heads {args.num_kv_heads} must divide by "
            f"--model-parallel {args.model_parallel}: the K/V kernels "
            "shard their head axis over the model mesh axis, and a "
            "non-dividing count silently falls back to a replicated "
            "(degraded) TP layout")
    ds = synthetic_lm_dataset(
        n_train=args.batch_size * 8,
        n_test=args.batch_size * 2,
        seq_len=args.seq_len,
        vocab_size=cfg.vocab_size,
    )
    trainer = Trainer(
        GPTLM(cfg),
        TrainerConfig(
            fused_steps=args.fused_steps,
            batch_size=args.batch_size,
            steps=args.steps,
            learning_rate=args.lr,
            warmup_steps=min(100, args.steps // 10),
            compute_dtype=dtype,
            checkpoint_dir=args.checkpoint_dir,
            mesh=MeshConfig(
                data=args.data_parallel,
                fsdp=args.fsdp,
                model=args.model_parallel,
                context=args.context,
            ),
            log_every_steps=10,
        ),
        loss_fn=causal_lm_loss,
        eval_metrics_fn=causal_lm_eval_metrics,
    )
    _, metrics = trainer.fit(ds)
    return metrics.get("final_loss", float("inf"))


if __name__ == "__main__":
    main()
