"""Migrating a Llama/Mistral checkpoint onto the platform, end to end —
exit-code asserted (the platform_tour pattern):

  1. a torch Llama checkpoint appears (here: a tiny randomly-initialized
     transformers.LlamaForCausalLM standing in for real weights — zero
     egress, but byte-for-byte the real import path)
  2. `import-llama` converts it into a serving-ready gpt-lm predictor
     dir (GPTConfig.llama family: rope + GQA + RMSNorm + SwiGLU)
  3. served greedy continuations are checked EXACTLY equal to
     transformers' own generate() for the same weights
  4. the same predictor serves through the continuous-batching engine
  5. speculative decoding: self-draft shows the acceptance mechanism
     (every proposal accepted), a deliberately mismatched random draft
     shows the safety property (output still target-exact), and the
     temperature>0 rejection-sampling mode runs seeded

Run: python -m examples.llama_migration  (CPU, ~1 min)
"""

from __future__ import annotations

import sys
import tempfile
from pathlib import Path

import numpy as np


def main() -> int:
    import torch
    import transformers

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from kubeflow_tpu.cli import main as cli
    from kubeflow_tpu.models.gpt import generate
    from kubeflow_tpu.models.speculative import speculative_generate
    from kubeflow_tpu.serving.model import load_generative_model

    tmp = Path(tempfile.mkdtemp(prefix="llama_migration_"))

    # ---- 1. the incoming torch checkpoint -------------------------------
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5,
        tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(hf_cfg)
    hf.eval()
    ckpt = tmp / "llama.pt"
    torch.save({"state_dict": hf.state_dict(),
                "config": hf_cfg.to_dict()}, ckpt)
    print(f"[1] torch checkpoint written: {ckpt}")

    # ---- 2. one command to a serving dir --------------------------------
    out = tmp / "predictor"
    rc = cli(["import-llama", "--checkpoint", str(ckpt), "-o", str(out),
              "--device", "cpu", "--max-new-tokens", "8"])
    assert rc == 0, f"import-llama failed rc={rc}"
    print(f"[2] serving dir: {out}")

    # ---- 3. parity with transformers ------------------------------------
    model, variables, gen_cfg = load_generative_model(out)
    ids = np.array([[5, 9, 2, 11, 3, 7]], np.int64)
    # the imported config carries the checkpoint's eos (LlamaConfig
    # default 2); run BOTH sides with it so stopping semantics align —
    # hf stops early on eos, ours clamps, so compare hf's length
    eos = gen_cfg.get("eos_token_id")
    ours = np.asarray(generate(model, variables,
                               jnp.asarray(ids, jnp.int32),
                               max_new_tokens=8, eos_token_id=eos))
    with torch.no_grad():
        theirs = hf.generate(torch.from_numpy(ids), max_new_tokens=8,
                             do_sample=False, pad_token_id=0).numpy()
    cont = theirs[0, ids.shape[1]:]
    np.testing.assert_array_equal(ours[0][: len(cont)], cont)
    print(f"[3] greedy continuations EXACTLY match transformers: "
          f"{ours[0].tolist()}")

    # ---- 4. continuous-batching engine ----------------------------------
    from kubeflow_tpu.serving.continuous import ContinuousBatcher

    eng = ContinuousBatcher(model, variables, max_rows=2,
                            eos_token_id=eos)
    reqs = [eng.submit(np.asarray(ids[0], np.int32), max_new_tokens=6)
            for _ in range(3)]
    eng.run_until_idle()
    for r in reqs:
        got = r.result(timeout=2)  # engine trims at stop; ours clamps
        np.testing.assert_array_equal(got, ours[0][: len(got)])
    print("[4] 3 engine rows served; each equals the solo greedy decode")

    # ---- 5. speculative decoding (greedy-exact, then sampled) -----------
    draft_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=1,
        max_position_embeddings=64, tie_word_embeddings=False)
    torch.manual_seed(1)
    draft_hf = transformers.LlamaForCausalLM(draft_cfg)
    torch.save({"state_dict": draft_hf.state_dict(),
                "config": draft_cfg.to_dict()}, tmp / "draft.pt")
    rc = cli(["import-llama", "--checkpoint", str(tmp / "draft.pt"),
              "-o", str(tmp / "draft_dir"), "--device", "cpu"])
    assert rc == 0
    dmodel, dvars, _ = load_generative_model(tmp / "draft_dir")
    # the acceptance MECHANISM: a perfect draft (the target itself)
    # accepts every proposal — gamma tokens per target pass
    _, self_stats = speculative_generate(
        model, variables, model, variables, jnp.asarray(ids, jnp.int32),
        max_new_tokens=8, gamma=3, eos_token_id=eos)
    assert int(self_stats["drafted_accepted"]) == 3 * int(
        self_stats["rounds"])
    print(f"[5] self-draft accepts everything: "
          f"{int(self_stats['drafted_accepted'])} drafted tokens over "
          f"{int(self_stats['rounds'])} rounds")
    # the SAFETY property: a mismatched random draft still yields the
    # target's exact greedy decode (it only costs acceptance rate)
    spec, stats = speculative_generate(
        model, variables, dmodel, dvars, jnp.asarray(ids, jnp.int32),
        max_new_tokens=8, gamma=3, eos_token_id=eos)
    np.testing.assert_array_equal(np.asarray(spec)[0], ours[0])
    print(f"[5] mismatched-draft speculative greedy == target greedy "
          f"(rounds={int(stats['rounds'])}, "
          f"accepted={int(stats['drafted_accepted'])})")
    sampled, _ = speculative_generate(
        model, variables, dmodel, dvars, jnp.asarray(ids, jnp.int32),
        max_new_tokens=8, gamma=3, temperature=0.8,
        rng=jax.random.PRNGKey(0))
    assert np.asarray(sampled).shape == (1, 8)
    print(f"[5] sampled (T=0.8, seeded): {np.asarray(sampled)[0].tolist()}")
    print("llama migration lifecycle OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
