"""ViT image classification — the MXU-native image path.

Reference parity: kubeflow/examples ships image-classification training
images (SURVEY.md L6); the in-tree ViT family (models/vit.py) is the
performance-first counterpoint to the conv-bound ResNet flagship on this
backend: patch embedding is one reshape + one matmul, the encoder reuses
the BERT layer stack, and every FLOP is a matmul the MXU tiles natively.

  python -m examples.vit --device=cpu --size=tiny --steps=20
  python -m examples.vit --device=tpu --size=base --bf16
"""

from __future__ import annotations

import argparse


def main(argv: list[str] | None = None) -> float:
    p = argparse.ArgumentParser()
    p.add_argument("--device", default="auto", choices=["tpu", "cpu", "auto"])
    p.add_argument("--size", default="tiny", choices=["tiny", "base"])
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--fused-steps", type=int, default=1,
                   help="optimizer steps per jit dispatch (lax.scan chunks)")
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--num-classes", type=int, default=10)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--bf16", action="store_true", default=True)
    p.add_argument("--no-bf16", dest="bf16", action="store_false")
    p.add_argument("--data-parallel", type=int, default=-1)
    p.add_argument("--fsdp", type=int, default=1)
    p.add_argument("--checkpoint-dir", default=None)
    args = p.parse_args(argv)

    from kubeflow_tpu.utils import select_device

    select_device(args.device)

    import jax.numpy as jnp

    from kubeflow_tpu.models.vit import ViTClassifier, ViTConfig
    from kubeflow_tpu.parallel import MeshConfig
    from kubeflow_tpu.train import Trainer, TrainerConfig
    from kubeflow_tpu.train.data import synthetic_image_dataset

    dtype = jnp.bfloat16 if args.bf16 else jnp.float32
    mk = ViTConfig.tiny if args.size == "tiny" else ViTConfig.base
    cfg = mk(num_classes=args.num_classes, dtype=dtype, dropout_rate=0.0)
    dataset = synthetic_image_dataset(
        n_train=args.batch_size * 8,
        n_test=args.batch_size * 2,
        shape=(cfg.image_size, cfg.image_size, 3),
        num_classes=args.num_classes,
    )
    trainer = Trainer(
        ViTClassifier(cfg),
        TrainerConfig(
            fused_steps=args.fused_steps,
            batch_size=args.batch_size,
            steps=args.steps,
            learning_rate=args.lr,
            compute_dtype=dtype,
            checkpoint_dir=args.checkpoint_dir,
            mesh=MeshConfig(data=args.data_parallel, fsdp=args.fsdp),
            log_every_steps=10,
        ),
    )
    _, metrics = trainer.fit(dataset)
    return metrics.get("final_accuracy", 0.0)


if __name__ == "__main__":
    main()
