"""One-shot op characterization of the axon TPU backend (diagnostic, not shipped).

Times individual HLO classes with true host-read sync, printing incrementally.
Establishes which ops are pathological through the remote tunnel and whether
device-born vs host-born arrays differ on re-dispatch.
"""
import os
import time, sys
import jax

if os.environ.get("KFT_PROBE_PLATFORM"):
    # the axon sitecustomize force-registers the TPU plugin; a config update
    # (which wins over env) is required to actually get CPU
    jax.config.update("jax_platforms", os.environ["KFT_PROBE_PLATFORM"])
import jax.numpy as jnp


# Sync protocol (docs/perf.md item 1): block_until_ready lies through the
# tunnel, and a host read of only the LAST of N independent dispatches need
# not wait for the other N-1. So each iteration's output is folded into a
# scalar token and the loop ends with one host read of the token — data-
# dependent on every iteration. (A single device executes its queue serially,
# so total wall time is the sum of the executions.)
_fold = jax.jit(lambda tok, x: tok + x.ravel()[0].astype(jnp.float32) * 0.0)


def t(label, f, *args, iters=5):
    try:
        r = f(*args)
        tok = jnp.zeros(())
        tok = _fold(tok, jax.tree.leaves(r)[0])  # compile _fold for this shape
        _ = float(tok)  # warmup + sync
        t0 = time.perf_counter()
        for _ in range(iters):
            r = f(*args)
            tok = _fold(tok, jax.tree.leaves(r)[0])
        _ = float(tok)  # true sync: depends on all iters' outputs
        ms = (time.perf_counter() - t0) / iters * 1e3
        print(f"{label:40s} {ms:9.2f} ms", flush=True)
        return ms
    except Exception as e:  # noqa: BLE001
        print(f"{label:40s} FAILED {type(e).__name__}: {e}", flush=True)


print("devices:", jax.devices(), flush=True)
print("default_backend:", jax.default_backend(),
      "platform:", jax.devices()[0].platform, flush=True)
# the ResNet conv_impl="auto" switch keys on default_backend() == "axon";
# this line is the ground truth for that assumption

# --- host-born vs device-born re-pass
N = 1 << 22  # 4M f32 = 16MB
host_x = jnp.ones((N,), jnp.float32)
dev_x = jax.jit(lambda: jnp.ones((N,), jnp.float32))()
add1 = jax.jit(lambda x: x + 1.0)
t("repass host-born 16MB", add1, host_x)
t("repass device-born 16MB", add1, dev_x)

# --- matmul classes (bf16)
mk = lambda *s: jax.jit(lambda: jnp.full(s, 0.01, jnp.bfloat16))()
a = mk(1024, 1024); b = mk(1024, 1024)
t("matmul 1024^3 bf16", jax.jit(lambda a, b: a @ b), a, b)
tall = mk(100352, 128); w128 = mk(128, 128)
t("matmul tall-skinny (100352,128)@(128,128)", jax.jit(lambda a, b: a @ b), tall, w128)

# --- gather / scatter / one-hot (embedding patterns)
table = mk(30522, 768)
idx = jax.jit(lambda: jnp.arange(2048, dtype=jnp.int32) % 30522)()
t("gather rows table[idx] (2048 of 30522x768)", jax.jit(lambda T, i: T[i]), table, idx)
onehot = jax.jit(lambda i: jax.nn.one_hot(i, 30522, dtype=jnp.bfloat16))
t("one-hot(2048,30522) build", onehot, idx)
t("one-hot @ table", jax.jit(lambda i, T: jax.nn.one_hot(i, 30522, dtype=jnp.bfloat16) @ T), idx, table)
dy = mk(2048, 768)
t("scatter-add grad-of-gather", jax.jit(
    lambda T, i, dy: jnp.zeros_like(T).at[i].add(dy)), table, idx, dy)

# --- elementwise / norm / softmax / transpose / reduce
x = mk(16, 128, 768)
t("layernorm (16,128,768)", jax.jit(
    lambda x: (x - x.mean(-1, keepdims=True)) / jnp.sqrt(x.var(-1, keepdims=True) + 1e-6)), x)
t("gelu", jax.jit(jax.nn.gelu), x)
s = mk(16, 12, 128, 128)
t("softmax (16,12,128,128)", jax.jit(lambda s: jax.nn.softmax(s.astype(jnp.float32), -1)), s)
big = mk(4096, 4096)
t("transpose 4096^2", jax.jit(lambda x: x.T.copy()), big)
t("reduce sum 4096^2", jax.jit(lambda x: x.sum()), big)

# --- convs
img = mk(128, 56, 56, 64)
k3 = mk(3, 3, 64, 64)
t("conv 3x3 56x56x64 bs128", jax.jit(
    lambda x, w: jax.lax.conv_general_dilated(
        x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))), img, k3)
img8 = mk(8, 56, 56, 64)
t("conv 3x3 56x56x64 bs8", jax.jit(
    lambda x, w: jax.lax.conv_general_dilated(
        x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))), img8, k3)

from kubeflow_tpu.models.conv import im2col_conv  # noqa: E402

t("im2col 3x3 56x56x64 bs128", jax.jit(lambda x, w: im2col_conv(x, w)), img, k3)
t("im2col bwd 3x3 56x56x64 bs128", jax.jit(jax.grad(
    lambda w, x: (im2col_conv(x, w) ** 2).mean())), k3, img)
t("conv bwd 3x3 56x56x64 bs128", jax.jit(jax.grad(
    lambda w, x: (jax.lax.conv_general_dilated(
        x, w, (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC")) ** 2).mean())), k3, img)
t("maxpool 3x3s2 112x112x64 bs128", jax.jit(
    lambda x: jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME")),
  mk(128, 112, 112, 64))
t("batchnorm-reduce (128,56,56,64)", jax.jit(
    lambda x: (x - x.mean((0, 1, 2))) / jnp.sqrt(x.var((0, 1, 2)) + 1e-5)), img)

# --- optimizer-shaped pytree update (many buffers)
tree = [jax.jit(lambda i=i: jnp.full((512, 512), float(i)))() for i in range(40)]
t("pytree update 40x(512,512)", jax.jit(lambda t: [x * 0.999 + 0.001 for x in t]), tree)

# --- full BERT-ish transformer layer fwd+bwd (no embed)
def layer(p, x):
    q = x @ p["q"]; k = x @ p["k"]; v = x @ p["v"]
    B, L, H = x.shape
    q = q.reshape(B, L, 12, 64); k = k.reshape(B, L, 12, 64); v = v.reshape(B, L, 12, 64)
    sc = jnp.einsum("blhd,bmhd->bhlm", q, k) / 8.0
    pr = jax.nn.softmax(sc.astype(jnp.float32), -1).astype(x.dtype)
    y = jnp.einsum("bhlm,bmhd->blhd", pr, v).reshape(B, L, H)
    y = y @ p["o"]
    h = jax.nn.gelu(y @ p["up"]) @ p["dn"]
    return ((x + h) ** 2).mean()

p = {k: mk(*s) for k, s in dict(
    q=(768, 768), k=(768, 768), v=(768, 768), o=(768, 768),
    up=(768, 3072), dn=(3072, 768)).items()}
xin = mk(16, 128, 768)
t("1 bert layer fwd+loss", jax.jit(layer), p, xin)
t("1 bert layer grad", jax.jit(jax.grad(layer)), p, xin)
# --- full-model conv head-to-head (LAST: each variant AOT-compiles a full
# train step through the tunnel, the likeliest section to wedge — a hang
# here must not cost the cheap measurements above): ResNet-18 (32x32) train step, xla conv
# vs im2col — the end-to-end evidence for conv_impl="auto" (per-op numbers
# above don't capture fusion/backward effects)
def _resnet_step_ms(impl: str) -> None:
    from kubeflow_tpu.models.resnet import ResNet18
    from kubeflow_tpu.train import Trainer, TrainerConfig
    from kubeflow_tpu.train.data import synthetic_image_dataset

    ds = synthetic_image_dataset(n_train=32, n_test=32, shape=(32, 32, 3),
                                 num_classes=10)
    trainer = Trainer(
        ResNet18(num_classes=10, dtype=jnp.bfloat16, small_inputs=True,
                 conv_impl=impl),
        TrainerConfig(batch_size=32, compute_dtype=jnp.bfloat16,
                      log_every_steps=10**9),
    )
    from bench import _timed_steps  # the ONE timing protocol (true sync)

    state = trainer.init_state(ds.x_train[:32])
    batch = (ds.x_train[:32], ds.y_train[:32])
    steps = 5
    dt = _timed_steps(trainer, state, batch, steps)
    print(f"{'resnet18-32px step (' + impl + ')':40s} "
          f"{dt / steps * 1e3:9.2f} ms", flush=True)


for _impl in ("xla", "im2col"):
    try:
        _resnet_step_ms(_impl)
    except Exception as e:  # noqa: BLE001
        print(f"resnet18 step ({_impl}) FAILED {type(e).__name__}: {e}",
              flush=True)

print("probe done", flush=True)
