"""BERT step breakdown on the axon backend (diagnostic, not shipped).

Round-2 mystery: BERT-base bs=16 L=128 measured 0.52 steps/s (~2 s/step) on
the v5e while its ~1.4 TFLOP/step should take ~15 ms at the measured matmul
throughput. This probe bisects the step: embedding, encoder depth sweep,
head, loss/grad, optimizer — all with the token-chained true-sync protocol
from probe_ops.py (block_until_ready lies through the tunnel).
"""
import os
import time

import jax

if os.environ.get("KFT_PROBE_PLATFORM"):
    # the axon sitecustomize force-registers the TPU plugin; a config update
    # (which wins over env) is required to actually get CPU
    jax.config.update("jax_platforms", os.environ["KFT_PROBE_PLATFORM"])
import jax.numpy as jnp

_fold = jax.jit(lambda tok, x: tok + x.ravel()[0].astype(jnp.float32) * 0.0)


def t(label, f, *args, iters=3):
    try:
        r = f(*args)
        tok = jnp.zeros(())
        tok = _fold(tok, jax.tree.leaves(r)[0])
        _ = float(tok)
        t0 = time.perf_counter()
        for _ in range(iters):
            r = f(*args)
            tok = _fold(tok, jax.tree.leaves(r)[0])
        _ = float(tok)
        ms = (time.perf_counter() - t0) / iters * 1e3
        print(f"{label:44s} {ms:9.2f} ms", flush=True)
        return ms
    except Exception as e:  # noqa: BLE001
        print(f"{label:44s} FAILED {type(e).__name__}: {e}", flush=True)


def devborn(x):
    """Rebirth a (pytree of) host-born array(s) as jit outputs so the tunnel
    stops re-uploading them on every dispatch (docs/perf.md item 2)."""
    return jax.jit(lambda t_: jax.tree.map(lambda a: a + jnp.zeros((), a.dtype), t_))(x)


print("devices:", jax.devices(), flush=True)

from kubeflow_tpu.models import BertConfig, BertForSequenceClassification  # noqa: E402
from kubeflow_tpu.models.bert import (  # noqa: E402
    BertEmbeddings, BertLayer, VocabEmbed,
)

# KFT_PROBE_TINY=1: tiny config for CPU smoke tests of this script itself
if os.environ.get("KFT_PROBE_TINY"):
    cfg = BertConfig.tiny(dtype=jnp.bfloat16, dropout_rate=0.0)
    bs, L = 4, 16
else:
    cfg = BertConfig.base(dtype=jnp.bfloat16, dropout_rate=0.0)
    bs, L = 16, 128
rng = jax.random.PRNGKey(0)
ids = devborn(jnp.ones((bs, L), jnp.int32))

# --- raw vocab lookup (gather vs one-hot paths)
emb = VocabEmbed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype)
pe = devborn(emb.init(rng, ids))
t("vocab-embed fwd", jax.jit(lambda p, i: emb.apply(p, i)), pe, ids)
t("vocab-embed fwd+bwd", jax.jit(jax.grad(
    lambda p, i: emb.apply(p, i).astype(jnp.float32).sum())), pe, ids)

# --- full embeddings block (token+pos+type+LN)
embs = BertEmbeddings(cfg)
pem = devborn(embs.init(rng, ids))
t("bert-embeddings fwd", jax.jit(lambda p, i: embs.apply(p, i)), pem, ids)
t("bert-embeddings fwd+bwd", jax.jit(jax.grad(
    lambda p, i: embs.apply(p, i).astype(jnp.float32).sum())), pem, ids)

# --- one transformer layer given hidden states
x = devborn(jnp.full((bs, L, cfg.hidden_size), 0.01, cfg.dtype))
mask = devborn(jnp.ones((bs, L), bool))
layer = BertLayer(cfg)
pl = devborn(layer.init(rng, x, mask, False))
t("1 bert layer fwd", jax.jit(
    lambda p, x, m: layer.apply(p, x, m, False)), pl, x, mask)
t("1 bert layer fwd+bwd", jax.jit(jax.grad(
    lambda p, x, m: layer.apply(
        p, x, m, False).astype(jnp.float32).sum())), pl, x, mask)

# --- full model fwd / value_and_grad / full train step
model = BertForSequenceClassification(cfg, num_classes=2)
pm = devborn(model.init(rng, ids))
t("full bert fwd", jax.jit(
    lambda p, i: model.apply(p, i)), pm, ids)

y = devborn(jnp.zeros((bs,), jnp.int32))


def loss_fn(p, i, y):
    logits = model.apply(p, i).astype(jnp.float32)
    import optax

    return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()


t("full bert loss grad", jax.jit(jax.grad(loss_fn)), pm, ids, y)

from kubeflow_tpu.train import Trainer, TrainerConfig  # noqa: E402
from kubeflow_tpu.parallel.sharding import shard_batch  # noqa: E402

trainer = Trainer(BertForSequenceClassification(cfg, num_classes=2),
                  TrainerConfig(batch_size=bs, compute_dtype=jnp.bfloat16,
                                log_every_steps=10**9))
state = trainer.init_state(jnp.ones((bs, L), jnp.int32))
with jax.set_mesh(trainer.mesh):
    batch = shard_batch((jnp.ones((bs, L), jnp.int32),
                         jnp.zeros((bs,), jnp.int32)), trainer.mesh)
    batch = jax.jit(lambda t_: jax.tree.map(lambda a: a + 0, t_))(batch)
state, m = trainer.train_step(state, batch)
float(m["loss"])
t0 = time.perf_counter()
for _ in range(5):
    state, m = trainer.train_step(state, batch)
float(m["loss"])
print(f"{'full train_step (device-born batch)':44s} "
      f"{(time.perf_counter() - t0) / 5 * 1e3:9.2f} ms", flush=True)
print("probe_bert done", flush=True)
