# Top-level developer entry points. The native core has its own Makefile
# (kubeflow_tpu/native/Makefile) for building libkfcore.so and the
# sanitizer self-test binaries.

NATIVE := kubeflow_tpu/native

.PHONY: test lint modelcheck test-analysis test-chaos test-trace test-health test-prof test-cplane test-fleet test-hotpath test-partition test-slo test-decode test-soak test-pods test-sched test-protocheck selftest-sanitizers native

test: lint modelcheck
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow'

# kftpu-check: AST invariant linter (docs/analysis.md). Exits non-zero on
# any finding not pinned in tests/golden/lint_baseline.json; regenerate
# with `KFTPU_UPDATE_LINT_BASELINE=1 python -m kubeflow_tpu.analysis`
# (only to shrink it — never grow it to dodge a new finding).
lint:
	python -m kubeflow_tpu.analysis

# kftpu-protocheck: bounded-exhaustive model checking of the wire /
# paged-KV-handoff / chip-ledger protocol state machines, with minimal
# counterexample schedules on violation (docs/analysis.md "Protocol
# model checking"; KFTPU_MODELCHECK_DEPTH / KFTPU_MODELCHECK_SEED widen
# the sweep). Sub-second at the default budget — a `make test` step.
modelcheck:
	python -m kubeflow_tpu.analysis --modelcheck

# kftpu-check's own suite: checker fixtures, baseline round-trip, and the
# lock-order/race detector unit tests (docs/analysis.md)
test-analysis:
	JAX_PLATFORMS=cpu python -m pytest tests/test_analysis.py -q -m analysis

# recovery drills only (seeded fault injection — docs/chaos.md)
test-chaos:
	JAX_PLATFORMS=cpu python -m pytest tests/test_chaos_drills.py -q -m chaos

# tracing + flight-recorder suite, incl. the gang-restart trace drill
# (docs/observability.md)
test-trace:
	JAX_PLATFORMS=cpu python -m pytest tests/test_tracing.py -q -m trace

# liveness layer: heartbeat leases, hang/straggler detection, and the
# verified-checkpoint fallback drill (docs/health.md)
test-health:
	JAX_PLATFORMS=cpu python -m pytest tests/test_health_drills.py -q -m health

# profiling layer: trace analytics + golden trace-shape drill + the
# CPU-proxy perf gate against tests/golden/prof_budgets.json
# (docs/profiling.md; KFTPU_UPDATE_PROF_BUDGETS=1 regenerates budgets)
test-prof:
	JAX_PLATFORMS=cpu python -m pytest tests/test_profiling.py tests/test_prof_gate.py -q -m prof

# control-plane scale-out suite: sharded/filtered watch drills, keyed-pool
# per-key ordering, status-write group commit, and the 10k-pod storm gate
# (docs/architecture.md "Control-plane scaling")
test-cplane:
	JAX_PLATFORMS=cpu python -m pytest tests/test_cplane.py -q -m cplane
	JAX_PLATFORMS=cpu python -m pytest tests/test_prof_gate.py -q -m prof

# serving-fleet suite: paged-KV prefix reuse, chunked-prefill equivalence,
# router admission/shed + the seeded replica-kill drill, and the
# serve_fleet cpu-proxy gate (docs/serving.md)
test-fleet:
	JAX_PLATFORMS=cpu python -m pytest tests/test_fleet.py -q -m fleet
	JAX_PLATFORMS=cpu python -m pytest tests/test_prof_gate.py -q -m prof

# training hot-path suite: restart-warm compile cache (warm incarnation
# = zero backend compiles), AsyncLoader edge drills under the lock-order
# detector, analytics splits, and the train_restart_warm cpu-proxy gate
# (docs/perf.md "MFU hunt")
test-hotpath:
	JAX_PLATFORMS=cpu python -m pytest tests/test_hotpath.py -q -m hotpath
	JAX_PLATFORMS=cpu python -m pytest tests/test_prof_gate.py -q -m prof

# kftpu-partition suite: logical-axis rule derivation, legacy round-trip,
# hybrid-mesh guard, bf16-by-default numerics gate, buffer-donation
# accounting, and the grad_overlap cpu-proxy gate (docs/partitioner.md)
test-partition:
	JAX_PLATFORMS=cpu python -m pytest tests/test_partitioner.py -q -m partition
	JAX_PLATFORMS=cpu python -m pytest tests/test_prof_gate.py -q -m prof

# kftpu-reqtrace suite: serving request tracing (golden kill→requeue
# trace shape), the bounded TSDB, SLO burn-rate evaluation, /debug/slo
# surface agreement, and the decode-tick burn teeth in the prof gate
# (docs/slo.md)
test-slo:
	JAX_PLATFORMS=cpu python -m pytest tests/test_slo.py -q -m slo
	JAX_PLATFORMS=cpu python -m pytest tests/test_prof_gate.py -q -m prof

# kftpu-decode suite: decode rows growing paged block chains
# (allocate-on-boundary, COW-safe sharing), block-budgeted admission,
# chain adoption by digest, speculative x chunked composition pinned
# token-identical, the disaggregated prefill/decode tier, and the
# resume-from-KV requeue drill + serve_disagg cpu-proxy gate
# (docs/serving.md "Disaggregated prefill/decode")
test-decode:
	JAX_PLATFORMS=cpu python -m pytest tests/test_decode.py -q -m decode
	JAX_PLATFORMS=cpu python -m pytest tests/test_prof_gate.py -q -m prof

# kftpu-storm suite: the closed autoscaling loop (scale-up cooldown,
# graceful-drain scale-down, loss-free drain-kill resume, scale-to-zero
# + wake-on-arrival, hang detection, frozen-scaler chaos mode), the
# golden scaler decision trace, activator cold-start Retry-After
# calibration, SLO monitoring across scaler activity, and the seeded
# production-day soak + its prod_day cpu-proxy gate
# (docs/autoscaling.md)
test-soak:
	JAX_PLATFORMS=cpu python -m pytest tests/test_soak.py -q -m soak
	JAX_PLATFORMS=cpu python -m pytest tests/test_prof_gate.py -q -m prof

# kftpu-pods suite: cross-process pod-backed replicas — real subprocess
# workers behind the length-prefixed wire protocol over BOTH transports
# (AF_UNIX and kftpu-net's 127.0.0.1 TCP), the digest-checked paged-KV
# handoff codec, SIGKILL mid-decode zero-drop chain resume, SIGSTOP
# heartbeat-age hang indictment + scaler replacement, torn-frame retry
# idempotency, end-to-end deadline propagation, the network failure
# family (severed-connection replay, stale-epoch 410 fencing, the
# partition-heal split-brain drill), and the serve_pods/serve_pods_tcp
# cpu-proxy gates with their wire-fault and net-fault teeth
# (docs/serving.md "Pod-backed replicas")
test-pods:
	JAX_PLATFORMS=cpu python -m pytest tests/test_pods.py -q -m pods
	JAX_PLATFORMS=cpu python -m pytest tests/test_prof_gate.py -q -m prof

# kftpu-chipsched suite: the shared chip ledger both workload classes
# claim through — slice-aware placement, priority preemption through
# the gang-restart path (sched.preempt→job.gang_restart span link +
# restart-warm resume), DRF tenant quotas with borrow/reclaim, the
# deny/Retry-After contract, /debug/sched surface agreement, and the
# diurnal_storm cpu-proxy gate with its sched_freeze teeth
# (docs/scheduler.md)
test-sched:
	JAX_PLATFORMS=cpu python -m pytest tests/test_chipsched.py -q -m sched
	JAX_PLATFORMS=cpu python -m pytest tests/test_prof_gate.py -q -m prof

# kftpu-protocheck suite: exploration-kernel unit tests, HEAD-clean pins,
# the per-mutation counterexample pins, and recorded-trace conformance
# (docs/analysis.md "Protocol model checking")
test-protocheck: modelcheck
	JAX_PLATFORMS=cpu python -m pytest tests/test_protocheck.py -q -m modelcheck

native:
	$(MAKE) -C $(NATIVE)

# Run the prebuilt ASan/UBSan + TSan self-tests of the native core
# (workqueue, expectations, event hub, reconciler, metastore). The
# checked-in binaries are the fast path; a binary that is missing or was
# linked against a sanitizer runtime this machine doesn't ship (ldd
# reports 'not found') is rebuilt from source first.
selftest-sanitizers:
	@for t in selftest_asan selftest_tsan; do \
	  bin=$(NATIVE)/build/$$t; \
	  if ! ldd $$bin >/dev/null 2>&1 || ldd $$bin | grep -q "not found"; then \
	    echo "rebuilding $$t (prebuilt binary not runnable here)"; \
	    $(MAKE) -B -C $(NATIVE) build/$$t || exit 1; \
	  fi; \
	done
	$(NATIVE)/build/selftest_asan
	$(NATIVE)/build/selftest_tsan
