# Top-level developer entry points. The native core has its own Makefile
# (kubeflow_tpu/native/Makefile) for building libkfcore.so and the
# sanitizer self-test binaries.

NATIVE := kubeflow_tpu/native

.PHONY: test test-chaos test-trace test-health selftest-sanitizers native

test:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow'

# recovery drills only (seeded fault injection — docs/chaos.md)
test-chaos:
	JAX_PLATFORMS=cpu python -m pytest tests/test_chaos_drills.py -q -m chaos

# tracing + flight-recorder suite, incl. the gang-restart trace drill
# (docs/observability.md)
test-trace:
	JAX_PLATFORMS=cpu python -m pytest tests/test_tracing.py -q -m trace

# liveness layer: heartbeat leases, hang/straggler detection, and the
# verified-checkpoint fallback drill (docs/health.md)
test-health:
	JAX_PLATFORMS=cpu python -m pytest tests/test_health_drills.py -q -m health

native:
	$(MAKE) -C $(NATIVE)

# Run the prebuilt ASan/UBSan + TSan self-tests of the native core
# (workqueue, expectations, event hub, reconciler, metastore). The
# checked-in binaries are the fast path; a binary that is missing or was
# linked against a sanitizer runtime this machine doesn't ship (ldd
# reports 'not found') is rebuilt from source first.
selftest-sanitizers:
	@for t in selftest_asan selftest_tsan; do \
	  bin=$(NATIVE)/build/$$t; \
	  if ! ldd $$bin >/dev/null 2>&1 || ldd $$bin | grep -q "not found"; then \
	    echo "rebuilding $$t (prebuilt binary not runnable here)"; \
	    $(MAKE) -B -C $(NATIVE) build/$$t || exit 1; \
	  fi; \
	done
	$(NATIVE)/build/selftest_asan
	$(NATIVE)/build/selftest_tsan
