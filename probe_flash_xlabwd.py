"""Hardware verdict for the new FLASH_BWD_IMPL="xla" default: pallas
forward (Mosaic-validated) + residual-consuming XLA backward — correctness
vs the blockwise reference, and fwd+bwd timing vs the pure-XLA path it
must beat (it saves one forward replay by consuming the saved lse)."""

from __future__ import annotations

import os
import threading
import time

WATCHDOG_S = 420.0
_last = [time.monotonic()]


def _pet():
    _last[0] = time.monotonic()


def _watchdog():
    while True:
        time.sleep(5.0)
        if time.monotonic() - _last[0] > WATCHDOG_S:
            print("RESULT watchdog=hang", flush=True)
            os._exit(3)


threading.Thread(target=_watchdog, daemon=True).start()


def main() -> None:
    import jax

    if os.environ.get("KFT_BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["KFT_BENCH_PLATFORM"])
    import jax.numpy as jnp

    from kubeflow_tpu.parallel import ring_attention as ra
    from kubeflow_tpu.parallel.ring_attention import (
        blockwise_attention,
        flash_attention,
    )

    dev = jax.devices()[0]
    print(f"RESULT device_kind={dev.device_kind!r} platform={dev.platform}",
          flush=True)
    float((jnp.ones((8, 8)) @ jnp.ones((8, 8))).sum())
    _pet()
    assert ra.FLASH_BWD_IMPL == "xla"

    def born(*shape, key, dtype=jnp.bfloat16):
        x = jax.random.normal(jax.random.PRNGKey(key), shape, jnp.float32)
        return jax.jit(lambda v: (v * 0.125).astype(dtype))(x)

    # ---- correctness at training shapes ---------------------------------
    # (tiny on CPU: the interpret-mode pallas forward is minutes-slow at
    # real shapes, and the CPU pass only sanity-checks the script)
    small = jax.default_backend() == "cpu"
    b, l, h, d = (1, 128, 2, 32) if small else (2, 1024, 12, 64)
    q = born(b, l, h, d, key=0)
    k = born(b, l, h, d, key=1)
    v = born(b, l, h, d, key=2)
    bias = born(b, 1, 1, l, key=4, dtype=jnp.bfloat16)
    ct = born(b, l, h, d, key=3)

    for causal in (False, True):
        tag = "causal" if causal else "full"

        def loss_ref(q, k, v, bias, c=causal):
            return (blockwise_attention(q, k, v, bias, block=256, causal=c)
                    .astype(jnp.float32) * ct.astype(jnp.float32)).sum()

        def loss_flash(q, k, v, bias, c=causal):
            return (flash_attention(q, k, v, bias, block=256, causal=c)
                    .astype(jnp.float32) * ct.astype(jnp.float32)).sum()

        try:
            ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2, 3)))(
                q, k, v, bias)
            got = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2, 3)))(
                q, k, v, bias)
            errs = [
                float(jnp.max(jnp.abs(
                    a.astype(jnp.float32) - r.astype(jnp.float32))))
                for a, r in zip(got, ref)
            ]
            ok = max(errs[:3]) < 0.25 and errs[3] < 2.0
            print(f"RESULT xlabwd_{tag}={'PASS' if ok else 'FAIL'} "
                  f"dq={errs[0]:.4g} dk={errs[1]:.4g} dv={errs[2]:.4g} "
                  f"dbias={errs[3]:.4g}", flush=True)
        except Exception as exc:  # noqa: BLE001 — verdict line
            print(f"RESULT xlabwd_{tag}=ERROR {type(exc).__name__}",
                  flush=True)
        _pet()

    # ---- timing at GPT-2s 2k shapes -------------------------------------
    b, l = (1, 256) if small else (4, 2048)
    q = born(b, l, h, d, key=10)
    k = born(b, l, h, d, key=11)
    v = born(b, l, h, d, key=12)
    bias = jnp.zeros((b, 1, 1, l), jnp.bfloat16)
    ct = born(b, l, h, d, key=13)
    total_flops = 2 * 2 * b * h * l * l * d * 0.5 * 3.5

    def timed(fn, *args, iters=8):
        val = fn(*args)
        val = fn(*args)
        jax.tree_util.tree_map(
            lambda x: float(x.astype(jnp.float32).sum()), val)
        _pet()
        t0 = time.perf_counter()
        for _ in range(iters):
            val = fn(*args)
        jax.tree_util.tree_map(
            lambda x: float(x.astype(jnp.float32).sum()), val)
        return (time.perf_counter() - t0) / iters

    def loss_flash(q, k, v, bias):
        return (flash_attention(q, k, v, bias, block=256, causal=True)
                .astype(jnp.float32) * ct.astype(jnp.float32)).sum()

    def loss_bw(q, k, v, bias):
        return (blockwise_attention(q, k, v, bias, block=256, causal=True)
                .astype(jnp.float32) * ct.astype(jnp.float32)).sum()

    for tag, fn in (("flash_xlabwd", loss_flash), ("pure_xla", loss_bw)):
        try:
            dt = timed(jax.jit(jax.grad(fn, argnums=(0, 1, 2, 3))), q, k, v,
                       bias)
            print(f"RESULT {tag}_fwdbwd_ms={dt * 1e3:.2f} "
                  f"tflops={total_flops / dt / 1e12:.2f}", flush=True)
        except Exception as exc:  # noqa: BLE001
            print(f"RESULT {tag}_timing=ERROR {type(exc).__name__}",
                  flush=True)
        _pet()

    print("RESULT probe_flash_xlabwd=complete", flush=True)


if __name__ == "__main__":
    main()
