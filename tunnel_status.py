"""Regenerate TUNNEL_STATUS.md — the at-a-glance capture-state artifact.

VERDICT r4 #8: the watcher's state (windows seen, stages pending, metric
coverage) must be visible to every session — builder, judge, driver —
without reading tunnel_watch logs. tunnel_watch3.sh runs this on every
poll loop; it is also safe to run by hand. Imports bench (no jax at module
level) for the capture-merge logic so the coverage table can never drift
from what bench.py itself would adopt.

  python tunnel_status.py --alive 0|1   # watcher poll result for the header
"""

from __future__ import annotations

import os
import sys
import time

import bench

HERE = os.path.dirname(os.path.abspath(__file__))

# (artifact, description) in the exact order tunnel_watch3.sh runs them
STAGES = [
    ("bench_r5_headline.jsonl",
     "headline: resnet+bert only, <5 min — banks the north-star numbers"),
    ("probe_flash_r5.txt",
     "flash-backward verdict: loop2 + dd-prekernel candidates, term bisect"),
    ("probe_flash_r5b.txt",
     "which-side forensics: per-side NaN counts + dense-f32 v2 verdicts"),
    ("bench_r5_suite.jsonl",
     "full fixed-protocol suite (resume-seeded; never-captured rows first)"),
    ("probe_resnet.txt",
     "conv ceiling / stem A-B (shipped flags) for the ResNet MFU verdict"),
    ("probe_flash_xlabwd.txt", "xla-backward timing/numerics detail"),
]

WATCH_LOG = "tunnel_watch3.log"


def _stage_state(artifact: str) -> tuple[str, str]:
    """(status, detail) for one staged artifact."""
    path = os.path.join(HERE, artifact)
    script_missing = (
        artifact.startswith("probe_")
        and not os.path.exists(os.path.join(
            HERE, artifact.replace(".txt", ".py"))))
    if script_missing:
        return "not staged", "probe script absent"
    if os.path.exists(path + ".done"):
        return "DONE", _mtime(path + ".done")
    if os.path.exists(path):
        detail = f"partial since {_mtime(path)}"
        if artifact.endswith(".jsonl"):
            with open(path) as fh:
                rows = bench._parse_capture_lines(fh)
            detail += f", {len(rows)} row(s) banked"
        return "partial", detail
    return "pending", "no output yet"


def _mtime(path: str) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ",
                         time.gmtime(os.path.getmtime(path)))


def _windows_seen() -> list[str]:
    """Distinct live windows from the watcher log: consecutive 'alive'
    polls <20 min apart are the SAME window (one window survives several
    loop iterations when a stage inside it fails and the loop re-polls) —
    counting raw alive lines would overstate how often the tunnel opens,
    the exact stat the capture plan is calibrated against."""
    stamps = []
    try:
        with open(os.path.join(HERE, WATCH_LOG)) as fh:
            for ln in fh:
                if "tunnel alive" in ln and " at " in ln:
                    stamp = ln.strip().split(" at ")[1].split(" ")[0]
                    try:
                        t = time.mktime(time.strptime(
                            stamp, "%Y-%m-%dT%H:%M:%SZ"))
                    except ValueError:
                        continue
                    stamps.append((t, stamp))
    except OSError:
        pass
    windows: list[str] = []
    last_t = None
    for t, stamp in stamps:
        if last_t is None or t - last_t > 20 * 60:
            windows.append(f"window opened {stamp}")
        else:
            windows[-1] = windows[-1].split(" — ")[0] + f" — last alive {stamp}"
        last_t = t
    return windows


def main() -> None:
    alive = None
    if "--alive" in sys.argv:
        alive = sys.argv[sys.argv.index("--alive") + 1] == "1"
    now = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())

    out = ["# Tunnel capture status", "",
           f"Generated {now} by tunnel_status.py "
           f"(regenerated on every tunnel_watch3.sh poll).", ""]
    if alive is not None:
        out += [f"**Last probe:** tunnel {'ALIVE' if alive else 'down'} "
                f"at {now}", ""]
    windows = _windows_seen()
    out += [f"**Live windows seen by this watcher:** {len(windows)}"]
    out += [f"- `{w}`" for w in windows[-8:]]
    out += ["", "## Stages", "",
            "| artifact | status | detail | purpose |", "|---|---|---|---|"]
    for artifact, desc in STAGES:
        status, detail = _stage_state(artifact)
        out.append(f"| `{artifact}` | {status} | {detail} | {desc} |")

    out += ["", "## Metric coverage (merged captures, newest wins)", "",
            "| metric | value | mfu | protocol | captured |",
            "|---|---|---|---|---|"]
    captures = bench._load_captures()
    captured = captures[0] if captures else {}
    for _fn, metric, unit in bench.SUITE_BENCHES:
        r = captured.get(metric)
        if r:
            out.append(
                f"| {metric} | {r['value']} {unit} | {r.get('mfu')} | "
                f"{r.get('capture_protocol')} | {r.get('captured_at')} |")
        else:
            out.append(f"| {metric} | — | — | — | **NEVER** |")
    never = [m for _f, m, _u in bench.SUITE_BENCHES if m not in captured]
    out += ["",
            f"Never captured: {len(never)}/{len(bench.SUITE_BENCHES)}"
            + (f" ({', '.join(never)})" if never else ""), ""]

    with open(os.path.join(HERE, "TUNNEL_STATUS.md"), "w") as fh:
        fh.write("\n".join(out))


if __name__ == "__main__":
    main()
