"""Shared machinery for the staged hardware probes (probe_flash_r5,
probe_resnet): cross-window resume banking and the ERROR-exit contract.

The watcher (tunnel_watch3.sh) appends probe stdout to the artifact on
every exit path and marks `.done` only on exit 0. Probes therefore:
  - skip work whose RESULT keys are already BANKED (recorded with a
    non-ERROR value) so successive short windows converge;
  - exit nonzero when any section recorded ERROR this run, so the stage
    is NOT marked done and the un-banked ERROR keys retry at the next
    window (a deterministic ERROR re-runs cheaply — everything else is
    banked and skipped).
This mirrors bench.py's last-line-per-metric capture contract and
tunnel_watch3.sh's last_val parsing.
"""

from __future__ import annotations

import os

_ERRORS: list[str] = []


def banked_keys(artifact: str) -> set[str]:
    """RESULT keys recorded with a non-ERROR value in the appended
    artifact (KFT_PROBE_ARTIFACT overrides the path for tests)."""
    path = os.environ.get("KFT_PROBE_ARTIFACT") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), artifact)
    keys: set[str] = set()
    try:
        with open(path) as fh:
            for ln in fh:
                if ln.startswith("RESULT ") and "=" in ln:
                    key, val = ln[len("RESULT "):].split("=", 1)
                    if val.split(None, 1)[0].strip() != "ERROR":
                        keys.add(key.strip())
    except OSError:
        pass
    return keys


def record_error(key: str) -> None:
    """Note an ERROR verdict so exit_code() keeps the stage retryable."""
    _ERRORS.append(key)


def exit_code() -> int:
    """0 = everything this run succeeded or was banked; 2 = at least one
    section ERRORed (stage stays un-done; banked keys still skip)."""
    return 2 if _ERRORS else 0
