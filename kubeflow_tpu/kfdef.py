"""KfDef — the declarative platform installer (kfctl parity).

Reference parity (unverified cites, SURVEY.md §2.7 old-fork era):
`bootstrap/` ships kfctl, a CLI that materializes a whole Kubeflow
deployment from a KfDef manifest (an application list plus platform
config). The TPU rebuild keeps the capability: ONE YAML describes the
platform — capacity, which component families run, tenant profiles to
pre-create, extra manifests to apply — and `kubeflow_tpu platform -f
kfdef.yaml` brings it up (`platform init` scaffolds the file). Component
toggles work through the Platform's controller registry, so a disabled
application is absent from reconciliation AND /metrics, not merely idle.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path

from kubeflow_tpu.api.common import ObjectMeta

#: application name -> controller-registry keys it owns. "profiles" also
#: powers kfam authz; it stays on unless explicitly dropped.
APPLICATIONS: dict[str, tuple[str, ...]] = {
    "training": ("job", "autoscaler"),
    "katib": ("experiment",),
    "kserve": ("isvc",),
    "pipelines": ("pipelinerun",),
    "profiles": ("profile",),
    "devservers": ("tensorboard", "notebook", "pvcviewer"),
}


@dataclass
class KfDefServer:
    host: str = "127.0.0.1"
    port: int = 8080
    # serverless front door (serving/activator.py): 0 = pick a free
    # port; None/absent = no activator
    activator_port: int | None = None


@dataclass
class KfDefProfile:
    name: str = ""
    owner: str = ""
    chips: int | None = None
    max_jobs: int | None = None


@dataclass
class KfDefSpec:
    capacity_chips: int = 8
    controller_workers: int = 2
    log_dir: str = ".kubeflow_tpu/pod-logs"
    server: KfDefServer = field(default_factory=KfDefServer)
    # empty == all applications (kfctl default manifests posture)
    applications: list[str] = field(default_factory=list)
    profiles: list[KfDefProfile] = field(default_factory=list)
    # extra CR manifests (paths relative to the kfdef file) applied after
    # bring-up — the ksonnet-prototype analogue
    resources: list[str] = field(default_factory=list)


@dataclass
class KfDef:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: KfDefSpec = field(default_factory=KfDefSpec)
    kind: str = "KfDef"
    api_version: str = "kubeflow-tpu.org/v1"


def validate_kfdef(kfdef: KfDef) -> None:
    unknown = [a for a in kfdef.spec.applications if a not in APPLICATIONS]
    if unknown:
        raise ValueError(
            f"unknown application(s) {unknown} "
            f"(one of {sorted(APPLICATIONS)})")
    if kfdef.spec.capacity_chips <= 0:
        raise ValueError("capacityChips must be positive")
    if kfdef.spec.controller_workers <= 0:
        raise ValueError("controllerWorkers must be positive")
    for p in kfdef.spec.profiles:
        if not p.name:
            raise ValueError("every profile needs a name")
    if (kfdef.spec.profiles and kfdef.spec.applications
            and "profiles" not in kfdef.spec.applications):
        raise ValueError(
            "spec.profiles declared but the 'profiles' application is "
            "disabled — nothing would reconcile them")
    if (kfdef.spec.server.activator_port is not None
            and kfdef.spec.applications
            and "kserve" not in kfdef.spec.applications):
        raise ValueError(
            "server.activatorPort declared but the 'kserve' application "
            "is disabled — the front door could never activate anything")


def kfdef_from_dict(manifest: dict) -> KfDef:
    from kubeflow_tpu.api.serde import _from_dict

    body = {k: v for k, v in manifest.items()
            if k not in ("kind", "apiVersion")}
    kfdef = _from_dict(KfDef, body)
    validate_kfdef(kfdef)
    return kfdef


def load_kfdef(path: str | Path) -> KfDef:
    import yaml

    manifest = yaml.safe_load(Path(path).read_text())
    if not isinstance(manifest, dict) or manifest.get("kind") != "KfDef":
        raise ValueError(f"{path}: not a KfDef manifest")
    return kfdef_from_dict(manifest)


SCAFFOLD = """\
# kubeflow_tpu platform deployment (kfctl KfDef analogue).
# Bring it up:  python -m kubeflow_tpu platform -f kfdef.yaml
kind: KfDef
apiVersion: kubeflow-tpu.org/v1
metadata:
  name: kubeflow-tpu
spec:
  capacityChips: 8
  server:
    host: 127.0.0.1
    port: 8080
    # uncomment for the serverless front door (stable per-service URLs,
    # scale-from-zero request holding; requires the kserve application):
    # activatorPort: 8081
  # Component families to run (drop entries to slim the deployment;
  # omit the list entirely to run everything):
  applications:
    - training
    - katib
    - kserve
    - pipelines
    - profiles
    - devservers
  # Tenant namespaces created at bring-up (kfam owner bindings follow):
  profiles:
    - name: ml-team
      owner: owner@example.com
      chips: 4
  # Extra CR manifests applied after bring-up (paths relative to this file):
  resources: []
"""


def init_scaffold(directory: str | Path) -> Path:
    """`platform init` — write a commented kfdef.yaml scaffold."""
    d = Path(directory)
    d.mkdir(parents=True, exist_ok=True)
    path = d / "kfdef.yaml"
    if path.exists():
        raise FileExistsError(f"{path} already exists")
    path.write_text(SCAFFOLD)
    return path


def apply_kfdef(kfdef: KfDef, base_dir: str | Path = "."):
    """Materialize the deployment: a started Platform (with only the
    selected applications registered) plus its REST server. Returns
    (platform, server); the caller owns shutdown."""
    from kubeflow_tpu.apiserver import PlatformServer, _deserialize
    from kubeflow_tpu.client import Platform
    from kubeflow_tpu.controller.profile import (
        Profile,
        ProfileQuota,
        ProfileSpec,
    )

    spec = kfdef.spec
    platform = Platform(
        log_dir=spec.log_dir,
        capacity_chips=spec.capacity_chips,
        controller_workers=spec.controller_workers,
    )
    if spec.applications:
        keep = {key
                for app in spec.applications
                for key in APPLICATIONS[app]}
        for key in list(platform.controllers):
            if key not in keep:
                platform.controllers.pop(key)
    platform.start()
    server = None
    try:
        for p in spec.profiles:
            platform.cluster.create("profiles", Profile(
                metadata=ObjectMeta(name=p.name),
                spec=ProfileSpec(
                    owner=p.owner,
                    quota=ProfileQuota(chips=p.chips, max_jobs=p.max_jobs),
                ),
            ))
        import yaml

        for rel in spec.resources:
            rpath = Path(base_dir) / rel
            for doc in yaml.safe_load_all(rpath.read_text()):
                if not doc:
                    continue
                bucket, obj = _deserialize(doc)
                platform.cluster.create(bucket, obj)
        server = PlatformServer(
            platform, port=spec.server.port, host=spec.server.host,
        ).start()
        if spec.server.activator_port is not None:
            # same bind host as the API server it fronts — a 0.0.0.0
            # deployment must not hide the front door on loopback
            platform.start_activator(port=spec.server.activator_port,
                                     host=spec.server.host)
    except BaseException:
        if server is not None:
            server.stop()
        platform.stop()
        raise
    return platform, server
