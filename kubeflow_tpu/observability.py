"""Observability — Prometheus-text /metrics endpoint for the platform.

Reference parity (unverified cites, SURVEY.md §5.5): every operator exposes
a controller-runtime Prometheus endpoint (workqueue depth, reconcile
totals, custom counters). Here one endpoint aggregates all in-process
controllers, the object store, and the pod runtime.

Format is the Prometheus text exposition format, served by stdlib
http.server — scrape `GET /metrics`.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubeflow_tpu.utils.prom import Exposition, observe

#: preempt-to-resume histogram buckets (seconds): a resume rides a
#: diurnal trough, so the range runs sub-second (unit drills) to
#: minutes (a gang parked across a whole serving peak)
SCHED_RESUME_BUCKETS = (0.1, 0.5, 1.0, 2.0, 5.0, 10.0, 30.0, 60.0,
                        120.0, 300.0)


def render_metrics(platform) -> str:
    """Aggregate platform state into Prometheus text format."""
    # one builder, one HELP/TYPE declaration path (utils/prom.Exposition):
    # repeated TYPE lines for a family are exposition-format violations,
    # and multi-sample families below (per-kind gauges, per-controller
    # quantiles) would hand-roll that bug without the de-dup
    exp = Exposition()
    counter, gauge = exp.counter, exp.gauge

    worker_depths: list[tuple[str, list[int]]] = []
    for cname, ctrl in platform.controllers.items():
        for mname, v in sorted(ctrl.metrics.items()):
            counter(f"kftpu_{cname}_{mname}", v)
        gauge(
            f"kftpu_{cname}_workqueue_depth", len(ctrl.wq),
            help_="pending reconcile keys",
        )
        worker_depths.append((cname, ctrl.wq.depths()))
        # reconcile-duration histogram (controller-runtime parity):
        # cumulative le buckets + _sum/_count in exposition format
        counts, total = ctrl.latency_snapshot()
        exp.histogram(
            f"kftpu_{cname}_reconcile_duration_seconds",
            ctrl.latency_buckets, counts, total,
        )

    # keyed-pool shape (docs/architecture.md "Control-plane scaling"): one
    # depth sample per worker queue — a skewed profile means hot keys are
    # hashing onto one worker. Emitted AFTER the per-controller loop so
    # the family's samples form one contiguous exposition group.
    for cname, depths in worker_depths:
        for i, depth in enumerate(depths):
            gauge(
                "kftpu_cplane_worker_queue_depth", depth,
                help_="pending keys per keyed-pool worker queue",
                labels=f'{{controller="{cname}",worker="{i}"}}',
            )

    # control-plane scale-out signals (docs/architecture.md): shard-lock
    # contention on the sharded store, and the status-write coalescing
    # effectiveness of the kubelet layer's group commit
    counter(
        "kftpu_cplane_shard_lock_waits_total",
        sum(platform.cluster.lock_wait_counts().values()),
    )
    runtime_sb = getattr(getattr(platform, "pod_runtime", None),
                         "status_writes", None)
    if runtime_sb is not None:
        for mname, v in sorted(runtime_sb.metrics.items()):
            counter(f"kftpu_cplane_status_{mname}", v)

    # serving fleet (kubeflow_tpu/serving/fleet, docs/serving.md):
    # admission/shed/requeue accounting, queue+latency autoscaler signals,
    # and the prefix-reuse ledger, aggregated over every registered
    # router. Families render ZERO-valued on a fleetless platform so the
    # golden exposition pins a stable surface (KFTPU-METRIC contract).
    routers = list(getattr(platform, "fleet_routers", {}).values())
    snaps = [r.snapshot() for r in routers]

    def fleet_sum(field_):
        return sum(s.get(field_, 0) for s in snaps)

    for fam, field_, help_ in (
        ("kftpu_fleet_requests_admitted_total", "requests_admitted_total",
         "requests past the SLO admission gate"),
        ("kftpu_fleet_requests_shed_total", "requests_shed_total",
         "requests shed with 503 + Retry-After by admission control"),
        ("kftpu_fleet_requests_requeued_total", "requests_requeued_total",
         "in-flight requests requeued to a surviving replica"),
        ("kftpu_fleet_requeues_resumed_total", "requeues_resumed_total",
         "requeues that resumed from the surviving paged-KV chain"),
        ("kftpu_fleet_requeue_resumed_tokens_total",
         "requeue_resumed_tokens_total",
         "tokens salvaged from surviving KV chains instead of re-decoded"),
        ("kftpu_fleet_prefill_handoffs_total", "prefill_handoffs_total",
         "chains handed from the prefill tier to a decode replica"),
        ("kftpu_fleet_requests_completed_total", "requests_completed_total",
         None),
        ("kftpu_fleet_requests_failed_total", "requests_failed_total",
         None),
        ("kftpu_fleet_replica_kills_total", "replica_kills_total", None),
    ):
        counter(fam, fleet_sum(field_), help_=help_)
    prefill = reused = 0
    for r in routers:
        for rep in r.replicas:
            prefill += rep.engine.prefill_tokens_total
            reused += rep.engine.prefill_tokens_reused
    counter("kftpu_fleet_prefill_tokens_total", prefill,
            help_="prompt tokens the engines actually computed")
    counter("kftpu_fleet_prefill_tokens_reused_total", reused,
            help_="prompt tokens seeded from the paged-KV prefix pool")
    # paged-KV pool health (fleet/pagedkv.py): the pinned working set and
    # the eviction/COW churn, deduped across routers sharing one pool —
    # previously only the prefill reuse ledger was surfaced
    pools: dict[int, object] = {}
    for r in routers:
        for rep in r.replicas:
            p = getattr(rep.engine, "paged_kv", None)
            if p is not None:
                pools[id(p)] = p
    gauge("kftpu_fleet_kv_blocks_in_use",
          sum(p.blocks_in_use() for p in pools.values()),
          help_="paged-KV blocks pinned by live sequences (the "
                "block-budgeted admission working set)")
    counter("kftpu_fleet_kv_evictions_total",
            sum(p.metrics["blocks_evicted_total"] for p in pools.values()),
            help_="unreferenced paged-KV blocks evicted (LRU, leaf-first)")
    counter("kftpu_fleet_kv_cow_copies_total",
            sum(p.metrics["cow_copies_total"] for p in pools.values()),
            help_="copy-on-write block copies on shared-chain divergence")
    for fam, field_, help_ in (
        ("kftpu_fleet_queue_depth", "queue_depth",
         "queued + in-flight requests across live replicas"),
        ("kftpu_fleet_pending_tokens", "pending_tokens",
         "token backlog (queued prompts + in-flight budgets)"),
        ("kftpu_fleet_replicas_alive", "replicas_alive", None),
        ("kftpu_fleet_demand_replicas", "demand_replicas",
         "autoscaler demand signal from the queue/latency view"),
    ):
        gauge(fam, fleet_sum(field_), help_=help_)
    for q, field_ in (("0.5", "ttft_p50_s"), ("0.99", "ttft_p99_s")):
        gauge("kftpu_fleet_ttft_seconds",
              max((s.get(field_, 0.0) for s in snaps), default=0.0),
              help_="time-to-first-token quantiles over the fleet's "
                    "sample window",
              labels=f'{{quantile="{q}"}}')

    # fleet autoscaler (serving/fleet/scaler.py, docs/autoscaling.md):
    # the closed loop's decision ledger — scale events, graceful-drain
    # vs polite-kill outcomes, scale-to-zero/wake cycles, hang
    # detections — aggregated over every registered fleet's scaler and
    # ZERO-valued on a scalerless platform (KFTPU-METRIC contract)
    scalers = [s for s in (getattr(r, "scaler", None) for r in routers)
               if s is not None]

    def scaler_sum(field_):
        return sum(s.metrics.get(field_, 0) for s in scalers)

    for fam, field_, help_ in (
        ("kftpu_scaler_evaluations_total", "evaluations_total",
         "scaling-loop passes over the demand signal"),
        ("kftpu_scaler_frozen_evaluations_total",
         "frozen_evaluations_total",
         "passes that evaluated but acted on nothing (the "
         "scaler_freeze chaos mode)"),
        ("kftpu_scaler_scale_ups_total", "scale_ups_total", None),
        ("kftpu_scaler_scale_downs_total", "scale_downs_total", None),
        ("kftpu_scaler_replicas_added_total", "replicas_added_total",
         None),
        ("kftpu_scaler_replicas_removed_total",
         "replicas_removed_total", None),
        ("kftpu_scaler_drains_completed_total", "drains_completed_total",
         "scale-down drains that emptied gracefully"),
        ("kftpu_scaler_drain_kills_total", "drain_kills_total",
         "drains finished as a polite kill after the grace window "
         "(requests chain-resumed onto survivors)"),
        ("kftpu_scaler_hangs_detected_total", "hangs_detected_total",
         "replicas declared hung (work held, engine not advancing)"),
        ("kftpu_scaler_scale_to_zero_total", "scale_to_zero_total",
         None),
        ("kftpu_scaler_scale_from_zero_total", "scale_from_zero_total",
         "wake-on-arrival cold starts out of the scaled-to-zero state"),
    ):
        counter(fam, scaler_sum(field_), help_=help_)
    gauge("kftpu_scaler_target_replicas",
          sum(s.target_replicas for s in scalers),
          help_="the demand signal's last clamped target")
    gauge("kftpu_scaler_frozen",
          sum(1 for s in scalers if s.frozen),
          help_="scalers currently frozen (chaos mode)")
    gauge("kftpu_scaler_cold_start_seconds",
          max((s.cold_start_ewma_s for s in scalers), default=0.0),
          help_="EWMA of observed replica cold-start durations")

    # chip scheduler (kubeflow_tpu/scheduler, docs/scheduler.md): the
    # shared inventory BOTH workload classes claim through — the grant/
    # deny/preemption/quota decision counters, the free-chip view, the
    # per-tenant fair-share accounting, and the preempt-to-resume
    # latency histogram. One consistent snapshot (ChipScheduler holds
    # its mutex once), ZERO-valued on a schedulerless platform and with
    # the per-tenant families DECLARED even before any tenant has
    # claimed (KFTPU-METRIC contract: the golden pins a stable
    # surface).
    sched = getattr(platform, "chip_scheduler", None)
    sched_snap = sched.snapshot() if sched is not None else {}
    sched_counts = sched_snap.get("metrics", {})
    for fam, field_, help_ in (
        ("kftpu_sched_grants_total", "grants_total",
         "chip claims admitted (gangs and serving replicas alike)"),
        ("kftpu_sched_denies_total", "denies_total",
         "chip claims refused (frozen / quota / capacity) with a "
         "Retry-After hint and a traced sched.deny"),
        ("kftpu_sched_preemptions_total", "preemptions_total",
         "lower-priority gang claims evicted for a claim that could "
         "not otherwise fit (each emits a sched.preempt span)"),
        ("kftpu_sched_quota_borrows_total", "quota_borrows_total",
         "grants that ran a tenant past its fair-share entitlement "
         "on idle (reclaimable) chips"),
        ("kftpu_sched_quota_reclaims_total", "quota_reclaims_total",
         "preemptions that reclaimed borrowed chips for an "
         "under-entitlement tenant"),
        ("kftpu_sched_resumes_total", "resumes_total",
         "preempted gangs that re-claimed their chips (closes a "
         "preempt-to-resume latency sample)"),
        ("kftpu_sched_reclaimed_chips_total", "reclaimed_chips_total",
         "chips returned to the pool by releases and evictions"),
        ("kftpu_sched_double_count_avoided_chips_total",
         "double_count_avoided_chips_total",
         "pending-gang chips the combined demand_and_free snapshot "
         "kept out of demand because the ledger already holds them "
         "(the autoscaler paired-read race, counted)"),
    ):
        counter(fam, sched_counts.get(field_, 0), help_=help_)
    gauge("kftpu_sched_free_chips", sched_snap.get("free_chips", 0),
          help_="unclaimed chips in the shared ledger")
    gauge("kftpu_sched_used_chips", sched_snap.get("used_chips", 0))
    gauge("kftpu_sched_frozen",
          1 if sched_snap.get("frozen") else 0,
          help_="1 while the ledger refuses all claims (the "
                "sched_freeze chaos mode)")
    gauge("kftpu_sched_quota_enforced",
          1 if sched_snap.get("quota_enforced") else 0,
          help_="1 once set_shares armed fair-share tenant quotas")
    tenant_fams = (
        ("kftpu_sched_tenant_share", "share",
         "armed fair-share weight per tenant"),
        ("kftpu_sched_tenant_entitled_chips", "entitled_chips",
         "weighted max-min chip entitlement under the armed shares"),
        ("kftpu_sched_tenant_used_chips", "used_chips",
         "chips each tenant's claims currently hold"),
        ("kftpu_sched_tenant_borrowed_chips", "borrowed_chips",
         "held chips past the entitlement (reclaim-eligible)"),
    )
    for fam, _, help_ in tenant_fams:
        exp.declare(fam, "gauge", help_)
    # zero-valued-stable (the kftpu_slo_* pattern): an idle ledger still
    # exposes the two default claim tenants, so the families are pinned
    # in the golden exposition with samples, not just HELP/TYPE
    tenants = sched_snap.get("tenants", {}) or {
        t: {"share": 0.0, "entitled_chips": 0, "used_chips": 0,
            "borrowed_chips": 0}
        for t in ("default", "serving")
    }
    for t, info in sorted(tenants.items()):
        for fam, field_, _ in tenant_fams:
            gauge(fam, info[field_], labels=f'{{tenant="{t}"}}')
    # preempt-to-resume: eviction to re-grant wall time — the latency a
    # batch gang actually waited for serving to hand the chips back
    resume_counts = [0] * (len(SCHED_RESUME_BUCKETS) + 1)
    resume_total = 0.0
    for s in sched_snap.get("preempt_to_resume_s", ()):
        observe(SCHED_RESUME_BUCKETS, resume_counts, s)
        resume_total += s
    exp.histogram(
        "kftpu_sched_preempt_to_resume_seconds", SCHED_RESUME_BUCKETS,
        resume_counts, resume_total,
        help_="preempted-gang eviction-to-resume wall time")

    # pod-backed serving replicas (serving/fleet/podclient.py): the
    # cross-process tier's lifecycle and wire-health ledger — spawns,
    # kills (graceful and SIGKILL alike), retried/reset wire ops,
    # deadline rejections, and the KV-handoff volume crossing the
    # process boundary. Module-global like the ckpt-verify counters
    # (pods outlive any one router) and ZERO-valued with no pod tier
    # (KFTPU-METRIC contract).
    from kubeflow_tpu.serving.fleet.podclient import (
        pod_heartbeat_age_max_s,
        pod_metrics_snapshot,
    )

    pod_help = {
        "spawns_total": "pod worker processes launched (spawn_pod)",
        "kills_total": "pod workers terminated — graceful kills, wire "
                       "deaths, and real SIGKILLs alike",
        "wire_retries_total": "pod wire ops retried under the backoff "
                              "policy (resets, torn frames, 503 "
                              "backpressure)",
        "wire_retries_exhausted_total": "pod wire calls that exhausted "
                                        "the retry policy — the give-up "
                                        "that escalates to pod death, "
                                        "visible here instead of only "
                                        "as an unexplained kill",
        "wire_resets_total": "pod wire connections torn down by fault "
                             "injection (chaos WireFault)",
        "net_reconnects_total": "pod wire redials AFTER an established "
                                "connection — each one exercised the "
                                "rid-dedup + cumulative-ack replay "
                                "contract",
        "net_fenced_frames_total": "frames refused by the epoch fence, "
                                   "both directions: worker 410s to "
                                   "stale clients and client refusals "
                                   "of a fenced pod's late acks/tokens",
        "net_duplicate_acks_refused_total": "redelivered outbox events "
                                            "dropped by the cumulative-"
                                            "ack id filter (lost acks, "
                                            "replayed ticks) — never "
                                            "double-pushed",
        "net_partitions_injected_total": "network partitions opened "
                                         "against pod hosts (chaos "
                                         "NetFault windows and drill-"
                                         "driven set_partitioned)",
        "deadline_rejects_total": "pod calls refused 504 — the "
                                  "propagated deadline was spent on "
                                  "arrival",
        "handoff_bytes_total": "serialized paged-KV chain bytes that "
                               "crossed a pod process boundary",
    }
    for mname, v in sorted(pod_metrics_snapshot().items()):
        counter(f"kftpu_pod_{mname}", v, help_=pod_help.get(mname))
    gauge("kftpu_pod_heartbeat_age_seconds", pod_heartbeat_age_max_s(),
          help_="oldest live pod worker heartbeat age (the hang "
                "watch's SIGSTOP signal); 0 with no live pods")

    # protocol model checker (kubeflow_tpu/analysis/protocheck,
    # docs/analysis.md "Protocol model checking"): sweep accounting —
    # nonzero only after `make modelcheck` / run_modelcheck() ran in
    # this process
    from kubeflow_tpu.analysis.protocheck import protocheck_metrics_snapshot
    protocheck_help = {
        "models_checked_total": "protocol models swept by the "
                                "bounded-exhaustive explorer "
                                "(wire/kv/ledger x runs)",
        "states_explored_total": "distinct protocol states visited "
                                 "across all modelcheck sweeps",
        "violations_total": "invariant violations found (0 at HEAD; "
                            "nonzero means a counterexample schedule "
                            "was rendered)",
    }
    for mname, v in sorted(protocheck_metrics_snapshot().items()):
        counter(f"kftpu_protocheck_{mname}", v,
                help_=protocheck_help.get(mname))

    # SLO burn-rate monitor (kubeflow_tpu/monitoring, docs/slo.md):
    # evaluation/alert counters, per-objective burn-rate and alert
    # gauges, and the TSDB's volume/loss accounting. A platform without
    # start_slo() renders the DEFAULT objective set zero-valued so the
    # golden exposition pins a stable surface (KFTPU-METRIC contract).
    from kubeflow_tpu.monitoring import SLOMonitor, default_slos

    monitor = getattr(platform, "slo_monitor", None)
    if monitor is not None:
        slo_states = monitor.describe()
        slo_counts = monitor.metrics
        tsdb_stats = monitor.tsdb.stats()
    else:
        slo_states = [
            {"name": c.name, "fired": False,
             "burn_rates": {SLOMonitor._wkey(w): 0.0
                            for w, _ in c.windows}}
            for c in default_slos()
        ]
        slo_counts = {"evaluations_total": 0, "alerts_fired_total": 0}
        tsdb_stats = {"series": 0, "samples_total": 0,
                      "samples_dropped_total": 0,
                      "series_rejected_total": 0}
    counter("kftpu_slo_evaluations_total",
            slo_counts["evaluations_total"],
            help_="SLO monitor evaluation passes")
    counter("kftpu_slo_alerts_fired_total",
            slo_counts["alerts_fired_total"],
            help_="alerts fired across evaluations (docs/slo.md)")
    counter("kftpu_slo_samples_total", tsdb_stats["samples_total"],
            help_="samples recorded into the monitoring TSDB")
    counter("kftpu_slo_samples_dropped_total",
            tsdb_stats["samples_dropped_total"],
            help_="samples evicted from full series rings (raise "
                  "KFTPU_SLO_CAPACITY)")
    counter("kftpu_slo_series_rejected_total",
            tsdb_stats["series_rejected_total"],
            help_="new series refused past the bounded series set")
    gauge("kftpu_slo_series", tsdb_stats["series"],
          help_="live series in the monitoring TSDB")
    for st in slo_states:
        gauge("kftpu_slo_alert_active", 1 if st["fired"] else 0,
              help_="1 while the objective's multi-window burn alert "
                    "fires",
              labels=f'{{slo="{st["name"]}"}}')
    for st in slo_states:
        for wkey in sorted(st["burn_rates"], key=float, reverse=True):
            gauge("kftpu_slo_burn_rate", st["burn_rates"][wkey],
                  help_="error-budget burn rate per objective window "
                        "(1.0 = burning exactly the budget)",
                  labels=f'{{slo="{st["name"]}",window_s="{wkey}"}}')

    # training hot path (utils/compile_cache.py + train/data.AsyncLoader,
    # docs/perf.md "MFU hunt"): restart-warm compile reuse and the async
    # host-loader ledger. Both registries are process-global — trainers
    # are constructed ad hoc by jobs, drills, and benches — and families
    # render ZERO-valued on an idle platform so the golden exposition
    # pins a stable surface (KFTPU-METRIC contract).
    from kubeflow_tpu.train.data import loader_metrics_snapshot
    from kubeflow_tpu.utils.compile_cache import compile_metrics_snapshot

    for mname, v in sorted(compile_metrics_snapshot().items()):
        counter(f"kftpu_train_compile_{mname}", v)
    loader_snap = loader_metrics_snapshot()
    live_loaders = loader_snap.pop("live_loaders")
    for mname, v in sorted(loader_snap.items()):
        counter(f"kftpu_train_loader_{mname}",
                v if isinstance(v, int) else f"{v:.6f}")
    gauge(
        "kftpu_train_loader_live", live_loaders,
        help_="AsyncLoader producer threads still running "
              "(a wedged loader thread shows here)",
    )
    # gradient-communication ledger (parallel/partitioner.py, docs/
    # partitioner.md "Overlap mechanics"): host-visible comm time left ON
    # the step critical path, and the latest overlapped/serialized
    # step-time ratio the grad_overlap machinery measured. Process-global
    # and zero-valued when idle, like the loader/compile families above.
    from kubeflow_tpu.parallel.partitioner import comm_metrics_snapshot

    comm_snap = comm_metrics_snapshot()
    counter("kftpu_train_comm_seconds_total",
            f"{comm_snap['comm_seconds_total']:.6f}",
            help_="gradient-collective wall time charged to step "
                  "critical paths (train.comm spans)")
    counter("kftpu_train_comm_overlap_measurements_total",
            comm_snap["overlap_measurements_total"])
    gauge(
        "kftpu_train_overlap_ratio", comm_snap["overlap_ratio"],
        help_="latest overlapped/serialized step-time ratio from the "
              "grad_overlap measurement (lower is better; 0 = none yet)",
    )

    # liveness layer (kubeflow_tpu/health.py): lease expiries and straggler
    # declarations counted apart from crash deaths, plus per-incarnation
    # heartbeat age straight from the kubelet layer's side table
    liveness = getattr(getattr(platform, "controller", None), "liveness", None)
    if liveness is not None:
        for mname, v in sorted(liveness.metrics.items()):
            counter(f"kftpu_health_{mname}", v)
    runtime = getattr(platform, "pod_runtime", None)
    if runtime is not None:
        ages = runtime.heartbeat_ages()
        for (key, uid), age in sorted(ages.items()):
            gauge("kftpu_health_heartbeat_age_seconds", f"{age:.3f}",
                  labels=f'{{pod="{key}",uid="{uid}"}}')

    # checkpoint integrity verification (train/checkpoint.py): the registry
    # is process-global — checkpointers are constructed ad hoc by trainers,
    # drills, and pipelines, and all of them report here
    from kubeflow_tpu.health import ckpt_verify_snapshot

    for mname, v in sorted(ckpt_verify_snapshot().items()):
        counter(f"kftpu_ckpt_verify_{mname}", v)

    # chaos-drill injection counters (kubeflow_tpu/chaos.py): exported so
    # recovery behavior is measurable against what was actually injected
    chaos = getattr(platform, "chaos", None)
    if chaos is not None:
        for mname, v in sorted(chaos.metrics.items()):
            counter(f"kftpu_chaos_{mname}", v)
        gauge(
            "kftpu_chaos_plan_seed", chaos.plan.seed,
            help_="seed of the armed fault plan (reproduce with this)",
        )

    # span tracing (kubeflow_tpu/tracing): volume + loss accounting for the
    # flight recorder, so a ring sized too small for the span rate is
    # visible as kftpu_trace_spans_dropped_total
    tracer = getattr(platform, "tracer", None)
    if tracer is not None and tracer.recorder is not None:
        for mname, v in sorted(tracer.metrics.items()):
            counter(f"kftpu_trace_{mname}", v)
        gauge(
            "kftpu_trace_recorder_spans", len(tracer.recorder),
            help_="completed spans currently held in the flight recorder",
        )
        gauge(
            "kftpu_trace_recorder_capacity", tracer.recorder.capacity,
            help_="flight recorder ring size",
        )

        # profiling analytics (kubeflow_tpu/profiling, docs/profiling.md):
        # the same breakdown /debug/profile and `kftpu profile` serve,
        # derived from the recorder snapshot (+ worker flushes in
        # trace_dir) at scrape time — scrapers get step-time histograms
        # and goodput without a second instrumentation path
        from kubeflow_tpu.profiling import (
            PROF_BUCKETS,
            REQUEST_PHASES,
            control_plane_stats,
            goodput as prof_goodput,
            platform_spans,
            request_breakdown,
            step_breakdown,
        )

        spans, _dropped = platform_spans(platform)
        steps = step_breakdown(spans)
        # serving request breakdown (the step-breakdown analogue over
        # `request` root spans — profiling/analytics.request_breakdown):
        # per-request wall histogram + sum-exact phase totals, the same
        # numbers /debug/slo and the `slo` CLI serve (docs/slo.md)
        reqs = request_breakdown(spans)
        req_counts = [0] * (len(PROF_BUCKETS) + 1)
        req_total = 0.0
        for rq in reqs:
            observe(PROF_BUCKETS, req_counts, rq["wall"])
            req_total += rq["wall"]
        exp.histogram(
            "kftpu_request_wall_seconds", PROF_BUCKETS, req_counts,
            req_total,
            help_="serving request wall time (submit to done, requeues "
                  "included) from request root spans")
        for phase in REQUEST_PHASES:
            counter(
                "kftpu_request_phase_seconds_total",
                f"{sum(rq[phase] for rq in reqs):.6f}",
                help_="per-phase serving request time; phases sum "
                      "exactly to request wall (docs/slo.md)",
                labels=f'{{phase="{phase}"}}')
        counter("kftpu_request_requeues_total",
                sum(max(rq["attempts"] - 1, 0) for rq in reqs),
                help_="extra dispatch attempts across traced requests "
                      "(the replica-kill requeue chain)")
        for fam, phase, help_ in (
            ("kftpu_prof_step_time_seconds", "wall",
             "per-step cycle wall time (end of previous step to end of "
             "this one)"),
            ("kftpu_prof_data_load_seconds", "data_load",
             "host-side input fetch time charged to each step cycle"),
            ("kftpu_prof_stall_seconds", "stall",
             "per-step unattributed remainder (wall - accounted phases)"),
        ):
            counts = [0] * (len(PROF_BUCKETS) + 1)
            total = 0.0
            for st in steps:
                observe(PROF_BUCKETS, counts, st[phase])
                total += st[phase]
            exp.histogram(fam, PROF_BUCKETS, counts, total, help_=help_)
        gauge(
            "kftpu_prof_goodput_ratio",
            prof_goodput(spans, steps)["goodput"],
            help_="productive step time over the trace window "
                  "(docs/profiling.md)",
        )
        # stable label set: every registered controller gets its quantile
        # samples (0 until reconcile spans exist), so dashboards and the
        # golden pin see the same series on a fresh and a busy platform
        rec_stats = control_plane_stats(spans)["reconcile"]
        for ctrl in sorted(set(platform.controllers) | set(rec_stats)):
            st = rec_stats.get(ctrl)
            for q, key in (("0.5", "p50_s"), ("0.99", "p99_s")):
                gauge(
                    "kftpu_prof_reconcile_latency_seconds",
                    st[key] if st else 0.0,
                    help_="reconcile-duration quantiles per controller, "
                          "derived from reconcile spans",
                    labels=f'{{controller="{ctrl}",quantile="{q}"}}',
                )

    cluster = platform.cluster
    for kind in cluster.KINDS:
        gauge("kftpu_objects", len(cluster.list(kind)),
              labels=f'{{kind="{kind}"}}')
    gauge("kftpu_events_total", len(cluster.events))
    gauge(
        "kftpu_capacity_chips", cluster.capacity_chips,
        help_="schedulable chips in the gang scheduler",
    )
    return exp.text()


class MetricsServer:
    """GET /metrics and GET /healthz on a local port."""

    def __init__(self, platform, port: int = 0, host: str = "127.0.0.1"):
        self.platform = platform
        self.host = host
        self.port = port
        self._httpd: ThreadingHTTPServer | None = None

    def start(self) -> "MetricsServer":
        plat = self.platform

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass  # metrics scrapes are not worth log noise

            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path == "/metrics":
                    body = render_metrics(plat).encode()
                    ctype = "text/plain; version=0.0.4"
                elif self.path == "/healthz":
                    body, ctype = b"ok\n", "text/plain"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
