"""Observability — Prometheus-text /metrics endpoint for the platform.

Reference parity (unverified cites, SURVEY.md §5.5): every operator exposes
a controller-runtime Prometheus endpoint (workqueue depth, reconcile
totals, custom counters). Here one endpoint aggregates all in-process
controllers, the object store, and the pod runtime.

Format is the Prometheus text exposition format, served by stdlib
http.server — scrape `GET /metrics`.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def render_metrics(platform) -> str:
    """Aggregate platform state into Prometheus text format."""
    lines: list[str] = []

    def counter(name: str, value, help_: str = "") -> None:
        if help_:
            lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} counter")
        lines.append(f"{name} {value}")

    def gauge(name: str, value, help_: str = "", labels: str = "") -> None:
        if help_:
            lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name}{labels} {value}")

    for cname, ctrl in platform.controllers.items():
        for mname, v in sorted(ctrl.metrics.items()):
            counter(f"kftpu_{cname}_{mname}", v)
        gauge(
            f"kftpu_{cname}_workqueue_depth", len(ctrl.wq),
            help_="pending reconcile keys",
        )
        # reconcile-duration histogram (controller-runtime parity):
        # cumulative le buckets + _sum/_count in exposition format
        from kubeflow_tpu.utils.prom import render_histogram

        counts, total = ctrl.latency_snapshot()
        render_histogram(
            lines, f"kftpu_{cname}_reconcile_duration_seconds",
            ctrl.latency_buckets, counts, total,
        )

    # liveness layer (kubeflow_tpu/health.py): lease expiries and straggler
    # declarations counted apart from crash deaths, plus per-incarnation
    # heartbeat age straight from the kubelet layer's side table
    liveness = getattr(getattr(platform, "controller", None), "liveness", None)
    if liveness is not None:
        for mname, v in sorted(liveness.metrics.items()):
            counter(f"kftpu_health_{mname}", v)
    runtime = getattr(platform, "pod_runtime", None)
    if runtime is not None:
        ages = runtime.heartbeat_ages()
        if ages:
            lines.append("# TYPE kftpu_health_heartbeat_age_seconds gauge")
            for (key, uid), age in sorted(ages.items()):
                lines.append(
                    f'kftpu_health_heartbeat_age_seconds'
                    f'{{pod="{key}",uid="{uid}"}} {age:.3f}'
                )

    # checkpoint integrity verification (train/checkpoint.py): the registry
    # is process-global — checkpointers are constructed ad hoc by trainers,
    # drills, and pipelines, and all of them report here
    from kubeflow_tpu.health import ckpt_verify_snapshot

    for mname, v in sorted(ckpt_verify_snapshot().items()):
        counter(f"kftpu_ckpt_verify_{mname}", v)

    # chaos-drill injection counters (kubeflow_tpu/chaos.py): exported so
    # recovery behavior is measurable against what was actually injected
    chaos = getattr(platform, "chaos", None)
    if chaos is not None:
        for mname, v in sorted(chaos.metrics.items()):
            counter(f"kftpu_chaos_{mname}", v)
        gauge(
            "kftpu_chaos_plan_seed", chaos.plan.seed,
            help_="seed of the armed fault plan (reproduce with this)",
        )

    # span tracing (kubeflow_tpu/tracing): volume + loss accounting for the
    # flight recorder, so a ring sized too small for the span rate is
    # visible as kftpu_trace_spans_dropped_total
    tracer = getattr(platform, "tracer", None)
    if tracer is not None and tracer.recorder is not None:
        for mname, v in sorted(tracer.metrics.items()):
            counter(f"kftpu_trace_{mname}", v)
        gauge(
            "kftpu_trace_recorder_spans", len(tracer.recorder),
            help_="completed spans currently held in the flight recorder",
        )
        gauge(
            "kftpu_trace_recorder_capacity", tracer.recorder.capacity,
            help_="flight recorder ring size",
        )

    cluster = platform.cluster
    # one TYPE line, then one sample per label — repeated TYPE lines for the
    # same metric are invalid exposition format and fail real scrapes
    lines.append("# TYPE kftpu_objects gauge")
    for kind in cluster.KINDS:
        lines.append(f'kftpu_objects{{kind="{kind}"}} {len(cluster.list(kind))}')
    gauge("kftpu_events_total", len(cluster.events))
    gauge(
        "kftpu_capacity_chips", cluster.capacity_chips,
        help_="schedulable chips in the gang scheduler",
    )
    return "\n".join(lines) + "\n"


class MetricsServer:
    """GET /metrics and GET /healthz on a local port."""

    def __init__(self, platform, port: int = 0, host: str = "127.0.0.1"):
        self.platform = platform
        self.host = host
        self.port = port
        self._httpd: ThreadingHTTPServer | None = None

    def start(self) -> "MetricsServer":
        plat = self.platform

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass  # metrics scrapes are not worth log noise

            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path == "/metrics":
                    body = render_metrics(plat).encode()
                    ctype = "text/plain; version=0.0.4"
                elif self.path == "/healthz":
                    body, ctype = b"ok\n", "text/plain"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
