"""Lineage queries over the C++ metadata store (the MLMD read side).

Reference parity (unverified cites, SURVEY.md §2.6/§3.4): KFP's runs UI
walks MLMD to show each step's execution with its input/output artifacts.
The write side lives in pipelines/runner.py#_record_lineage; this module
is the query: one run's executions, artifacts, and typed edges as a JSON
graph, served at GET /api/v1/pipelineruns/{ns}/{name}/lineage.
"""

from __future__ import annotations

import json


def run_lineage(ms, run_id: str) -> dict:
    """The lineage graph of one pipeline run.

    Returns {"executions": [...], "artifacts": [...], "edges": [...]}
    with edges {"execution", "artifact", "direction": "input"|"output"}.
    Names are namespaced '<run_id>/<task>[/in|/out/<name>]' by the
    recorder, so a simple prefix filter scopes the run.
    """
    prefix = f"{run_id}/"
    # type filters keep the scan bounded to lineage rows even as the
    # durable store accrues platform history
    execs = [e for e in ms.list_executions("pipeline_task")
             if e.get("name", "").startswith(prefix)]
    for e in execs:
        e["id"] = int(e["id"])  # the C++ store serializes ids as strings
    arts = {}
    for atype in ("parameter", "file"):
        for a in ms.list_artifacts(atype):
            if a.get("name", "").startswith(prefix):
                a["id"] = int(a["id"])
                arts[a["id"]] = a
    edges = []
    for e in execs:
        for ev in ms.events(execution_id=e["id"]):
            aid = int(ev["artifact_id"])
            if aid not in arts:
                continue
            edges.append({
                "execution": e["id"],
                "artifact": aid,
                "direction":
                    "input" if int(ev["direction"]) == 0 else "output",
            })

    def slim(obj: dict) -> dict:
        out = {k: obj[k] for k in ("id", "type", "name", "state", "uri")
               if obj.get(k) not in (None, "")}
        props = obj.get("props")
        if props:
            try:
                out["props"] = json.loads(props)
            except (TypeError, ValueError):
                out["props"] = props
        return out

    return {
        "runId": run_id,
        "executions": [slim(e) for e in execs],
        "artifacts": [slim(a) for a in arts.values()],
        "edges": edges,
    }
