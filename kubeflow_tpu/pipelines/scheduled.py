"""Recurring runs — ScheduledWorkflow analogue.

Reference parity (unverified cites, SURVEY.md §2.6): pipelines
backend/src/crd/controller/scheduledworkflow — cron/interval-triggered
pipeline runs with run history and concurrency control. Interval-based
here (the cron-expression surface collapses to a period), driven by a
daemon thread per schedule.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from kubeflow_tpu.pipelines.runner import LocalPipelineRunner, PipelineRun


@dataclass
class RecurringRun:
    name: str
    ir: dict
    arguments: dict
    interval_s: float
    max_runs: int | None = None       # None = until stop()
    enabled: bool = True
    history: list[PipelineRun] = field(default_factory=lambda: [])


class ScheduleManager:
    def __init__(self, runner: LocalPipelineRunner):
        self.runner = runner
        self._schedules: dict[str, RecurringRun] = {}
        self._threads: dict[str, threading.Thread] = {}
        self._stop_flags: dict[str, threading.Event] = {}

    def create(
        self,
        name: str,
        ir: dict,
        arguments: dict | None = None,
        interval_s: float = 60.0,
        max_runs: int | None = None,
    ) -> RecurringRun:
        if name in self._schedules:
            raise KeyError(f"schedule {name!r} already exists")
        rr = RecurringRun(
            name=name, ir=ir, arguments=arguments or {},
            interval_s=interval_s, max_runs=max_runs,
        )
        self._schedules[name] = rr
        stop = threading.Event()
        self._stop_flags[name] = stop
        t = threading.Thread(
            target=self._loop, args=(rr, stop), name=f"sched-{name}", daemon=True
        )
        self._threads[name] = t
        t.start()
        return rr

    def _loop(self, rr: RecurringRun, stop: threading.Event) -> None:
        while not stop.wait(rr.interval_s):
            if not rr.enabled:
                continue
            run = self.runner.run(rr.ir, rr.arguments)
            rr.history.append(run)
            if rr.max_runs is not None and len(rr.history) >= rr.max_runs:
                return

    def get(self, name: str) -> RecurringRun | None:
        return self._schedules.get(name)

    def pause(self, name: str) -> None:
        self._schedules[name].enabled = False

    def resume(self, name: str) -> None:
        self._schedules[name].enabled = True

    def delete(self, name: str) -> None:
        if name in self._stop_flags:
            self._stop_flags.pop(name).set()
        self._schedules.pop(name, None)
        self._threads.pop(name, None)

    def stop_all(self) -> None:
        for name in list(self._schedules):
            self.delete(name)
