"""LocalPipelineRunner — executes compiled IR with caching + lineage.

Reference parity (unverified cites, SURVEY.md §2.6, §3.4): the KFP backend
path collapsed to one host — apiserver translate (here: IR validation),
Argo DAG engine (topological executor), the v2 driver/launcher pair (per-
step subprocess that resolves inputs, runs the user function, uploads
outputs), step-result caching keyed by component+args fingerprint
(backend/src/cache), and MLMD lineage recording (artifacts/executions/
events) into the native C++ metadata store.
"""

from __future__ import annotations

import enum
import hashlib
import operator
import json
import os
import re
import shutil
import subprocess
import sys
import textwrap
import time
import uuid
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

from kubeflow_tpu.analysis.lockcheck import make_lock
from kubeflow_tpu.native import MetadataStore
from kubeflow_tpu.pipelines.compiler import validate_ir


class TaskState(str, enum.Enum):
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    CACHED = "Cached"
    FAILED = "Failed"
    SKIPPED = "Skipped"


@dataclass
class TaskResult:
    state: TaskState = TaskState.PENDING
    output: Any = None
    # named OutputPath artifacts: artifact name -> filesystem path
    artifacts: dict[str, str] = field(default_factory=dict)
    error: str = ""
    fingerprint: str = ""
    duration_s: float = 0.0


@dataclass
class PipelineRun:
    run_id: str
    pipeline_name: str
    arguments: dict[str, Any]
    tasks: dict[str, TaskResult] = field(default_factory=dict)
    state: TaskState = TaskState.PENDING
    output: Any = None

    @property
    def succeeded(self) -> bool:
        return self.state in (TaskState.SUCCEEDED, TaskState.CACHED)


class LocalPipelineRunner:
    def __init__(
        self,
        work_dir: str = ".kubeflow_tpu/pipelines",
        metadata_store: MetadataStore | None = None,
        cache: bool = True,
        platform=None,
        max_parallel: int = 4,
    ):
        # platform enables trainJob steps (pipeline -> TrainJob recursion);
        # python-function steps never need it
        self.platform = platform
        # independent DAG branches run concurrently up to this width
        self.max_parallel = max(1, max_parallel)
        self.work_dir = Path(work_dir)
        self.cache_dir = self.work_dir / "cache"
        self.cache_enabled = cache
        self.ms = metadata_store
        # run() is called from multiple schedule threads (ScheduleManager):
        # the id sequence must be atomic or run dirs/lineage keys collide
        self._seq_lock = make_lock("runner.LocalPipelineRunner._seq_lock")
        self._run_seq = 0

    # ----------------------------------------------------------------- run

    def run(self, ir: dict, arguments: dict[str, Any] | None = None) -> PipelineRun:
        validate_ir(ir)
        with self._seq_lock:
            self._run_seq += 1
            seq = self._run_seq
        # uuid suffix: seq resets with every runner instance and the
        # timestamp is second-granular, so two controllers (or two CRs in
        # the same second) would otherwise collide — and colliding run_ids
        # MERGE lineage graphs in the shared durable MLMD store
        run_id = (f"{ir['pipelineInfo']['name']}-{seq:04d}-"
                  f"{int(time.time())}-{uuid.uuid4().hex[:6]}")
        run_dir = self.work_dir / "runs" / run_id
        run_dir.mkdir(parents=True, exist_ok=True)

        params = dict(ir["root"]["inputDefinitions"].get("parameters", {}))
        args = {
            name: (arguments or {}).get(name, spec.get("defaultValue"))
            for name, spec in params.items()
        }
        missing = [k for k, v in args.items() if v is None]
        if missing:
            raise ValueError(f"missing pipeline arguments: {missing}")

        run = PipelineRun(run_id=run_id, pipeline_name=ir["pipelineInfo"]["name"],
                          arguments=args)
        tasks = ir["root"]["dag"]["tasks"]
        for t in tasks:
            run.tasks[t] = TaskResult()

        run_exec_id = None
        if self.ms is not None:
            run_exec_id = self.ms.put_execution(
                "pipeline_run", run_id, state="RUNNING",
                props=json.dumps({"pipeline": run.pipeline_name}),
            )

        order = self._topo_order(tasks)
        # exit handlers run LAST regardless of upstream verdicts (kfp
        # ExitHandler semantics); everything else runs through the parallel
        # DAG executor (independent branches concurrently, like Argo)
        regular = [t for t in order if not tasks[t].get("exitHandler")]
        handlers = [t for t in order if tasks[t].get("exitHandler")]
        self._execute_dag(ir, run, run_dir, tasks, regular, run_exec_id)
        for tname in handlers:
            spec = tasks[tname]
            if not self._conditions_hold(run, spec):
                run.tasks[tname].state = TaskState.SKIPPED
                continue
            self._run_task(ir, run, run_dir, tname, spec, run_exec_id)
            if run.tasks[tname].state == TaskState.FAILED:
                run.state = TaskState.FAILED

        if run.state != TaskState.FAILED:
            run.state = TaskState.SUCCEEDED
            out_from = ir["root"].get("outputFrom")
            if out_from:
                run.output = self._resolve_value(run, {
                    "taskOutputParameter": {
                        "producerTask": out_from["producerTask"],
                        "outputParameterKey": out_from.get(
                            "outputParameterKey", "Output"
                        ),
                    }
                })
        if self.ms is not None and run_exec_id is not None:
            self.ms.put_execution(
                "pipeline_run", run_id,
                state="COMPLETE" if run.succeeded else "FAILED",
                props=json.dumps({"pipeline": run.pipeline_name}),
                id=run_exec_id,
            )
        (run_dir / "result.json").write_text(json.dumps(
            {
                "run_id": run_id,
                "state": run.state.value,
                "tasks": {t: r.state.value for t, r in run.tasks.items()},
            },
            indent=2,
        ))
        return run

    # --------------------------------------------------------------- helpers

    def _execute_dag(self, ir, run, run_dir, tasks, names, run_exec_id) -> None:
        """Dependency-driven parallel execution (Argo/KFP semantics):
        a task launches the moment every dependency SUCCEEDED; any
        failed/skipped dependency cascades a skip; independent branches run
        concurrently up to max_parallel (each step is its own subprocess,
        so the pool parallelizes real work, not bytecode). A failure stops
        dependents only — independent branches still complete, matching
        the serial executor's semantics."""
        from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor
        from concurrent.futures import wait as _fwait

        remaining = list(names)
        futures: dict = {}
        with ThreadPoolExecutor(max_workers=self.max_parallel) as ex:
            while remaining or futures:
                progressed = True
                while progressed:
                    progressed = False
                    for tname in list(remaining):
                        spec = tasks[tname]
                        states = [
                            run.tasks[d].state for d in self._deps_of(spec)
                        ]
                        if any(s in (TaskState.FAILED, TaskState.SKIPPED)
                               for s in states):
                            run.tasks[tname].state = TaskState.SKIPPED
                            remaining.remove(tname)
                            progressed = True
                        elif all(s in (TaskState.SUCCEEDED, TaskState.CACHED)
                                 for s in states):
                            if not self._conditions_hold(run, spec):
                                run.tasks[tname].state = TaskState.SKIPPED
                            else:
                                futures[ex.submit(
                                    self._run_task, ir, run, run_dir,
                                    tname, spec, run_exec_id,
                                )] = tname
                            remaining.remove(tname)
                            progressed = True
                if not futures:
                    if remaining:  # acyclic per validate_ir; belt-and-braces
                        raise RuntimeError(
                            f"pipeline deadlock: unrunnable tasks {remaining}"
                        )
                    break
                done, _ = _fwait(futures, return_when=FIRST_COMPLETED)
                for f in done:
                    tname = futures.pop(f)
                    f.result()  # surface unexpected executor exceptions
                    if run.tasks[tname].state == TaskState.FAILED:
                        run.state = TaskState.FAILED

    @staticmethod
    def _deps_of(spec: dict) -> set[str]:
        deps = set(spec.get("dependentTasks", []))
        refs = list(spec.get("inputs", {}).get("parameters", {}).values())
        for cond in spec.get("when", []):
            # BOTH sides: validate_ir's all_deps and the DSL include rhs
            # producers too; hand-authored IR must topo-order (and
            # skip-cascade) against them identically (ADVICE r2)
            refs.append(cond.get("lhs", {}))
            refs.append(cond.get("rhs", {}))
        it = spec.get("iterator")
        if it is not None:
            refs.append(it.get("items", {}))
        for v in refs:
            if "taskOutputParameter" in v:
                deps.add(v["taskOutputParameter"]["producerTask"])
        return deps

    def _resolve_value(self, run: PipelineRun, ref: dict) -> Any:
        if "runtimeValue" in ref:
            return ref["runtimeValue"]["constant"]
        if "componentInputParameter" in ref:
            return run.arguments[ref["componentInputParameter"]]
        if "taskOutputParameter" in ref:
            # a producer that never ran (exit-handler path) resolves to None
            t = run.tasks[ref["taskOutputParameter"]["producerTask"]]
            key = ref["taskOutputParameter"].get("outputParameterKey", "Output")
            if key == "Output":
                return t.output
            return t.artifacts.get(key)  # named OutputPath artifact -> path
        raise ValueError(f"unresolvable value ref {ref!r}")

    _CMP = {
        "==": operator.eq, "!=": operator.ne, "<": operator.lt,
        "<=": operator.le, ">": operator.gt, ">=": operator.ge,
    }

    def _conditions_hold(self, run: PipelineRun, spec: dict) -> bool:
        for cond in spec.get("when", []):
            lhs = self._resolve_value(run, cond["lhs"])
            rhs = self._resolve_value(run, cond["rhs"])
            try:
                if not self._CMP[cond["op"]](lhs, rhs):
                    return False
            except TypeError:
                # incomparable types (e.g. None from a skipped producer)
                return False
        return True

    def _topo_order(self, tasks: dict) -> list[str]:
        order: list[str] = []
        done: set[str] = set()

        def visit(n: str) -> None:
            if n in done:
                return
            for d in sorted(self._deps_of(tasks[n])):
                visit(d)
            done.add(n)
            order.append(n)

        for n in sorted(tasks):
            visit(n)
        return order

    def _resolve_inputs(self, run: PipelineRun, spec: dict) -> dict[str, Any]:
        return {
            pname: self._resolve_value(run, v)
            for pname, v in spec.get("inputs", {}).get("parameters", {}).items()
        }

    def _run_task(self, ir: dict, run: PipelineRun, run_dir: Path, tname: str,
                  spec: dict, run_exec_id: int | None) -> None:
        result = run.tasks[tname]
        comp = ir["components"][spec["componentRef"]["name"]]
        executor = ir["deploymentSpec"]["executors"][comp["executorLabel"]]
        inputs = self._resolve_inputs(run, spec)
        retries = int(spec.get("retryPolicy", {}).get("maxRetryCount", 0))
        if spec.get("iterator") is not None and "pythonFunction" not in executor:
            result.state = TaskState.FAILED
            result.error = "iterator tasks require a pythonFunction executor"
            self._record_lineage(run, tname, inputs, result, run_exec_id)
            return
        if "trainJob" in executor or "sweep" in executor:
            # kfp retryPolicy for job-launching steps: resubmit the whole
            # step (fresh TaskResult per attempt; each attempt records its
            # own lineage execution). Attempts run against a DETACHED
            # result and publish only the terminal verdict: the concurrent
            # DAG scheduler must never observe a transient FAILED between
            # retries (it would permanently skip dependents).
            helper = (
                self._run_train_job_task if "trainJob" in executor
                else self._run_sweep_task
            )
            result.state = TaskState.RUNNING
            for attempt in range(retries + 1):
                attempt_result = TaskResult()
                helper(run, run_dir, tname, executor, inputs, run_exec_id,
                       result=attempt_result)
                if (attempt_result.state != TaskState.FAILED
                        or attempt == retries):
                    run.tasks[tname] = attempt_result
                    return
            return
        it = spec.get("iterator")
        items = None
        if it is not None:
            items = self._resolve_value(run, it["items"])
            if isinstance(items, str):
                try:
                    items = json.loads(items)
                except json.JSONDecodeError:
                    pass  # falls into the not-a-list task failure below
            if not isinstance(items, list):
                result.state = TaskState.FAILED
                result.error = f"iterator items is {type(items).__name__}, not a list"
                self._record_lineage(run, tname, inputs, result, run_exec_id)
                return

        source = executor["pythonFunction"]["source"]
        fn_name = executor["pythonFunction"]["functionName"]
        out_artifacts = sorted(
            comp.get("outputDefinitions", {}).get("artifacts", {})
        )
        if out_artifacts and it is not None:
            result.state = TaskState.FAILED
            result.error = "iterator tasks cannot declare OutputPath artifacts"
            self._record_lineage(run, tname, inputs, result, run_exec_id)
            return

        # cache key: exact executor source + resolved inputs (KFP cache
        # fingerprint parity: component + args hash); iterator runs key on
        # the resolved item list too. Artifact-path INPUTS are fingerprinted
        # by file CONTENT, not path — paths embed run ids and would never hit.
        fp_in = dict(inputs)
        for pname, ptype in comp.get("inputDefinitions", {}).get(
            "parameters", {}
        ).items():
            if ptype.get("parameterType") == "ARTIFACT_PATH" and pname in fp_in:
                fp_in[pname] = self._content_digest(fp_in[pname])
        fp_fields = {"src": source, "fn": fn_name, "in": fp_in}
        if it is not None:
            # iterator-only field: keeps pre-existing non-iterator cache
            # entries (keyed without "items") valid
            fp_fields["items"] = items
        if out_artifacts:
            fp_fields["artifacts"] = out_artifacts
        fp = hashlib.sha256(
            json.dumps(fp_fields, sort_keys=True).encode()
        ).hexdigest()
        result.fingerprint = fp
        cache_file = self.cache_dir / f"{fp}.json"
        if self.cache_enabled and cache_file.exists():
            cached = json.loads(cache_file.read_text())
            arts = cached.get("artifacts", {})
            # a pruned cache (json kept, artifact files gone — or files gone
            # INSIDE a directory artifact) must MISS, not hand downstream
            # tasks dangling paths; the manifest lists every file published
            manifest = cached.get("artifact_files", {})
            def _cache_intact() -> bool:
                for a, p in arts.items():
                    base = Path(p)
                    if not base.exists():
                        return False
                    for rel in manifest.get(a, []):
                        if not (base / rel).exists():
                            return False
                return True
            if _cache_intact():
                result.output = cached["output"]
                result.artifacts = arts
                result.state = TaskState.CACHED
                self._record_lineage(run, tname, inputs, result, run_exec_id,
                                     cached=True)
                return

        t0 = time.monotonic()
        result.state = TaskState.RUNNING
        if it is None:
            # kfp retryPolicy: re-run the executor on failure. Every attempt
            # gets its OWN dir — including its own artifacts dir, so a failed
            # attempt's partial artifact files can never satisfy the
            # missing-check for (or be published as) a later attempt's output
            for attempt in range(retries + 1):
                attempt_dir = (
                    run_dir / tname if attempt == 0
                    else run_dir / tname / f"retry-{attempt}"
                )
                art_dir = attempt_dir / "artifacts"
                exec_inputs = dict(inputs)
                if out_artifacts:
                    art_dir.mkdir(parents=True, exist_ok=True)
                for a in out_artifacts:
                    exec_inputs[a] = str(art_dir / a)
                ok, out, err = self._exec_python_once(
                    attempt_dir, source, fn_name, exec_inputs
                )
                if ok:
                    missing = [
                        a for a in out_artifacts if not (art_dir / a).exists()
                    ]
                    if missing:
                        ok = False
                        err = f"declared artifact(s) never written: {missing}"
                    else:
                        result.artifacts = {
                            a: str(art_dir / a) for a in out_artifacts
                        }
                if ok or attempt == retries:
                    break
        else:
            # fan out over items (per-item subdir); output = collected list.
            # retryPolicy applies PER ITEM (a transient failure re-runs just
            # that item, not the whole fan-out)
            outs = []
            ok, err = True, ""
            for idx, item in enumerate(items):
                sub = dict(inputs)
                sub[it["itemInput"]] = item
                for attempt in range(retries + 1):
                    it_dir = (
                        run_dir / tname / f"it-{idx}" if attempt == 0
                        else run_dir / tname / f"it-{idx}" / f"retry-{attempt}"
                    )
                    ok, out_i, err = self._exec_python_once(
                        it_dir, source, fn_name, sub
                    )
                    if ok or attempt == retries:
                        break
                if not ok:
                    err = f"item {idx}: {err}"
                    break
                outs.append(out_i)
            out = outs
        result.duration_s = time.monotonic() - t0
        if not ok:
            result.state = TaskState.FAILED
            result.error = err
            self._record_lineage(run, tname, inputs, result, run_exec_id)
            return
        result.output = out
        result.state = TaskState.SUCCEEDED
        if self.cache_enabled:
            self.cache_dir.mkdir(parents=True, exist_ok=True)
            # Artifacts (files OR directories — the KFP model-dir pattern) are
            # copied INTO the cache so a hit stays valid after its producing
            # run directory is cleaned up. Staged + atomically renamed:
            # concurrent runs of the same fingerprint must never interleave
            # writes into the published path (first publisher wins).
            cached_arts = {}
            if result.artifacts:
                final = self.cache_dir / f"{fp}-artifacts"
                stage = self.cache_dir / f"{fp}-artifacts.stage-{os.getpid()}-{id(result)}"
                for a, p in result.artifacts.items():
                    dst = stage / a
                    dst.parent.mkdir(parents=True, exist_ok=True)
                    if Path(p).is_dir():
                        shutil.copytree(p, dst)
                    else:
                        shutil.copyfile(p, dst)  # constant memory
                try:
                    os.rename(stage, final)
                except OSError:
                    shutil.rmtree(stage, ignore_errors=True)  # racer won
                cached_arts = {a: str(final / a) for a in result.artifacts}
            # per-artifact file manifests let a later hit verify directory
            # artifacts are complete, not just present
            art_files = {}
            for a, p in cached_arts.items():
                base = Path(p)
                art_files[a] = (
                    sorted(str(q.relative_to(base)) for q in base.rglob("*") if q.is_file())
                    if base.is_dir() else []
                )
            # unique tmp per publisher: a shared name lets concurrent
            # same-fingerprint runs truncate each other mid-publish. Stale
            # tmps from CRASHED publishers are reaped best-effort — age-gated
            # so a live concurrent publisher's in-flight tmp is never
            # unlinked (a publish takes seconds; an hour-old tmp is dead).
            cutoff = time.time() - 3600.0
            for stray in self.cache_dir.glob(f"{cache_file.name}.tmp-*"):
                try:
                    if stray.stat().st_mtime < cutoff:
                        stray.unlink()
                except OSError:
                    pass
            tmp = cache_file.with_name(
                f"{cache_file.name}.tmp-{os.getpid()}-{id(result)}"
            )
            tmp.write_text(json.dumps(
                {"output": result.output, "artifacts": cached_arts,
                 "artifact_files": art_files}
            ))
            os.replace(tmp, cache_file)  # atomic publish
        self._record_lineage(run, tname, inputs, result, run_exec_id)

    @staticmethod
    def _content_digest(path: Any) -> str:
        """Constant-memory content hash of an artifact file OR directory
        (relative names + per-file digests, sorted for determinism)."""
        try:
            p = Path(str(path))
            if p.is_dir():
                h = hashlib.sha256()
                for f in sorted(q for q in p.rglob("*") if q.is_file()):
                    h.update(str(f.relative_to(p)).encode())
                    with open(f, "rb") as fh:
                        h.update(hashlib.file_digest(fh, "sha256").digest())
                return "sha256dir:" + h.hexdigest()
            with open(p, "rb") as f:
                return "sha256:" + hashlib.file_digest(f, "sha256").hexdigest()
        except OSError:
            return f"missing:{path}"

    def _exec_python_once(
        self, task_dir: Path, source: str, fn_name: str, inputs: dict
    ) -> tuple[bool, Any, str]:
        """One subprocess execution of a python-function executor (the v2
        driver/launcher analogue). Returns (ok, output, error)."""
        task_dir.mkdir(parents=True, exist_ok=True)
        (task_dir / "inputs.json").write_text(json.dumps(inputs))
        script = task_dir / "executor.py"
        script.write_text(
            # lazy annotations: component sources may annotate params with
            # dsl.InputPath/OutputPath, which don't exist in the executor
            # interpreter — PEP 563 keeps them unevaluated strings
            "from __future__ import annotations\n"
            + source
            + textwrap.dedent(
                f"""
                if __name__ == "__main__":
                    import json, sys
                    _in = json.loads(open(sys.argv[1]).read())
                    _out = {fn_name}(**_in)
                    open(sys.argv[2], "w").write(json.dumps({{"output": _out}}))
                """
            )
        )
        proc = subprocess.run(
            [sys.executable, str(script), str(task_dir / "inputs.json"),
             str(task_dir / "output.json")],
            capture_output=True,
            text=True,
        )
        (task_dir / "log.txt").write_text(proc.stdout + proc.stderr)
        if proc.returncode != 0:
            return False, None, (proc.stderr or proc.stdout).strip()[-2000:]
        out_file = task_dir / "output.json"
        out = (
            json.loads(out_file.read_text())["output"] if out_file.exists() else None
        )
        return True, out, ""

    def _run_train_job_task(self, run: PipelineRun, run_dir: Path, tname: str,
                            executor: dict, inputs: dict,
                            run_exec_id: int | None,
                            result: TaskResult | None = None) -> None:
        """Launch a TrainJob through the platform and adopt its verdict.
        Never cached: a training run's value is its side effects
        (checkpoints), not a JSON output. `result` (when given) is a
        detached per-attempt record the retry loop publishes terminally."""
        from kubeflow_tpu.api.serde import job_from_yaml
        from kubeflow_tpu.client import TrainingClient

        result = result if result is not None else run.tasks[tname]
        if self.platform is None:
            result.state = TaskState.FAILED
            result.error = (
                "trainJob step requires LocalPipelineRunner(platform=...)"
            )
            self._record_lineage(run, tname, inputs, result, run_exec_id)
            return
        timeout_s = float(executor["trainJob"].get("timeoutSeconds", 3600.0))
        try:
            # a forgotten argument must fail fast, not train with a literal
            # '${lr}' string
            manifest, suffix = self._resolve_manifest(
                run, tname, executor["trainJob"]["manifest"], inputs
            )
        except ValueError as exc:
            result.state = TaskState.FAILED
            result.error = str(exc)
            self._record_lineage(run, tname, inputs, result, run_exec_id)
            return
        job = job_from_yaml(manifest)
        # Unique name per (run, step): seq+timestamp from run_id plus the
        # task name, so two steps sharing a manifest name in one run — or
        # back-to-back runs in the same second — never collide on the CR name.
        job.metadata.name = f"{job.metadata.name}-{tname}-{suffix}"[-63:].strip("-")
        client = TrainingClient(self.platform)
        t0 = time.monotonic()
        result.state = TaskState.RUNNING
        try:
            client.create_job(job)
            done = client.wait_for_job_conditions(
                job.metadata.name, job.metadata.namespace, timeout_s=timeout_s
            )
        except Exception as exc:  # noqa: BLE001 — bad manifest => task fails
            result.state = TaskState.FAILED
            result.error = f"{type(exc).__name__}: {exc}"
            # a timed-out (or unwaitable) job must not run on as an orphan
            try:
                client.delete_job(job.metadata.name, job.metadata.namespace)
            except Exception:  # noqa: BLE001
                pass
            self._record_lineage(run, tname, inputs, result, run_exec_id)
            return
        result.duration_s = time.monotonic() - t0
        conditions = [
            {"type": c.type.value, "reason": c.reason}
            for c in done.status.conditions if c.status
        ]
        result.output = {
            "jobName": job.metadata.name,
            "succeeded": done.status.is_succeeded,
            "restartCount": done.status.restart_count,
            "conditions": conditions,
        }
        result.state = (
            TaskState.SUCCEEDED if done.status.is_succeeded else TaskState.FAILED
        )
        if not done.status.is_succeeded:
            result.error = f"job {job.metadata.name} failed: {conditions}"
        self._record_lineage(run, tname, inputs, result, run_exec_id)

    @staticmethod
    def _resolve_manifest(run: PipelineRun, tname: str, manifest: str,
                          inputs: dict, allow_prefix: str = "") -> tuple[str, str]:
        """Shared CR-step manifest plumbing: substitute ${param} inputs,
        reject leftovers (optionally excluding `allow_prefix` placeholders —
        trialParameters belong to the Experiment, not the pipeline), and
        return (manifest, unique-name suffix for this run+step)."""
        for k, v in inputs.items():
            manifest = manifest.replace("${" + k + "}", str(v))
        leftover = sorted(set(re.findall(r"\$\{([\w.-]+)\}", manifest)))
        if allow_prefix:
            leftover = [x for x in leftover if not x.startswith(allow_prefix)]
        if leftover:
            raise ValueError(
                f"unresolved manifest placeholder(s) {leftover}; pass them "
                f"as arguments to the {tname!r} step"
            )
        suffix = "-".join(run.run_id.rsplit("-", 2)[-2:])
        return manifest, suffix

    def _run_sweep_task(self, run: PipelineRun, run_dir: Path, tname: str,
                        executor: dict, inputs: dict,
                        run_exec_id: int | None,
                        result: TaskResult | None = None) -> None:
        """Run an Experiment through the platform; output = optimal trial.

        Never cached (trials are side-effectful jobs). Downstream steps
        consume output["optimalParameters"] — the KFP-then-Katib-then-train
        composition (SURVEY.md §3.4 recursing into §3.3). `result` (when
        given) is a detached per-attempt record the retry loop publishes
        terminally."""
        result = result if result is not None else run.tasks[tname]
        if self.platform is None:
            result.state = TaskState.FAILED
            result.error = "sweep step requires LocalPipelineRunner(platform=...)"
            self._record_lineage(run, tname, inputs, result, run_exec_id)
            return
        timeout_s = float(executor["sweep"].get("timeoutSeconds", 3600.0))
        try:
            manifest, suffix = self._resolve_manifest(
                run, tname, executor["sweep"]["manifest"], inputs,
                allow_prefix="trialParameters",
            )
        except ValueError as exc:
            result.state = TaskState.FAILED
            result.error = str(exc)
            self._record_lineage(run, tname, inputs, result, run_exec_id)
            return
        from kubeflow_tpu.sweep import SweepClient
        from kubeflow_tpu.sweep.serde import experiment_from_yaml

        exp = experiment_from_yaml(manifest)
        exp.metadata.name = (
            f"{exp.metadata.name}-{tname}-{suffix}"[-63:].strip("-")
        )
        client = SweepClient(self.platform, work_dir=str(self.work_dir / "sweeps"))
        t0 = time.monotonic()
        result.state = TaskState.RUNNING
        try:
            client.create_experiment(exp)
            done = client.wait_for_experiment(
                exp.metadata.name, exp.metadata.namespace, timeout_s=timeout_s
            )
        except Exception as exc:  # noqa: BLE001 — bad manifest => task fails
            result.state = TaskState.FAILED
            result.error = f"{type(exc).__name__}: {exc}"
            try:
                client.delete_experiment(exp.metadata.name, exp.metadata.namespace)
            except Exception:  # noqa: BLE001
                pass
            self._record_lineage(run, tname, inputs, result, run_exec_id)
            return
        result.duration_s = time.monotonic() - t0
        best = done.status.current_optimal_trial
        result.output = {
            "experimentName": exp.metadata.name,
            "condition": done.status.condition.value,
            "trials": done.status.trials,
            "trialsSucceeded": done.status.trials_succeeded,
            "optimalTrial": best.trial_name if best else None,
            "optimalParameters": (
                {a.name: a.value for a in best.parameter_assignments}
                if best else {}
            ),
            "optimalMetrics": (
                {m.name: m.latest for m in best.observation.metrics}
                if best else {}
            ),
        }
        succeeded = done.status.condition.value == "Succeeded" and best is not None
        result.state = TaskState.SUCCEEDED if succeeded else TaskState.FAILED
        if not succeeded:
            result.error = (
                f"experiment {exp.metadata.name} {done.status.condition.value}: "
                f"{done.status.message}"
            )
        self._record_lineage(run, tname, inputs, result, run_exec_id)

    def _record_lineage(self, run: PipelineRun, tname: str, inputs: dict,
                        result: TaskResult, run_exec_id: int | None,
                        cached: bool = False) -> None:
        if self.ms is None:
            return
        state = {
            TaskState.SUCCEEDED: "COMPLETE",
            TaskState.CACHED: "CACHED",
            TaskState.FAILED: "FAILED",
        }.get(result.state, "UNKNOWN")
        exec_id = self.ms.put_execution(
            "pipeline_task", f"{run.run_id}/{tname}", state=state,
            props=json.dumps({"fingerprint": result.fingerprint, "cached": cached}),
        )
        for pname, v in inputs.items():
            art = self.ms.put_artifact(
                "parameter", f"{run.run_id}/{tname}/in/{pname}",
                props=json.dumps({"value": v}),
            )
            self.ms.put_event(exec_id, art, MetadataStore.INPUT)
        if result.state in (TaskState.SUCCEEDED, TaskState.CACHED):
            art = self.ms.put_artifact(
                "parameter", f"{run.run_id}/{tname}/out/Output",
                props=json.dumps({"value": result.output}),
            )
            self.ms.put_event(exec_id, art, MetadataStore.OUTPUT)
            for aname, apath in result.artifacts.items():
                fart = self.ms.put_artifact(
                    "file", f"{run.run_id}/{tname}/out/{aname}",
                    uri=apath,
                )
                self.ms.put_event(exec_id, fart, MetadataStore.OUTPUT)
