"""Run visualization report — the KFP visualization-server analogue.

Reference parity (unverified cites, SURVEY.md §2.6/§5.1): KFP ships a
visualization server that renders step artifacts (confusion matrix, ROC
curve, scalar metrics, markdown) for the run view. Here a finished
PipelineRun renders to ONE self-contained HTML report (no CDN, no JS
frameworks — the zero-egress posture of the /ui SPA) served at
`GET /api/v1/pipelineruns/{ns}/{name}/report`.

Recognized step artifacts (by OutputPath artifact name):
  - ``metrics``          JSON {"name": number, ...}      -> stat tiles
  - ``confusion_matrix`` JSON {"labels": [...],
                                "matrix": [[...], ...]}  -> heatmap
  - ``roc``              JSON {"fpr": [...], "tpr": [...]} -> line chart
  - ``report``           text/markdown                   -> preformatted

Chart discipline follows the data-viz method: form picked by the data's
job (magnitude -> sequential heatmap; a curve -> single-series line;
headline scalars -> stat tiles), colors taken VERBATIM from the
validated reference palette (single blue sequential ramp light->dark,
categorical slot 1 for the one line series; no new colors are
introduced, so no re-validation is owed and none is possible here — the
image has no node), marks thin (2px line, >=8px markers via hover
targets), text in ink tokens never series colors, native <title> hover
on every mark, and a <details> table view per chart so identity and
values are never color-alone. Dark mode is the palette's own dark
steps via prefers-color-scheme; the heatmap ramp REVERSES on dark so
near-zero still recedes toward the surface.
"""

from __future__ import annotations

import html
import json
from pathlib import Path

# reference palette (validated defaults; see module docstring)
_SEQ_LIGHT = ["#cde2fb", "#b7d3f6", "#9ec5f4", "#86b6ef", "#6da7ec",
              "#5598e7", "#3987e5", "#2a78d6", "#256abf", "#1c5cab",
              "#184f95", "#104281", "#0d366b"]

_CSS = """
.viz-root {
  color-scheme: light;
  --surface-1: #fcfcfb;
  --surface-2: #f2f1ef;
  --text-primary: #0b0b0b;
  --text-secondary: #52514e;
  --series-1: #2a78d6;
  --grid: #e4e3e0;
  font: 14px/1.45 system-ui, sans-serif;
  background: var(--surface-1);
  color: var(--text-primary);
  margin: 0; padding: 24px;
}
@media (prefers-color-scheme: dark) {
  :root:where(:not([data-theme="light"])) .viz-root {
    color-scheme: dark;
    --surface-1: #1a1a19;
    --surface-2: #262524;
    --text-primary: #ffffff;
    --text-secondary: #c3c2b7;
    --series-1: #3987e5;
    --grid: #3a3938;
  }
}
.viz-root h1 { font-size: 18px; margin: 0 0 4px; }
.viz-root h2 { font-size: 15px; margin: 24px 0 8px; }
.viz-root .sub { color: var(--text-secondary); margin: 0 0 16px; }
.viz-root .tiles { display: flex; flex-wrap: wrap; gap: 12px; }
.viz-root .tile {
  background: var(--surface-2); border-radius: 8px; padding: 12px 16px;
  min-width: 120px;
}
.viz-root .tile .v { font-size: 22px; font-weight: 600; }
.viz-root .tile .k { color: var(--text-secondary); font-size: 12px; }
.viz-root svg text { fill: var(--text-secondary); font-size: 11px; }
.viz-root details { margin: 8px 0 0; }
.viz-root summary { color: var(--text-secondary); cursor: pointer; }
.viz-root table { border-collapse: collapse; margin-top: 6px; }
.viz-root td, .viz-root th {
  border: 1px solid var(--grid); padding: 3px 8px; font-size: 12px;
}
.viz-root pre {
  background: var(--surface-2); padding: 12px; border-radius: 8px;
  overflow-x: auto;
}
"""


def _esc(v) -> str:
    return html.escape(str(v))


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def _stat_tiles(metrics: dict) -> str:
    tiles = "".join(
        f'<div class="tile"><div class="v">{_esc(_fmt(v))}</div>'
        f'<div class="k">{_esc(k)}</div></div>'
        for k, v in metrics.items()
    )
    return f'<div class="tiles">{tiles}</div>'


def _heatmap(labels: list, matrix: list, dark_reverse: bool = False) -> str:
    """Confusion matrix: sequential single-hue heatmap + table view. Cell
    ink flips to white on the dark half of the ramp (the relief rule —
    values stay readable at every step)."""
    n = len(labels)
    if n == 0 or len(matrix) != n or any(len(r) != n for r in matrix):
        return '<p class="sub">confusion_matrix artifact malformed</p>'
    cell = 44
    pad_l, pad_t = 90, 30
    w = pad_l + n * cell + 10
    h = pad_t + n * cell + 40
    lo = min(min(r) for r in matrix)
    hi = max(max(r) for r in matrix)
    span = max(hi - lo, 1e-9)
    parts = [f'<svg viewBox="0 0 {w} {h}" width="{w}" height="{h}" '
             f'role="img" aria-label="confusion matrix">']
    for i, row in enumerate(matrix):
        for j, v in enumerate(row):
            t = (v - lo) / span
            idx = round(t * (len(_SEQ_LIGHT) - 1))
            fill = _SEQ_LIGHT[idx]
            ink = "#ffffff" if idx >= 7 else "#0b0b0b"
            x, y = pad_l + j * cell, pad_t + i * cell
            # 2px surface gap between fills (the spacer rule)
            parts.append(
                f'<rect x="{x + 1}" y="{y + 1}" width="{cell - 2}" '
                f'height="{cell - 2}" rx="4" fill="{fill}">'
                f'<title>true {_esc(labels[i])}, predicted '
                f'{_esc(labels[j])}: {_esc(_fmt(v))}</title></rect>'
                f'<text x="{x + cell / 2}" y="{y + cell / 2 + 4}" '
                f'text-anchor="middle" style="fill:{ink}">{_esc(_fmt(v))}</text>'
            )
    for i, lab in enumerate(labels):
        parts.append(
            f'<text x="{pad_l - 8}" y="{pad_t + i * cell + cell / 2 + 4}" '
            f'text-anchor="end">{_esc(lab)}</text>'
            f'<text x="{pad_l + i * cell + cell / 2}" y="{pad_t - 10}" '
            f'text-anchor="middle">{_esc(lab)}</text>'
        )
    parts.append(
        f'<text x="{pad_l + n * cell / 2}" y="{h - 8}" '
        f'text-anchor="middle">predicted → (rows: true)</text>'
    )
    parts.append("</svg>")
    head = "".join(f"<th>{_esc(c)}</th>" for c in labels)
    rows = "".join(
        f"<tr><th>{_esc(labels[i])}</th>"
        + "".join(f"<td>{_esc(_fmt(v))}</td>" for v in row) + "</tr>"
        for i, row in enumerate(matrix)
    )
    table = (f'<details><summary>table view</summary><table>'
             f'<tr><th></th>{head}</tr>{rows}</table></details>')
    return "".join(parts) + table


def _roc(fpr: list, tpr: list) -> str:
    """Single-series ROC line (slot-1 blue, 2px) over a diagonal
    reference; no legend box — the section title names the one series."""
    if len(fpr) != len(tpr) or len(fpr) < 2:
        return '<p class="sub">roc artifact malformed</p>'
    w, h, pad = 340, 280, 36
    px = lambda v: pad + v * (w - 2 * pad)            # noqa: E731
    py = lambda v: h - pad - v * (h - 2 * pad)        # noqa: E731
    pts = " ".join(f"{px(x):.1f},{py(y):.1f}" for x, y in zip(fpr, tpr))
    hover = "".join(
        f'<circle cx="{px(x):.1f}" cy="{py(y):.1f}" r="8" '
        f'fill="transparent"><title>fpr {x:.3g}, tpr {y:.3g}</title>'
        f'</circle>'
        for x, y in zip(fpr, tpr)
    )
    # trapezoidal AUC for the headline
    auc = sum(
        (fpr[i + 1] - fpr[i]) * (tpr[i + 1] + tpr[i]) / 2
        for i in range(len(fpr) - 1)
    )
    grid = "".join(
        f'<line x1="{px(0)}" y1="{py(g)}" x2="{px(1)}" y2="{py(g)}" '
        f'stroke="var(--grid)" stroke-width="1"/>'
        f'<text x="{px(0) - 6}" y="{py(g) + 4}" text-anchor="end">'
        f'{g:.1f}</text>'
        for g in (0.0, 0.5, 1.0)
    )
    svg = (
        f'<svg viewBox="0 0 {w} {h}" width="{w}" height="{h}" role="img" '
        f'aria-label="ROC curve, AUC {auc:.3f}">'
        f"{grid}"
        f'<line x1="{px(0)}" y1="{py(0)}" x2="{px(1)}" y2="{py(1)}" '
        f'stroke="var(--grid)" stroke-width="1" stroke-dasharray="4 3"/>'
        f'<polyline points="{pts}" fill="none" stroke="var(--series-1)" '
        f'stroke-width="2" stroke-linejoin="round"/>'
        f"{hover}"
        f'<text x="{(px(0) + px(1)) / 2}" y="{h - 6}" '
        f'text-anchor="middle">false positive rate</text>'
        f'<text x="12" y="{pad - 10}">true positive rate</text>'
        f"</svg>"
    )
    rows = "".join(
        f"<tr><td>{x:.4g}</td><td>{y:.4g}</td></tr>"
        for x, y in zip(fpr, tpr)
    )
    return (f'<p class="sub">AUC {auc:.3f}</p>{svg}'
            f'<details><summary>table view</summary>'
            f'<table><tr><th>fpr</th><th>tpr</th></tr>{rows}</table>'
            f'</details>')


def _read_artifact(path: str):
    try:
        return Path(path).read_text()
    except OSError:
        return None


def render_run_report(run, pipeline_name: str = "") -> str:
    """One self-contained HTML report for a PipelineRun: per-task state
    plus every recognized visualization artifact."""
    sections: list[str] = []
    for tname in sorted(run.tasks):
        t = run.tasks[tname]
        bits: list[str] = []
        for aname, apath in sorted(t.artifacts.items()):
            raw = _read_artifact(apath)
            if raw is None:
                continue
            if aname == "metrics":
                try:
                    m = json.loads(raw)
                    if isinstance(m, dict):
                        bits.append(_stat_tiles(m))
                except json.JSONDecodeError:
                    pass
            elif aname == "confusion_matrix":
                try:
                    d = json.loads(raw)
                    bits.append(_heatmap(d.get("labels", []),
                                         d.get("matrix", [])))
                except json.JSONDecodeError:
                    bits.append('<p class="sub">confusion_matrix '
                                'artifact is not JSON</p>')
            elif aname == "roc":
                try:
                    d = json.loads(raw)
                    bits.append(_roc(list(d.get("fpr", [])),
                                     list(d.get("tpr", []))))
                except json.JSONDecodeError:
                    bits.append('<p class="sub">roc artifact is not '
                                'JSON</p>')
            elif aname == "report":
                bits.append(f"<pre>{_esc(raw)}</pre>")
        state = t.state.value if hasattr(t.state, "value") else str(t.state)
        body = "".join(bits) if bits else ""
        sections.append(
            f"<h2>{_esc(tname)} "
            f'<span class="sub">[{_esc(state)}'
            + (f", {t.duration_s:.2f}s" if t.duration_s else "")
            + "]</span></h2>" + body
        )
    state = run.state.value if hasattr(run.state, "value") else str(run.state)
    return (
        "<!doctype html><html><head><meta charset='utf-8'>"
        f"<title>run {_esc(run.run_id)}</title>"
        f"<style>{_CSS}</style></head>"
        f'<body class="viz-root"><h1>{_esc(pipeline_name or run.pipeline_name)}'
        f"</h1><p class='sub'>run {_esc(run.run_id)} — {_esc(state)}</p>"
        + "".join(sections)
        + "</body></html>"
    )
