"""Pipelines subsystem — KFP parity (SURVEY.md §2.6).

@component/@pipeline DSL -> compiled IR (PipelineSpec-shaped YAML) ->
local DAG runner with step caching and C++ MLMD-analogue lineage, plus
recurring schedules (ScheduledWorkflow analogue).
"""

from kubeflow_tpu.pipelines.compiler import (
    compile_pipeline,
    compile_to_yaml,
    validate_ir,
)
from kubeflow_tpu.pipelines.dsl import (
    Component,
    InputPath,
    OutputPath,
    artifact,
    Pipeline,
    PipelineParam,
    Task,
    TaskOutput,
    component,
    for_each,
    on_exit,
    pipeline,
    retry,
    sweep,
    train_job,
    when,
)
from kubeflow_tpu.pipelines.runner import (
    LocalPipelineRunner,
    PipelineRun,
    TaskResult,
    TaskState,
)
from kubeflow_tpu.pipelines.scheduled import RecurringRun, ScheduleManager

__all__ = [
    "Component",
    "InputPath",
    "OutputPath",
    "artifact",
    "LocalPipelineRunner",
    "Pipeline",
    "PipelineParam",
    "PipelineRun",
    "RecurringRun",
    "ScheduleManager",
    "Task",
    "TaskOutput",
    "TaskResult",
    "TaskState",
    "compile_pipeline",
    "compile_to_yaml",
    "component",
    "for_each",
    "on_exit",
    "pipeline",
    "retry",
    "sweep",
    "train_job",
    "validate_ir",
    "when",
]
