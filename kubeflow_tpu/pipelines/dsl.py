"""Pipeline DSL — @component / @pipeline decorators.

Reference parity (unverified cites, SURVEY.md §2.6): kfp sdk/python/kfp/dsl
— `@dsl.component` turns a self-contained Python function into a pipeline
step; `@dsl.pipeline` traces a function that wires components into a DAG.
Tracing works the same way the kfp SDK's does: calling a component inside a
pipeline function does not execute it — it records a Task node and returns
a placeholder output to thread into downstream calls.

Like kfp's lightweight components, a component function must be
SELF-CONTAINED: imports it needs go inside the function body, because the
executor runs its extracted source in a fresh interpreter.
"""

from __future__ import annotations

import inspect
import textwrap
import threading
from dataclasses import dataclass, field
from typing import Any, Callable

_TYPE_MAP = {
    str: "STRING",
    int: "NUMBER_INTEGER",
    float: "NUMBER_DOUBLE",
    bool: "BOOLEAN",
    list: "LIST",
    dict: "STRUCT",
}


class InputPath:
    """Parameter annotation: the function receives a FILESYSTEM PATH to an
    upstream task's artifact file (kfp dsl.InputPath analogue). Wire it with
    `dsl.artifact(producer_output, "artifact_name")`."""


class OutputPath:
    """Parameter annotation: the runner injects a writable path; whatever
    the function writes there becomes a named artifact of the task (kfp
    dsl.OutputPath analogue). Callers never pass these parameters."""


def _param_type(annotation) -> str:
    return _TYPE_MAP.get(annotation, "STRING")


@dataclass
class Component:
    """A pipeline step: a named, typed, source-extracted Python function."""

    name: str
    fn: Callable
    source: str
    inputs: dict[str, str]            # param name -> IR type
    defaults: dict[str, Any]
    output_type: str | None           # None = no return value
    # OutputPath-annotated params: runner-injected writable paths whose
    # files become named artifacts (never caller-supplied)
    output_artifacts: list[str] = field(default_factory=lambda: [])

    def __call__(self, *args, **kwargs):
        ctx = _PipelineContext.current()
        if ctx is None:
            # outside a pipeline: behave as the plain function (unit tests)
            return self.fn(*args, **kwargs)
        bound = inspect.signature(self.fn).bind_partial(*args, **kwargs)
        args_dict = dict(bound.arguments)
        supplied = set(args_dict) & set(self.output_artifacts)
        if supplied:
            raise ValueError(
                f"{self.name}: OutputPath parameter(s) {sorted(supplied)} are "
                f"runner-injected, not caller arguments"
            )
        for pname, v in args_dict.items():
            if (
                self.inputs.get(pname) == "ARTIFACT_PATH"
                and isinstance(v, TaskOutput)
                and v.key == "Output"
            ):
                raise ValueError(
                    f"{self.name}: InputPath parameter {pname!r} got a task's "
                    f"return value; wire an artifact with "
                    f"dsl.artifact(task, \"name\")"
                )
        task = ctx.add_task(self, args_dict)
        return task.output


def component(fn: Callable | None = None, *, name: str | None = None):
    """Wrap a self-contained function as a Component."""

    def wrap(f: Callable) -> Component:
        sig = inspect.signature(f)
        inputs, defaults, out_artifacts = {}, {}, []
        for pname, p in sig.parameters.items():
            if p.annotation is OutputPath:
                if pname == "Output":
                    raise ValueError(
                        "OutputPath parameter cannot be named 'Output' (the "
                        "reserved return-value key)"
                    )
                out_artifacts.append(pname)
                continue
            inputs[pname] = (
                "ARTIFACT_PATH" if p.annotation is InputPath
                else _param_type(p.annotation)
            )
            if p.default is not inspect.Parameter.empty:
                defaults[pname] = p.default
        out_t = (
            None
            if sig.return_annotation in (inspect.Signature.empty, None)
            else _param_type(sig.return_annotation)
        )
        return Component(
            name=name or f.__name__.replace("_", "-"),
            fn=f,
            source=_clean_source(f),
            inputs=inputs,
            defaults=defaults,
            output_type=out_t,
            output_artifacts=out_artifacts,
        )

    return wrap(fn) if fn is not None else wrap


def _clean_source(f: Callable) -> str:
    """Function source with any @component decorator lines stripped (the
    executor must see a plain def)."""
    lines = textwrap.dedent(inspect.getsource(f)).splitlines()
    start = next(i for i, ln in enumerate(lines) if ln.lstrip().startswith("def "))
    return "\n".join(lines[start:]) + "\n"


@dataclass(frozen=True)
class TaskOutput:
    """Placeholder for a task's return value during tracing."""

    producer: str       # task name
    key: str = "Output"


@dataclass(frozen=True)
class PipelineParam:
    """Placeholder for a pipeline-level input parameter."""

    name: str
    param_type: str = "STRING"
    default: Any = None


@dataclass
class Task:
    name: str
    component: Component
    arguments: dict[str, Any]         # const | TaskOutput | PipelineParam
    explicit_deps: list[str] = field(default_factory=lambda: [])
    # trigger conditions from enclosing `when(...)` blocks — ALL must hold
    # or the task (and its dependents) is skipped at runtime
    conditions: list["Condition"] = field(default_factory=lambda: [])
    # for_each fan-out: (items value-or-placeholder, loop arg name)
    iterate_over: tuple[Any, str] | None = None
    # exit handlers run last, regardless of upstream failure/skip
    is_exit_handler: bool = False
    # transient-failure retries for this task's executor (kfp set_retry)
    retries: int = 0

    @property
    def output(self) -> TaskOutput:
        return TaskOutput(producer=self.name)

    def set_retries(self, n: int) -> "Task":
        """Retry the executor up to n extra times on failure (kfp
        task.set_retry analogue)."""
        if n < 0:
            raise ValueError("retries must be >= 0")
        self.retries = n
        return self

    def after(self, *others: "Task | TaskOutput") -> "Task":
        for o in others:
            self.explicit_deps.append(o.producer if isinstance(o, TaskOutput) else o.name)
        return self

    def dependencies(self) -> list[str]:
        deps = {
            v.producer for v in self.arguments.values() if isinstance(v, TaskOutput)
        }
        for c in self.conditions:
            for side in (c.lhs, c.rhs):
                if isinstance(side, TaskOutput):
                    deps.add(side.producer)
        if self.iterate_over is not None and isinstance(self.iterate_over[0], TaskOutput):
            deps.add(self.iterate_over[0].producer)
        deps.update(self.explicit_deps)
        return sorted(deps)


@dataclass
class Pipeline:
    name: str
    description: str
    params: dict[str, PipelineParam]
    tasks: dict[str, Task]
    # the traced function's return (a TaskOutput) — the run's output
    result: TaskOutput | None = None


class _PipelineContext:
    _local = threading.local()

    def __init__(self, name: str, description: str,
                 task_prefix: str = ""):
        self.pipeline = Pipeline(name, description, {}, {})
        self._counts: dict[str, int] = {}
        self.cond_stack: list["Condition"] = []
        # nested-pipeline inlining: tasks are BORN with their final
        # prefixed names, so every intra-sub TaskOutput reference is
        # correct by construction and references passed in from the
        # caller are never rewritten (a post-hoc rename pass cannot tell
        # an outer producer from a same-named sub task)
        self.task_prefix = task_prefix

    @classmethod
    def current(cls) -> "_PipelineContext | None":
        return getattr(cls._local, "ctx", None)

    def __enter__(self):
        # re-entrant: nested-pipeline tracing opens a child context and
        # must restore the ENCLOSING one on exit, not clear it
        self._prev = _PipelineContext.current()
        self._local.ctx = self
        return self

    def __exit__(self, *exc):
        self._local.ctx = self._prev

    def add_task(self, comp: Component, arguments: dict[str, Any]) -> Task:
        n = self._counts.get(comp.name, 0)
        self._counts[comp.name] = n + 1
        base = comp.name if n == 0 else f"{comp.name}-{n + 1}"
        tname = f"{self.task_prefix}{base}"
        task = Task(
            name=tname, component=comp, arguments=arguments,
            conditions=list(self.cond_stack),
        )
        self.pipeline.tasks[tname] = task
        return task


# ------------------------------------------------------- control flow (v2)


@dataclass(frozen=True)
class Condition:
    """One `when` predicate: lhs <op> rhs. Either side may be a TaskOutput/
    PipelineParam placeholder or a constant."""

    lhs: Any
    op: str       # == != < <= > >=
    rhs: Any


_OPS = {"==", "!=", "<", "<=", ">", ">="}


class when:
    """Conditional block (kfp dsl.If/Condition analogue):

        with dsl.when(score.output, ">", 0.9):
            deploy(...)

    Every task created inside the block carries the predicate; at runtime a
    false predicate skips the task and (transitively) its dependents.
    Nested blocks AND their predicates."""

    def __init__(self, lhs, op: str, rhs):
        if op not in _OPS:
            raise ValueError(f"when: unsupported operator {op!r} (use {_OPS})")
        # both sides may be constants, task outputs, or pipeline params
        self.cond = Condition(lhs=lhs, op=op, rhs=rhs)

    def __enter__(self):
        ctx = _PipelineContext.current()
        if ctx is None:
            raise RuntimeError("when(...) blocks only apply inside a @pipeline")
        ctx.cond_stack.append(self.cond)
        return self

    def __exit__(self, *exc):
        _PipelineContext.current().cond_stack.pop()


def for_each(items, comp: Component, item_arg: str, **fixed) -> TaskOutput:
    """Fan a component out over a list (kfp dsl.ParallelFor + Collected
    analogue): `items` is a constant list OR an upstream list output; the
    component runs once per item with `item_arg` bound to it, and the task's
    output is the COLLECTED list of per-item outputs, in item order."""
    ctx = _PipelineContext.current()
    if ctx is None:
        raise RuntimeError("for_each can only be used inside a @pipeline")
    if item_arg not in comp.inputs:
        raise ValueError(f"for_each: {comp.name} has no input {item_arg!r}")
    unknown = set(fixed) - set(comp.inputs)
    if unknown:
        raise ValueError(f"for_each: {comp.name} has no input(s) {sorted(unknown)}")
    if item_arg in fixed:
        raise ValueError(f"for_each: {item_arg!r} is the loop variable, not a fixed arg")
    if comp.output_artifacts:
        raise ValueError(
            f"for_each: {comp.name} declares OutputPath artifact(s) "
            f"{comp.output_artifacts}; iterator tasks cannot produce artifacts"
        )
    task = ctx.add_task(comp, dict(fixed))
    task.iterate_over = (items, item_arg)
    return task.output


def artifact(out: TaskOutput, name: str) -> TaskOutput:
    """Reference a producer task's NAMED artifact (an OutputPath file) for a
    downstream InputPath parameter: `consume(path=dsl.artifact(t, "model"))`.
    Resolves at runtime to the artifact file's filesystem path."""
    ctx = _PipelineContext.current()
    if ctx is not None:
        task = ctx.pipeline.tasks.get(out.producer)
        if task is not None and name not in task.component.output_artifacts:
            raise ValueError(
                f"artifact: task {out.producer!r} has no OutputPath artifact "
                f"{name!r} (has {task.component.output_artifacts})"
            )
    return TaskOutput(producer=out.producer, key=name)


def retry(out: TaskOutput, n: int) -> TaskOutput:
    """Attach a retry policy to an already-declared task by its output:
    `r = dsl.retry(flaky(...), 2)` (kfp task.set_retry analogue)."""
    ctx = _PipelineContext.current()
    if ctx is None:
        raise RuntimeError("retry can only be used inside a @pipeline")
    task = ctx.pipeline.tasks.get(out.producer)
    if task is None:
        raise ValueError(f"retry: unknown task {out.producer!r}")
    task.set_retries(n)
    return out


def on_exit(out: TaskOutput) -> TaskOutput:
    """Mark an already-declared task as an exit handler (kfp dsl.ExitHandler
    analogue): it runs at the end of the run even when upstream tasks failed
    or were skipped (its input placeholders resolve to None for non-run
    producers). Its own failure still fails the run."""
    ctx = _PipelineContext.current()
    if ctx is None:
        raise RuntimeError("on_exit can only be used inside a @pipeline")
    task = ctx.pipeline.tasks.get(out.producer)
    if task is None:
        raise ValueError(f"on_exit: unknown task {out.producer!r}")
    task.is_exit_handler = True
    return out


@dataclass
class TrainJobComponent:
    """A pipeline step that launches a TrainJob through the platform —
    the reference's core composition (a KFP step creating a TFJob/
    PyTorchJob CR, SURVEY.md §3.4 recursing into §3.1). The manifest may
    carry ${param} placeholders bound via `arguments`."""

    name: str
    manifest: str
    timeout_s: float = 3600.0

    def __call__(self, **arguments) -> TaskOutput:
        ctx = _PipelineContext.current()
        if ctx is None:
            raise RuntimeError("train_job steps can only be called inside a @pipeline")
        comp = Component(
            name=self.name,
            fn=None,  # no python executor — the runner launches the job
            source="",
            inputs={k: "STRING" for k in arguments},
            defaults={},
            output_type="STRUCT",
        )
        comp.train_job_manifest = self.manifest
        comp.train_job_timeout_s = self.timeout_s
        task = ctx.add_task(comp, arguments)
        return task.output


def train_job(name: str, manifest: str, timeout_s: float = 3600.0) -> TrainJobComponent:
    """Declare a TrainJob-launching step for use inside @pipeline."""
    return TrainJobComponent(name=name, manifest=manifest, timeout_s=timeout_s)


@dataclass
class SweepComponent:
    """A pipeline step that runs a hyperparameter Experiment and outputs the
    optimal trial — the KFP-launches-Katib composition (SURVEY.md §3.4 ->
    §3.3): downstream steps consume `optimalParameters` to train/serve with
    the winning configuration. Manifest placeholders bind via `arguments`."""

    name: str
    manifest: str
    timeout_s: float = 3600.0

    def __call__(self, **arguments) -> TaskOutput:
        ctx = _PipelineContext.current()
        if ctx is None:
            raise RuntimeError("sweep steps can only be called inside a @pipeline")
        comp = Component(
            name=self.name,
            fn=None,
            source="",
            inputs={k: "STRING" for k in arguments},
            defaults={},
            output_type="STRUCT",
        )
        comp.sweep_manifest = self.manifest
        comp.sweep_timeout_s = self.timeout_s
        task = ctx.add_task(comp, arguments)
        return task.output


def sweep(name: str, manifest: str, timeout_s: float = 3600.0) -> SweepComponent:
    """Declare an Experiment-running step for use inside @pipeline."""
    return SweepComponent(name=name, manifest=manifest, timeout_s=timeout_s)


def _inline_subpipeline(f: Callable, pname: str, outer: "_PipelineContext",
                        overrides: dict):
    """kfp v2 pipeline-in-pipeline: calling a @pipeline inside another
    traces the sub-pipeline and INLINES its tasks into the caller —
    flattening is execution-equivalent to upstream's sub-DAG component
    and keeps one IR/runner shape. Sub-pipeline arguments substitute
    directly (constants, the caller's params, or upstream TaskOutputs);
    tasks are born with invocation-unique prefixed names (no post-hoc
    rename pass, so outer references can never be miswired by a name
    collision) and inherit the caller's active `when` conditions. The
    traced return value flows back verbatim — a sub returning its own
    parameter passes the caller's value through."""
    sig = inspect.signature(f)
    placeholders: dict[str, Any] = {}
    for arg_name, p in sig.parameters.items():
        if arg_name in overrides:
            placeholders[arg_name] = overrides[arg_name]
        elif p.default is not inspect.Parameter.empty:
            placeholders[arg_name] = p.default
        else:
            raise TypeError(
                f"nested pipeline {pname!r}: missing argument {arg_name!r}")
    unknown = set(overrides) - set(sig.parameters)
    if unknown:
        raise TypeError(
            f"nested pipeline {pname!r}: unknown argument(s) "
            f"{sorted(unknown)}")
    # invocation-unique prefix, CHAINED through the enclosing context's
    # own prefix so doubly-nested pipelines reached from different parents
    # get distinct names ('a-g-inc' vs 'b-g-inc', not a spurious collision)
    inv_key = f"__pipeline__{pname}"
    n = outer._counts.get(inv_key, 0)
    outer._counts[inv_key] = n + 1
    local = f"{pname}-" if n == 0 else f"{pname}-{n + 1}-"
    prefix = f"{outer.task_prefix}{local}"
    sub_ctx = _PipelineContext(pname, "", task_prefix=prefix)
    outer_conds = list(outer.cond_stack)
    with sub_ctx:
        result = f(**placeholders)
    for tname, task in sub_ctx.pipeline.tasks.items():
        if tname in outer.pipeline.tasks:
            raise ValueError(
                f"nested pipeline {pname!r}: inlined task name {tname!r} "
                "collides with an existing task — rename the component or "
                "the sub-pipeline")
        task.conditions = outer_conds + task.conditions
        outer.pipeline.tasks[tname] = task
    return result


def pipeline(fn: Callable | None = None, *, name: str | None = None,
             description: str = ""):
    """Trace a pipeline function into a Pipeline DAG. Calling a @pipeline
    from inside another @pipeline inlines it as a sub-DAG (kfp v2
    pipeline-in-pipeline composition) and returns its result TaskOutput."""

    def wrap(f: Callable) -> Callable[..., Pipeline]:
        pname = name or f.__name__.replace("_", "-")

        def build(**overrides) -> Pipeline:
            outer = _PipelineContext.current()
            if outer is not None:
                return _inline_subpipeline(f, pname, outer, overrides)
            sig = inspect.signature(f)
            ctx = _PipelineContext(pname, description or (f.__doc__ or "").strip())
            placeholders = {}
            for arg_name, p in sig.parameters.items():
                default = None if p.default is inspect.Parameter.empty else p.default
                if arg_name in overrides:
                    default = overrides[arg_name]
                pp = PipelineParam(
                    name=arg_name, param_type=_param_type(p.annotation),
                    default=default,
                )
                ctx.pipeline.params[arg_name] = pp
                placeholders[arg_name] = pp
            with ctx:
                result = f(**placeholders)
            if isinstance(result, TaskOutput):
                ctx.pipeline.result = result
            return ctx.pipeline

        build.__name__ = f.__name__
        build.pipeline_name = pname
        return build

    return wrap(fn) if fn is not None else wrap
