"""PipelineRun CR + controller — pipelines as platform objects over REST.

Reference parity (unverified cites, SURVEY.md §2.6 API-server row): the KFP
apiserver exposes pipeline/run CRUD as a network API (backend/src/apiserver)
and hands execution to Argo. Here a PipelineRun object in the cluster store
carries the compiled IR + arguments; a controller executes it with the
LocalPipelineRunner (DAG engine + cache + lineage) and mirrors task states
back onto the CR status — so remote SDKs/CLIs submit and poll runs exactly
like jobs.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any

from kubeflow_tpu.analysis.lockcheck import make_lock
from kubeflow_tpu.api.common import ObjectMeta, utcnow as _now
from kubeflow_tpu.controller.base import ControllerBase
from kubeflow_tpu.controller.fakecluster import ConflictError, FakeCluster


@dataclass
class PipelineRunSpec:
    # compiled IR (pipelines/compiler.py PipelineSpec-shaped dict)
    pipeline_spec: dict = field(default_factory=dict)
    arguments: dict = field(default_factory=dict)
    cache: bool = True


@dataclass
class PipelineRunStatus:
    state: str = "Pending"  # Pending | Running | Succeeded | Failed
    tasks: dict[str, str] = field(default_factory=dict)
    output: Any = None
    error: str = ""
    run_id: str = ""
    start_time: str = ""
    completion_time: str = ""

    @property
    def is_finished(self) -> bool:
        return self.state in ("Succeeded", "Failed")


@dataclass
class PipelineRunCR:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PipelineRunSpec = field(default_factory=PipelineRunSpec)
    status: PipelineRunStatus = field(default_factory=PipelineRunStatus)
    kind: str = "PipelineRun"
    api_version: str = "kubeflow-tpu.org/v1"


def pipelinerun_from_dict(manifest: dict) -> PipelineRunCR:
    from kubeflow_tpu.api.serde import _from_dict
    from kubeflow_tpu.pipelines.compiler import validate_ir

    body = {k: v for k, v in manifest.items() if k not in ("kind", "apiVersion")}
    body.pop("status", None)
    run = _from_dict(PipelineRunCR, body)
    validate_ir(run.spec.pipeline_spec)
    return run


class PipelineRunController(ControllerBase):
    """Executes PipelineRun objects; one executor thread per run."""

    WATCH_KINDS = ("pipelineruns",)

    ERROR_EVENT_KIND = "pipelineruns"
    #: finished-run results retained for the visualization report
    _RESULT_CAP = 64

    def metadata_store(self):
        """The controller's MLMD store (opened on first use)."""
        import os

        from kubeflow_tpu.native import MetadataStore

        with self._ms_mu:
            if self._metadata_store is None:
                # MetadataStore.__init__ creates the parent directory
                self._metadata_store = MetadataStore(
                    os.path.join(self.work_dir, "mlmd.db"))
            return self._metadata_store

    def result_for(self, namespace: str, name: str):
        """The runner's full result for a finished run (None when the run
        never finished here — e.g. a platform restart)."""
        with self._mu:
            return self._results.get(f"{namespace}/{name}")

    def __init__(
        self,
        cluster: FakeCluster,
        work_dir: str = ".kubeflow_tpu/pipelines",
        platform=None,
        workers: int = 1,
    ):
        super().__init__(cluster, name="pipelinerun", workers=workers,
                         resync_period_s=2.0)
        self.work_dir = work_dir
        self.platform = platform
        # platform-run lineage (MLMD write side, SURVEY §2.6): one durable
        # store per controller, shared by every runner it spawns (the C++
        # store is internally locked); lazily opened so merely
        # constructing a platform never touches disk
        self._metadata_store = None
        self._ms_mu = make_lock("crd.PipelineRunController._ms_mu")
        self._running: set[str] = set()  # uids with a live executor thread
        # key -> the runner's full result (task artifacts included) for
        # the visualization report; bounded by _RESULT_CAP, oldest evicted
        self._results: dict[str, object] = {}
        self._mu = make_lock("crd.PipelineRunController._mu")
        self.metrics.update({
            "pipelineruns_total": 0,
            "pipelineruns_succeeded_total": 0,
            "pipelineruns_failed_total": 0,
        })

    def kind_filter(self, etype, kind: str, obj) -> str | None:
        return self.cluster._key(obj) if kind == "pipelineruns" else None

    def resync_keys(self):
        return [
            self.cluster._key(r)
            for r in self.cluster.list("pipelineruns")
            if not r.status.is_finished
        ]

    def reconcile(self, key: str) -> float | None:
        run: PipelineRunCR | None = self.cluster.get(
            "pipelineruns", key, copy_obj=True
        )
        if run is None or run.status.is_finished:
            return None
        with self._mu:
            if run.metadata.uid in self._running:
                return None
            self._running.add(run.metadata.uid)
        if run.status.state == "Pending":
            run.status.state = "Running"
            run.status.start_time = _now()
            try:
                run = self.cluster.update("pipelineruns", run)
            except (ConflictError, KeyError):
                with self._mu:
                    self._running.discard(run.metadata.uid)
                return 0.1
            self.metrics["pipelineruns_total"] += 1
            self.cluster.record_event("pipelineruns", key, "RunStarted", "executing")
        threading.Thread(
            target=self._execute, args=(key, run.metadata.uid),
            name=f"pipelinerun-{run.metadata.name}", daemon=True,
        ).start()
        return None

    def _execute(self, key: str, uid: str) -> None:
        from kubeflow_tpu.pipelines.runner import LocalPipelineRunner

        run = self.cluster.get("pipelineruns", key, copy_obj=True)
        if run is None or run.metadata.uid != uid:
            with self._mu:
                self._running.discard(uid)
            return
        try:
            runner = LocalPipelineRunner(
                work_dir=self.work_dir,
                cache=run.spec.cache,
                platform=self.platform,
                metadata_store=self.metadata_store(),
            )
            result = runner.run(run.spec.pipeline_spec, run.spec.arguments)
            with self._mu:
                self._results[key] = result
                while len(self._results) > self._RESULT_CAP:
                    self._results.pop(next(iter(self._results)))
            state = "Succeeded" if result.succeeded else "Failed"
            tasks = {t: r.state.value for t, r in result.tasks.items()}
            output, error, run_id = result.output, "", result.run_id
            if not result.succeeded:
                error = "; ".join(
                    f"{t}: {r.error}" for t, r in result.tasks.items() if r.error
                )
        except Exception as exc:  # noqa: BLE001 — a bad IR must not kill the controller
            state, tasks, output, run_id = "Failed", {}, None, ""
            error = f"{type(exc).__name__}: {exc}"
        class _Vanished(Exception):
            """Run deleted/replaced while executing — nothing to finalize."""

        def finalize(cur):
            if cur.metadata.uid != uid:
                raise _Vanished
            cur.status.state = state
            cur.status.tasks = tasks
            cur.status.output = output
            cur.status.error = error
            cur.status.run_id = run_id
            cur.status.completion_time = _now()

        try:
            # the ONE sanctioned conflict loop (read_modify_write), not a
            # hand-rolled retry — and its give-up is recorded, not silent
            try:
                self.cluster.read_modify_write("pipelineruns", key, finalize)
            except (_Vanished, KeyError):
                return  # deleted/replaced while executing
            except ConflictError:
                self.cluster.record_event(
                    "pipelineruns", key, "StatusWriteLost",
                    "terminal status write kept conflicting", type="Warning",
                )
                return
        finally:
            # only AFTER the terminal status is durable (or the run is gone)
            # may a resync legally consider this uid idle — discarding
            # earlier would let reconcile spawn a second executor and run
            # every pipeline step twice
            with self._mu:
                self._running.discard(uid)
        counter = (
            "pipelineruns_succeeded_total" if state == "Succeeded"
            else "pipelineruns_failed_total"
        )
        self.metrics[counter] += 1
        self.cluster.record_event(
            "pipelineruns", key,
            "RunSucceeded" if state == "Succeeded" else "RunFailed",
            error or "pipeline complete",
            type="Normal" if state == "Succeeded" else "Warning",
        )
