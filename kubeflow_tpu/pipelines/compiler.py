"""Compiler: Pipeline DAG -> IR YAML.

Reference parity (unverified cites, SURVEY.md §2.6): kfp
sdk/python/kfp/compiler/compiler.py lowering the DSL to the PipelineSpec
proto IR. The IR here mirrors PipelineSpec's shape (pipelineInfo / root dag
/ components / deploymentSpec with per-executor source) in plain YAML, and
is the contract the runner and golden-file tests consume — compilation is
pure and cluster-free, the reference's own highest-leverage test seam
(SURVEY.md §4 'golden-file IR tests').
"""

from __future__ import annotations

from typing import Any

import yaml

from kubeflow_tpu.pipelines.dsl import (
    _OPS,
    Pipeline,
    PipelineParam,
    TaskOutput,
)


def _value_ref(value: Any) -> dict:
    """Encode a const / TaskOutput / PipelineParam as an IR value binding."""
    if isinstance(value, TaskOutput):
        return {
            "taskOutputParameter": {
                "producerTask": value.producer,
                "outputParameterKey": value.key,
            }
        }
    if isinstance(value, PipelineParam):
        return {"componentInputParameter": value.name}
    return {"runtimeValue": {"constant": value}}

SCHEMA_VERSION = "kubeflow-tpu.org/pipelinespec/v1"


def compile_pipeline(pipeline: Pipeline) -> dict:
    """Lower a traced Pipeline to its IR dict."""
    components: dict[str, Any] = {}
    executors: dict[str, Any] = {}
    tasks: dict[str, Any] = {}

    for task in pipeline.tasks.values():
        manifest = getattr(task.component, "train_job_manifest", None)
        sweep_manifest = getattr(task.component, "sweep_manifest", None)
        if manifest is not None:
            exec_def: dict[str, Any] = {"trainJob": {
                "manifest": manifest,
                "timeoutSeconds": getattr(
                    task.component, "train_job_timeout_s", 3600.0
                ),
            }}
        elif sweep_manifest is not None:
            exec_def = {"sweep": {
                "manifest": sweep_manifest,
                "timeoutSeconds": getattr(
                    task.component, "sweep_timeout_s", 3600.0
                ),
            }}
        else:
            exec_def = {
                "pythonFunction": {
                    "functionName": task.component.fn.__name__,
                    "source": task.component.source,
                }
            }
        comp_key = f"comp-{task.component.name}"
        exec_key = f"exec-{task.component.name}"
        if comp_key in components and executors.get(exec_key) != exec_def:
            # same component NAME, different body (e.g. two train_job steps
            # named alike with different manifests): fall back to the unique
            # task name so neither silently runs the other's executor
            comp_key = f"comp-{task.name}"
            exec_key = f"exec-{task.name}"
        if comp_key not in components:
            comp_def: dict[str, Any] = {
                "executorLabel": exec_key,
                "inputDefinitions": {
                    "parameters": {
                        p: {"parameterType": t}
                        for p, t in task.component.inputs.items()
                    }
                },
            }
            out_defs: dict[str, Any] = {}
            if task.component.output_type is not None:
                out_defs["parameters"] = {
                    "Output": {"parameterType": task.component.output_type}
                }
            if task.component.output_artifacts:
                out_defs["artifacts"] = {
                    a: {"artifactType": "system.Artifact"}
                    for a in task.component.output_artifacts
                }
            if out_defs:
                comp_def["outputDefinitions"] = out_defs
            components[comp_key] = comp_def
            executors[exec_key] = exec_def

        inputs: dict[str, Any] = {
            pname: _value_ref(value) for pname, value in task.arguments.items()
        }
        entry: dict[str, Any] = {
            "componentRef": {"name": comp_key},
            "inputs": {"parameters": inputs},
        }
        deps = task.dependencies()
        if deps:
            entry["dependentTasks"] = deps
        if task.conditions:
            # kfp triggerPolicy.condition analogue, structured not stringly
            entry["when"] = [
                {"lhs": _value_ref(c.lhs), "op": c.op, "rhs": _value_ref(c.rhs)}
                for c in task.conditions
            ]
        if task.iterate_over is not None:
            items, item_arg = task.iterate_over
            entry["iterator"] = {
                "items": _value_ref(items), "itemInput": item_arg,
            }
        if task.is_exit_handler:
            entry["exitHandler"] = True
        if task.retries:
            entry["retryPolicy"] = {"maxRetryCount": task.retries}
        tasks[task.name] = entry

    ir: dict[str, Any] = {
        "schemaVersion": SCHEMA_VERSION,
        "pipelineInfo": {
            "name": pipeline.name,
            "description": pipeline.description,
        },
        "root": {
            "inputDefinitions": {
                "parameters": {
                    p.name: _root_param(p) for p in pipeline.params.values()
                }
            },
            "dag": {"tasks": tasks},
        },
        "components": components,
        "deploymentSpec": {"executors": executors},
    }
    if pipeline.result is not None:
        ir["root"]["outputFrom"] = {
            "producerTask": pipeline.result.producer,
            "outputParameterKey": pipeline.result.key,
        }
    return ir


def _root_param(p: PipelineParam) -> dict:
    d: dict[str, Any] = {"parameterType": p.param_type}
    if p.default is not None:
        d["defaultValue"] = p.default
    return d


def compile_to_yaml(pipeline: Pipeline) -> str:
    return yaml.safe_dump(compile_pipeline(pipeline), sort_keys=False)


def validate_ir(ir: dict) -> dict:
    """Structural checks the runner relies on (apiserver admission parity)."""
    if ir.get("schemaVersion") != SCHEMA_VERSION:
        raise ValueError(f"unsupported schemaVersion {ir.get('schemaVersion')!r}")
    tasks = ir.get("root", {}).get("dag", {}).get("tasks", {})
    comps = ir.get("components", {})
    executors = ir.get("deploymentSpec", {}).get("executors", {})
    for tname, t in tasks.items():
        cref = t.get("componentRef", {}).get("name")
        if cref not in comps:
            raise ValueError(f"task {tname}: unknown component {cref!r}")
        ex = executors.get(comps[cref].get("executorLabel"))
        if ex is None:
            raise ValueError(f"task {tname}: component {cref} has no executor")
        if not ({"pythonFunction", "trainJob", "sweep"} & set(ex)):
            raise ValueError(f"task {tname}: executor has no known runtime")
        for dep in t.get("dependentTasks", []):
            if dep not in tasks:
                raise ValueError(f"task {tname}: unknown dependency {dep!r}")
        for pname, v in t.get("inputs", {}).get("parameters", {}).items():
            if "taskOutputParameter" in v:
                prod = v["taskOutputParameter"]["producerTask"]
                if prod not in tasks:
                    raise ValueError(
                        f"task {tname}: input {pname} references unknown "
                        f"producer {prod!r}"
                    )
        for cond in t.get("when", []):
            if cond.get("op") not in _OPS:
                raise ValueError(f"task {tname}: bad when operator {cond.get('op')!r}")
            for side in ("lhs", "rhs"):
                prod = cond.get(side, {}).get("taskOutputParameter", {}).get("producerTask")
                if prod is not None and prod not in tasks:
                    raise ValueError(
                        f"task {tname}: when references unknown task {prod!r}"
                    )
        rp = t.get("retryPolicy")
        if rp is not None:
            try:
                n = int(rp.get("maxRetryCount", 0))
            except (TypeError, ValueError, AttributeError):
                raise ValueError(
                    f"task {tname}: malformed retryPolicy {rp!r}"
                ) from None
            if n < 0:
                raise ValueError(f"task {tname}: negative maxRetryCount")
        it = t.get("iterator")
        if it is not None:
            if "itemInput" not in it or "items" not in it:
                raise ValueError(f"task {tname}: malformed iterator")
            prod = it["items"].get("taskOutputParameter", {}).get("producerTask")
            if prod is not None and prod not in tasks:
                raise ValueError(
                    f"task {tname}: iterator references unknown task {prod!r}"
                )
    def all_deps(t: dict) -> set:
        """EVERY edge the runner follows: inputs, explicit deps, when
        predicates (both sides), iterator items."""
        deps = set(t.get("dependentTasks", []))
        refs = list(t.get("inputs", {}).get("parameters", {}).values())
        for cond in t.get("when", []):
            refs += [cond.get("lhs", {}), cond.get("rhs", {})]
        if t.get("iterator") is not None:
            refs.append(t["iterator"].get("items", {}))
        for v in refs:
            if "taskOutputParameter" in v:
                deps.add(v["taskOutputParameter"]["producerTask"])
        return deps

    # nothing may depend on an exit handler: the runner defers exit handlers
    # to the end, so a dependent would read a PENDING (None) output
    exit_tasks = {n for n, t in tasks.items() if t.get("exitHandler")}
    for tname, t in tasks.items():
        bad = all_deps(t) & exit_tasks
        if bad and tname not in exit_tasks:
            raise ValueError(
                f"task {tname}: depends on exit handler(s) {sorted(bad)} "
                f"(exit handlers run last; their outputs cannot feed the DAG)"
            )

    # acyclicity over the SAME edge set the runner's topo sort follows
    state: dict[str, int] = {}

    def visit(n: str) -> None:
        if state.get(n) == 1:
            raise ValueError(f"dependency cycle through task {n!r}")
        if state.get(n) == 2:
            return
        state[n] = 1
        for d in all_deps(tasks[n]):
            visit(d)
        state[n] = 2

    for n in tasks:
        visit(n)
    return ir
