"""Compiler: Pipeline DAG -> IR YAML.

Reference parity (unverified cites, SURVEY.md §2.6): kfp
sdk/python/kfp/compiler/compiler.py lowering the DSL to the PipelineSpec
proto IR. The IR here mirrors PipelineSpec's shape (pipelineInfo / root dag
/ components / deploymentSpec with per-executor source) in plain YAML, and
is the contract the runner and golden-file tests consume — compilation is
pure and cluster-free, the reference's own highest-leverage test seam
(SURVEY.md §4 'golden-file IR tests').
"""

from __future__ import annotations

from typing import Any

import yaml

from kubeflow_tpu.pipelines.dsl import (
    Pipeline,
    PipelineParam,
    TaskOutput,
)

SCHEMA_VERSION = "kubeflow-tpu.org/pipelinespec/v1"


def compile_pipeline(pipeline: Pipeline) -> dict:
    """Lower a traced Pipeline to its IR dict."""
    components: dict[str, Any] = {}
    executors: dict[str, Any] = {}
    tasks: dict[str, Any] = {}

    for task in pipeline.tasks.values():
        manifest = getattr(task.component, "train_job_manifest", None)
        sweep_manifest = getattr(task.component, "sweep_manifest", None)
        if manifest is not None:
            exec_def: dict[str, Any] = {"trainJob": {
                "manifest": manifest,
                "timeoutSeconds": getattr(
                    task.component, "train_job_timeout_s", 3600.0
                ),
            }}
        elif sweep_manifest is not None:
            exec_def = {"sweep": {
                "manifest": sweep_manifest,
                "timeoutSeconds": getattr(
                    task.component, "sweep_timeout_s", 3600.0
                ),
            }}
        else:
            exec_def = {
                "pythonFunction": {
                    "functionName": task.component.fn.__name__,
                    "source": task.component.source,
                }
            }
        comp_key = f"comp-{task.component.name}"
        exec_key = f"exec-{task.component.name}"
        if comp_key in components and executors.get(exec_key) != exec_def:
            # same component NAME, different body (e.g. two train_job steps
            # named alike with different manifests): fall back to the unique
            # task name so neither silently runs the other's executor
            comp_key = f"comp-{task.name}"
            exec_key = f"exec-{task.name}"
        if comp_key not in components:
            comp_def: dict[str, Any] = {
                "executorLabel": exec_key,
                "inputDefinitions": {
                    "parameters": {
                        p: {"parameterType": t}
                        for p, t in task.component.inputs.items()
                    }
                },
            }
            if task.component.output_type is not None:
                comp_def["outputDefinitions"] = {
                    "parameters": {
                        "Output": {"parameterType": task.component.output_type}
                    }
                }
            components[comp_key] = comp_def
            executors[exec_key] = exec_def

        inputs: dict[str, Any] = {}
        for pname, value in task.arguments.items():
            if isinstance(value, TaskOutput):
                inputs[pname] = {
                    "taskOutputParameter": {
                        "producerTask": value.producer,
                        "outputParameterKey": value.key,
                    }
                }
            elif isinstance(value, PipelineParam):
                inputs[pname] = {"componentInputParameter": value.name}
            else:
                inputs[pname] = {"runtimeValue": {"constant": value}}
        entry: dict[str, Any] = {
            "componentRef": {"name": comp_key},
            "inputs": {"parameters": inputs},
        }
        deps = task.dependencies()
        if deps:
            entry["dependentTasks"] = deps
        tasks[task.name] = entry

    ir: dict[str, Any] = {
        "schemaVersion": SCHEMA_VERSION,
        "pipelineInfo": {
            "name": pipeline.name,
            "description": pipeline.description,
        },
        "root": {
            "inputDefinitions": {
                "parameters": {
                    p.name: _root_param(p) for p in pipeline.params.values()
                }
            },
            "dag": {"tasks": tasks},
        },
        "components": components,
        "deploymentSpec": {"executors": executors},
    }
    if pipeline.result is not None:
        ir["root"]["outputFrom"] = {
            "producerTask": pipeline.result.producer,
            "outputParameterKey": pipeline.result.key,
        }
    return ir


def _root_param(p: PipelineParam) -> dict:
    d: dict[str, Any] = {"parameterType": p.param_type}
    if p.default is not None:
        d["defaultValue"] = p.default
    return d


def compile_to_yaml(pipeline: Pipeline) -> str:
    return yaml.safe_dump(compile_pipeline(pipeline), sort_keys=False)


def validate_ir(ir: dict) -> dict:
    """Structural checks the runner relies on (apiserver admission parity)."""
    if ir.get("schemaVersion") != SCHEMA_VERSION:
        raise ValueError(f"unsupported schemaVersion {ir.get('schemaVersion')!r}")
    tasks = ir.get("root", {}).get("dag", {}).get("tasks", {})
    comps = ir.get("components", {})
    executors = ir.get("deploymentSpec", {}).get("executors", {})
    for tname, t in tasks.items():
        cref = t.get("componentRef", {}).get("name")
        if cref not in comps:
            raise ValueError(f"task {tname}: unknown component {cref!r}")
        ex = executors.get(comps[cref].get("executorLabel"))
        if ex is None:
            raise ValueError(f"task {tname}: component {cref} has no executor")
        if not ({"pythonFunction", "trainJob", "sweep"} & set(ex)):
            raise ValueError(f"task {tname}: executor has no known runtime")
        for dep in t.get("dependentTasks", []):
            if dep not in tasks:
                raise ValueError(f"task {tname}: unknown dependency {dep!r}")
        for pname, v in t.get("inputs", {}).get("parameters", {}).items():
            if "taskOutputParameter" in v:
                prod = v["taskOutputParameter"]["producerTask"]
                if prod not in tasks:
                    raise ValueError(
                        f"task {tname}: input {pname} references unknown "
                        f"producer {prod!r}"
                    )
    # acyclicity
    state: dict[str, int] = {}

    def visit(n: str) -> None:
        if state.get(n) == 1:
            raise ValueError(f"dependency cycle through task {n!r}")
        if state.get(n) == 2:
            return
        state[n] = 1
        t = tasks[n]
        deps = set(t.get("dependentTasks", []))
        for v in t.get("inputs", {}).get("parameters", {}).values():
            if "taskOutputParameter" in v:
                deps.add(v["taskOutputParameter"]["producerTask"])
        for d in deps:
            visit(d)
        state[n] = 2

    for n in tasks:
        visit(n)
    return ir
