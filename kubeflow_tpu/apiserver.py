"""Platform API server — the kube-apiserver analogue over HTTP.

Reference parity: the reference's entire L1 surface is a network API
(kube-apiserver CRUD on CRs — SURVEY.md §1; plus the KFP apiserver,
§2.6). This serves the in-process control plane's object store over REST
so that CLIs and SDKs in OTHER processes can drive the platform the way
kubectl/k8s clients drive the reference:

  GET    /healthz | /metrics | /readyz
  GET    /api/v1/{kind}                     list (?namespace=, ?labelSelector=k=v|k==v|k!=v[,..])
  GET    /api/v1/{kind}?watch=true          NDJSON event stream (list+watch:
                                            current objects replay as ADDED;
                                            &timeoutSeconds=N bounds it;
                                            &namespace=/&name= filter)
  GET    /api/v1/{kind}/{ns}/{name}         get
  POST   /api/v1/{kind}                     create (manifest body)
  DELETE /api/v1/{kind}/{ns}/{name}         delete (cascade for jobs/isvc)
  GET    /api/v1/jobs/{ns}/{name}/logs?replicaType=worker&index=0
                                            (&follow=true streams, kubectl logs -f)
  POST   /api/v1/jobs/{ns}/{name}/scale     {"replicas": N}
  GET    /api/v1/events/{ns}/{name}         events for an object

Optimistic-concurrency conflicts surface as 409; admission failures as 422.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubeflow_tpu.api.serde import (
    MANIFEST_KINDS,
    job_from_dict,
    job_to_dict,
    to_dict,
)
from kubeflow_tpu.api.validation import ValidationError, validate_job
from kubeflow_tpu.controller.fakecluster import ConflictError, WatchClosed


def _serialize(kind: str, obj) -> dict:
    if kind == "jobs":
        d = job_to_dict(obj)
        # status matters over the wire even when the spec-serializer would
        # drop a pristine one
        d["status"] = to_dict(obj.status)
        return d
    if kind == "experiments":
        from kubeflow_tpu.sweep.serde import experiment_to_dict

        d = experiment_to_dict(obj)
        d["status"] = to_dict(obj.status)
        return d
    if kind == "inferenceservices":
        from kubeflow_tpu.serving.serde import isvc_to_dict

        d = isvc_to_dict(obj)
        d["status"] = to_dict(obj.status)
        return d
    return to_dict(obj)


def _deserialize(manifest: dict):
    kind = manifest.get("kind", "")
    bucket = MANIFEST_KINDS.get(kind)
    if bucket is None:
        raise ValidationError("kind", f"unknown kind {kind!r}")
    if bucket == "jobs":
        job = job_from_dict(manifest)
        validate_job(job)
        return bucket, job
    if bucket == "profiles":
        from kubeflow_tpu.api.serde import _from_dict
        from kubeflow_tpu.controller.profile import Profile

        body = {k: v for k, v in manifest.items() if k not in ("kind", "apiVersion")}
        return bucket, _from_dict(Profile, body)
    if bucket == "experiments":
        from kubeflow_tpu.sweep.api import validate_experiment
        from kubeflow_tpu.sweep.serde import experiment_from_dict

        exp = experiment_from_dict(manifest)
        validate_experiment(exp)
        return bucket, exp
    if bucket == "inferenceservices":
        from kubeflow_tpu.serving.api import validate_isvc
        from kubeflow_tpu.serving.serde import isvc_from_dict

        isvc = isvc_from_dict(manifest)
        validate_isvc(isvc)
        return bucket, isvc
    if bucket == "pipelineruns":
        from kubeflow_tpu.pipelines.crd import pipelinerun_from_dict

        return bucket, pipelinerun_from_dict(manifest)
    # plain dataclass kinds: PodDefault / Tensorboard / Notebook /
    # PVCViewer / AccessBinding
    from kubeflow_tpu.api.serde import _from_dict
    from kubeflow_tpu.controller.devservers import Notebook, PVCViewer
    from kubeflow_tpu.controller.kfam import AccessBinding, validate_binding
    from kubeflow_tpu.controller.poddefault import PodDefault
    from kubeflow_tpu.controller.tensorboard import Tensorboard

    cls = {
        "poddefaults": PodDefault,
        "tensorboards": Tensorboard,
        "notebooks": Notebook,
        "pvcviewers": PVCViewer,
        "bindings": AccessBinding,
    }[bucket]
    body = {k: v for k, v in manifest.items() if k not in ("kind", "apiVersion")}
    body.pop("status", None)
    obj = _from_dict(cls, body)
    if bucket == "bindings":
        try:
            validate_binding(obj)
        except ValueError as exc:
            raise ValidationError("binding", str(exc)) from exc
    return bucket, obj


_POD_SEGMENT_RE = None


def _pod_log_name(name: str, query: dict) -> str | None:
    """The replica pod name for a logs request, or None when the query
    carries non-label characters (a traversal attempt like
    replicaType=x/../../ns2/victim must never reach the filesystem)."""
    global _POD_SEGMENT_RE
    if _POD_SEGMENT_RE is None:
        import re

        _POD_SEGMENT_RE = re.compile(r"^[a-z0-9]([a-z0-9-]*[a-z0-9])?$")
    rtype = query.get("replicaType", "worker")
    index = query.get("index", "0")
    if not _POD_SEGMENT_RE.match(rtype) or not index.isdigit():
        return None
    return f"{name}-{rtype}-{index}"


def _check_ns_access(cluster, user: str, namespace: str,
                     verb: str) -> tuple[int, dict] | None:
    """The ONE kfam gate both plain and streaming routes call — a
    hand-rolled copy per streaming branch would eventually ship a route
    open. Returns an error reply or None."""
    if not user:
        return None
    from kubeflow_tpu.controller.kfam import check_access

    try:
        check_access(cluster, namespace, user, verb)
    except PermissionError as exc:
        return 403, {"error": str(exc)}
    return None


class _Html(str):
    """String payload the handler serves as text/html (only /ui builds it)."""


class _Asset(tuple):
    """(payload_bytes, content_type) for whitelisted static dashboard files —
    an explicit marker type, same rule as _Html: the reply path never sniffs
    content types from payload bytes."""


def _render_dashboard(platform) -> str:
    """Server-rendered status page (GET /ui/plain) — the no-JS fallback to
    the SPA dashboard at /ui (SURVEY.md §1 L9): one table per object kind,
    no write paths. Auto-refreshes every 5s."""
    import html

    cluster = platform.cluster

    def esc(v) -> str:
        return html.escape(str(v))

    def job_state(j):
        conds = [c.type.value for c in j.status.conditions if c.status]
        return conds[-1] if conds else "-"

    sections = [
        ("Jobs", "jobs", lambda o: (
            o.kind.value, job_state(o),
            f"{sum(r.replicas for r in o.spec.replica_specs.values())} replicas",
        )),
        ("Experiments", "experiments", lambda o: (
            o.spec.algorithm.algorithm_name, o.status.condition.value,
            f"{o.status.trials_succeeded}/{o.status.trials} trials",
        )),
        ("InferenceServices", "inferenceservices", lambda o: (
            o.spec.predictor.runtime.value,
            "Ready" if o.status.ready else "NotReady", o.status.url or "-",
        )),
        ("PipelineRuns", "pipelineruns", lambda o: (
            "-", o.status.state,
            f"{sum(1 for s in o.status.tasks.values() if s in ('Succeeded', 'Cached'))}"
            f"/{len(o.status.tasks)} steps",
        )),
        ("Notebooks", "notebooks", lambda o: (
            "-", "Ready" if o.status.ready else "NotReady", o.status.url or "-",
        )),
        ("Tensorboards", "tensorboards", lambda o: (
            o.spec.logdir, "Ready" if o.status.ready else "NotReady",
            o.status.url or "-",
        )),
    ]
    parts = [
        "<!doctype html><html><head><title>kubeflow_tpu</title>",
        '<meta http-equiv="refresh" content="5">',
        "<style>body{font-family:monospace;margin:2em}table{border-collapse:"
        "collapse;margin-bottom:2em}td,th{border:1px solid #999;padding:4px "
        "10px;text-align:left}th{background:#eee}h2{margin-bottom:4px}"
        "</style></head><body><h1>kubeflow_tpu platform</h1>",
    ]
    for title, kind, row in sections:
        objs = cluster.list(kind)
        parts.append(f"<h2>{title} ({len(objs)})</h2>")
        if not objs:
            continue
        parts.append(
            "<table><tr><th>namespace/name</th><th>detail</th>"
            "<th>state</th><th>info</th></tr>"
        )
        for o in sorted(objs, key=lambda o: (o.metadata.namespace, o.metadata.name)):
            try:
                detail, state, info = row(o)
            except Exception:  # noqa: BLE001 — a bad row must not kill the page
                detail = state = info = "?"
            parts.append(
                f"<tr><td>{esc(o.metadata.namespace)}/{esc(o.metadata.name)}"
                f"</td><td>{esc(detail)}</td><td>{esc(state)}</td>"
                f"<td>{esc(info)}</td></tr>"
            )
        parts.append("</table>")
    parts.append("</body></html>")
    return "".join(parts)


class PlatformServer:
    """Serves a Platform over REST.

    Watch semantics (kube-apiserver `?watch=true` parity — round-1 weak #7:
    remote clients previously had only O(poll)): the stream replays current
    objects as ADDED then tails live events as NDJSON lines
    `{"type": "ADDED|MODIFIED|DELETED", "object": {...}}` until
    timeoutSeconds elapses or the client disconnects.
    """

    def __init__(self, platform, port: int = 8080, host: str = "127.0.0.1"):
        self.platform = platform
        self.host = host
        self.port = port
        self._httpd: ThreadingHTTPServer | None = None

    # ------------------------------------------------------------- routing

    def handle(self, method: str, path: str, body: dict | None,
               user: str = "") -> tuple[int, object]:
        cluster = self.platform.cluster
        parsed = urllib.parse.urlparse(path)
        query = dict(urllib.parse.parse_qsl(parsed.query))
        parts = [p for p in parsed.path.split("/") if p]

        if parsed.path == "/healthz" or parsed.path == "/readyz":
            return 200, {"ok": True}
        if parsed.path == "/kfam/v1/bindings":
            return self._handle_kfam(method, query, body, user)
        if parsed.path == "/ui/plain":
            # explicit marker type — the reply path must NEVER sniff
            # content types from payload bytes (pod logs are attacker text)
            return 200, _Html(_render_dashboard(self.platform))
        if parsed.path == "/ui" or parsed.path == "/ui/":
            from kubeflow_tpu.ui import load_asset

            asset = load_asset("index.html")
            if asset is None:
                return 500, {"error": "dashboard assets missing"}
            return 200, _Asset(asset)
        if parsed.path.startswith("/ui/"):
            from kubeflow_tpu.ui import load_asset

            # load_asset whitelists filenames, so traversal attempts
            # ("/ui/../x", encoded or not) fall through to 404 here
            asset = load_asset(parsed.path[len("/ui/"):])
            if asset is None:
                return 404, {"error": f"no asset {parsed.path!r}"}
            return 200, _Asset(asset)
        if parsed.path == "/metrics":
            from kubeflow_tpu.observability import render_metrics

            return 200, render_metrics(self.platform)  # raw text
        if parsed.path == "/debug/trace":
            # flight-recorder dump: text span tree by default,
            # ?format=chrome for the Perfetto-loadable trace-event JSON
            tracer = getattr(self.platform, "tracer", None)
            if tracer is None:
                return 404, {"error": "tracing is not enabled "
                                      "(Platform.start_tracing)"}
            from kubeflow_tpu.tracing import render_span_tree, to_chrome_trace

            spans = tracer.snapshot()
            if query.get("format") == "chrome":
                return 200, to_chrome_trace(spans, service=tracer.service)
            return 200, render_span_tree(spans)  # raw text
        if parsed.path == "/debug/profile":
            # trace analytics over the same recorder (+ worker flushes in
            # the tracer's trace_dir): step-time breakdown, goodput,
            # control-plane percentiles, restart attribution — JSON by
            # default, ?format=text for the operator table. The numbers
            # are the ones `kftpu profile` and kftpu_prof_* serve
            # (kubeflow_tpu/profiling, docs/profiling.md).
            if getattr(self.platform, "tracer", None) is None:
                return 404, {"error": "tracing is not enabled "
                                      "(Platform.start_tracing)"}
            from kubeflow_tpu.profiling import profile_platform, render_text

            prof = profile_platform(self.platform)
            if query.get("format") == "text":
                return 200, render_text(prof)  # raw text
            return 200, prof
        if parsed.path == "/debug/slo":
            # SLO burn-rate report + per-request breakdown over the same
            # recorder — JSON by default, ?format=text for the operator
            # table. One build path with the `slo` CLI
            # (monitoring/report.build_slo_report; docs/slo.md). Serves
            # the request breakdown even before start_slo(); 404 only
            # when there is no tracing to read requests from either.
            if getattr(self.platform, "tracer", None) is None \
                    and getattr(self.platform, "slo_monitor", None) is None:
                return 404, {"error": "neither tracing nor the SLO "
                                      "monitor is enabled "
                                      "(Platform.start_tracing / "
                                      "Platform.start_slo)"}
            from kubeflow_tpu.monitoring import (
                build_slo_report,
                render_slo_text,
            )

            report = build_slo_report(self.platform)
            if query.get("format") == "text":
                return 200, render_slo_text(report)  # raw text
            return 200, report
        if parsed.path == "/debug/sched":
            # chip-scheduler report: inventory, claim table, per-tenant
            # fair-share accounting, decision counters — JSON by
            # default, ?format=text for the operator table. One build
            # path with the `sched` CLI (scheduler/report
            # .build_sched_report; docs/scheduler.md).
            if getattr(self.platform, "chip_scheduler", None) is None:
                return 404, {"error": "platform has no chip scheduler"}
            from kubeflow_tpu.scheduler import (
                build_sched_report,
                render_sched_text,
            )

            report = build_sched_report(self.platform)
            if query.get("format") == "text":
                return 200, render_sched_text(report)  # raw text
            return 200, report
        if len(parts) < 3 or parts[0] != "api" or parts[1] != "v1":
            return 404, {"error": f"no route {parsed.path!r}"}
        kind = parts[2]

        # -------- kfam authz: every namespaced verb maps here, BEFORE any
        # route handling, so new routes are covered by construction. Only
        # enforced when the caller asserts an identity (kubeflow-userid);
        # profiles/namespaces stay platform-admin surfaces.
        if user and kind not in ("profiles", "namespaces"):
            from kubeflow_tpu.controller.kfam import role_of

            verb_ns: tuple[str, str] | None = None
            if method == "GET" and len(parts) >= 5:
                verb_ns = ("get", parts[3])  # object GET, events, logs
            elif method == "POST" and len(parts) == 3 and body is not None:
                ns = (body.get("metadata") or {}).get("namespace", "default")
                verb_ns = ("create", ns)
            elif method == "POST" and len(parts) == 6:
                verb_ns = ("scale", parts[3])
            elif method == "DELETE" and len(parts) == 5:
                verb_ns = ("delete", parts[3])
            if verb_ns is not None:
                err = _check_ns_access(cluster, user, verb_ns[1],
                                       verb_ns[0])
                if err is not None:
                    return err
                # bindings grant access — managing them needs the SAME
                # admin gate as /kfam/v1/bindings, or any edit-role user
                # could grant themselves admin through this route
                if (kind == "bindings"
                        and verb_ns[0] in ("create", "delete")
                        and cluster.get(
                            "profiles", f"default/{verb_ns[1]}") is not None
                        and role_of(cluster, verb_ns[1], user) != "admin"):
                    return 403, {"error":
                                 f"user {user!r} is not an admin of "
                                 f"{verb_ns[1]!r}"}

        # -------- events
        if kind == "events" and len(parts) == 5:
            evs = cluster.events_for(f"{parts[3]}/{parts[4]}")
            return 200, [
                {"reason": e.reason, "message": e.message, "type": e.type,
                 "timestamp": e.timestamp}
                for e in evs
            ]

        if kind not in cluster.KINDS:
            return 404, {"error": f"unknown kind {kind!r}"}

        # -------- run lineage graph (MLMD read side)
        if (kind == "pipelineruns" and len(parts) == 6
                and parts[5] == "lineage" and method == "GET"):
            cr = cluster.get("pipelineruns", f"{parts[3]}/{parts[4]}")
            if cr is None:
                return 404, {"error":
                             f"pipelinerun {parts[3]}/{parts[4]} not found"}
            if not cr.status.run_id:
                return 404, {"error": "run has no lineage yet (no run id)"}
            ctrl = self.platform.controllers.get("pipelinerun")
            if ctrl is None:
                return 404, {"error": "pipelines application is disabled"}
            from kubeflow_tpu.pipelines.lineage import run_lineage

            return 200, run_lineage(ctrl.metadata_store(),
                                    cr.status.run_id)

        # -------- run visualization report (KFP viz-server analogue)
        if (kind == "pipelineruns" and len(parts) == 6
                and parts[5] == "report" and method == "GET"):
            cr = cluster.get("pipelineruns", f"{parts[3]}/{parts[4]}")
            if cr is None:
                return 404, {"error":
                             f"pipelinerun {parts[3]}/{parts[4]} not found"}
            ctrl = self.platform.controllers.get("pipelinerun")
            result = (ctrl.result_for(parts[3], parts[4])
                      if ctrl is not None else None)
            # identity check: the retained result must belong to THIS CR's
            # finished run — a deleted-and-recreated run of the same name
            # must never serve the old run's report
            if (result is None or not cr.status.run_id
                    or getattr(result, "run_id", "") != cr.status.run_id):
                return 404, {"error":
                             "no retained result for this run (it did not "
                             "finish in this platform process)"}
            from kubeflow_tpu.pipelines.viz import render_run_report

            return 200, _Html(render_run_report(
                result, pipeline_name=cr.spec.pipeline_spec.get(
                    "pipelineInfo", {}).get("name", "")))

        # -------- subresources on jobs
        if kind == "jobs" and len(parts) == 6 and parts[5] == "logs" and method == "GET":
            if cluster.get("jobs", f"{parts[3]}/{parts[4]}") is None:
                return 404, {"error": f"job {parts[3]}/{parts[4]} not found"}
            pod_name = _pod_log_name(parts[4], query)
            if pod_name is None:
                return 400, {"error": "replicaType/index must be a label "
                                      "and a number"}
            return 200, self.platform._read_pod_log(pod_name, parts[3])  # raw text
        if kind == "jobs" and len(parts) == 6 and parts[5] == "scale" and method == "POST":
            from kubeflow_tpu.client import TrainingClient

            try:
                job = TrainingClient(self.platform).scale_job(
                    parts[4], int((body or {}).get("replicas", 0)), parts[3]
                )
            except KeyError:
                return 404, {"error": f"job {parts[3]}/{parts[4]} not found"}
            except ValueError as exc:
                return 422, {"error": str(exc)}
            return 200, _serialize("jobs", job)

        # -------- CRUD
        if method == "GET" and len(parts) == 3:
            objs = cluster.list(kind)
            if user:
                # cross-namespace listing shows only what the caller may
                # read (upstream dashboard posture), never a blanket 403
                from kubeflow_tpu.controller.kfam import can_read

                objs = [o for o in objs
                        if can_read(cluster, o.metadata.namespace, user)]
            if "namespace" in query:
                objs = [o for o in objs
                        if o.metadata.namespace == query["namespace"]]
            if "labelSelector" in query:
                # kubectl equality selectors: k=v | k==v | k!=v, comma-ANDed
                terms: list[tuple[str, str, bool]] = []
                for pair in query["labelSelector"].split(","):
                    if not pair:
                        return 400, {"error":
                                     "labelSelector has an empty term"}
                    if "!=" in pair:
                        k, _, v = pair.partition("!=")
                        eq = False
                    elif "==" in pair:
                        k, _, v = pair.partition("==")
                        eq = True
                    elif "=" in pair:
                        k, _, v = pair.partition("=")
                        eq = True
                    else:
                        return 400, {"error":
                                     "labelSelector must be "
                                     "k=v|k==v|k!=v[,more]"}
                    if not k:
                        return 400, {"error":
                                     "labelSelector term has an empty key"}
                    terms.append((k, v, eq))

                def matches(o) -> bool:
                    labels = o.metadata.labels or {}
                    for k, v, eq in terms:
                        if eq and labels.get(k) != v:
                            return False
                        # k8s != semantics: a MISSING key satisfies !=
                        if not eq and labels.get(k) == v:
                            return False
                    return True

                objs = [o for o in objs if matches(o)]
            return 200, [_serialize(kind, o) for o in objs]
        if method == "GET" and len(parts) == 5:
            obj = cluster.get(kind, f"{parts[3]}/{parts[4]}")
            if obj is None:
                return 404, {"error": f"{kind} {parts[3]}/{parts[4]} not found"}
            return 200, _serialize(kind, obj)
        if method == "POST" and len(parts) == 3:
            if body is None:
                return 400, {"error": "manifest body required"}
            try:
                bucket, obj = _deserialize(body)
            except (ValidationError, ValueError) as exc:
                return 422, {"error": str(exc)}
            if bucket != kind:
                return 422, {"error": f"manifest kind belongs to {bucket!r}, not {kind!r}"}
            if kind == "jobs":
                from kubeflow_tpu.controller.profile import check_job_admission

                try:
                    check_job_admission(cluster, obj)
                except ValueError as exc:
                    return 422, {"error": str(exc)}
            try:
                cluster.create(kind, obj)
            except KeyError as exc:
                return 409, {"error": str(exc)}
            return 201, _serialize(kind, obj)
        if method == "DELETE" and len(parts) == 5:
            key = f"{parts[3]}/{parts[4]}"
            if cluster.get(kind, key) is None:
                return 404, {"error": f"{kind} {key} not found"}
            if kind == "jobs":
                from kubeflow_tpu.controller.jobcontroller import delete_job_cascade

                delete_job_cascade(cluster, parts[4], parts[3])
            elif kind == "inferenceservices":
                from kubeflow_tpu.serving import ServingClient

                ServingClient(self.platform).delete(parts[4], parts[3])
            elif kind == "experiments":
                from kubeflow_tpu.sweep import SweepClient

                SweepClient(self.platform).delete_experiment(parts[4], parts[3])
            else:
                cluster.delete(kind, key)
            return 200, {"deleted": key}
        return 405, {"error": f"{method} not supported on {parsed.path!r}"}

    # --------------------------------------------------------------- kfam

    def _handle_kfam(self, method: str, query: dict, body: dict | None,
                     user: str) -> tuple[int, object]:
        """The kfam access-management REST surface (upstream
        components/access-management): GET lists Bindings in the upstream
        wire shape, POST/DELETE manage a contributor's role. Managing a
        namespace's bindings requires its admin role when the caller
        asserts an identity."""
        from kubeflow_tpu.controller.kfam import (
            bindings_for,
            can_read,
            from_kfam_dict,
            role_of,
            to_kfam_dict,
        )

        cluster = self.platform.cluster
        if method == "GET":
            ns = query.get("namespace", "")
            if ns:
                if user and not can_read(cluster, ns, user):
                    return 403, {"error":
                                 f"user {user!r} has no role in {ns!r}"}
                items = bindings_for(cluster, ns)
            else:
                # the contributor roster is per-namespace information:
                # identified callers see only namespaces they can read
                items = [b for b in cluster.list("bindings")
                         if not user
                         or can_read(cluster, b.metadata.namespace, user)]
            return 200, {"bindings": [to_kfam_dict(b) for b in items]}
        if method not in ("POST", "DELETE"):
            return 405, {"error": f"{method} not supported on kfam"}
        if body is None:
            return 400, {"error": "kfam Binding body required"}
        try:
            b = from_kfam_dict(body)
        except ValueError as exc:
            return 422, {"error": str(exc)}
        ns = b.metadata.namespace
        if cluster.get("profiles", f"default/{ns}") is None:
            return 404, {"error": f"namespace {ns!r} has no profile"}
        if user and role_of(cluster, ns, user) != "admin":
            return 403, {"error":
                         f"user {user!r} is not an admin of {ns!r}"}
        key = f"{ns}/{b.metadata.name}"
        if method == "POST":
            if cluster.get("bindings", key) is not None:
                return 409, {"error": f"binding {key} already exists"}
            cluster.create("bindings", b)
            return 201, to_kfam_dict(b)
        if cluster.get("bindings", key) is None:
            return 404, {"error": f"binding {key} not found"}
        cluster.delete("bindings", key)
        return 200, {"deleted": key}

    # --------------------------------------------------------------- logs

    def stream_logs(self, wfile, namespace: str, name: str,
                    pod_name: str, timeout_s: float) -> None:
        """kubectl `logs -f` analogue: tail the replica's log file,
        streaming appended bytes until the pod reaches a terminal phase
        or the JOB finishes/vanishes (plus one final drain), or the
        client disconnects. A pod that has not been CREATED yet (the
        reconcile race right after submit) is waited on, not treated as
        terminal."""
        from kubeflow_tpu.controller.fakecluster import PodPhase
        from kubeflow_tpu.utils.retry import BackoffPolicy, Deadline, backoff_sleep

        cluster = self.platform.cluster
        path = self.platform.pod_runtime.log_path(pod_name, namespace)
        deadline = Deadline(timeout_s)
        # responsive while the pod is chatty, settling to a gentle 200ms
        # tail poll; half jitter so N concurrent follows don't phase-lock
        # on the store lock (same rationale as POLL_POLICY)
        poll = BackoffPolicy(base_s=0.02, max_s=0.2, multiplier=2.0, jitter=0.5)
        attempt = 0
        offset = 0
        try:
            while not deadline.expired():
                pod = cluster.get("pods", f"{namespace}/{pod_name}")
                job = cluster.get("jobs", f"{namespace}/{name}")
                done = (
                    (pod is not None and pod.status.phase in (
                        PodPhase.SUCCEEDED, PodPhase.FAILED))
                    or job is None or job.status.is_finished
                )
                try:
                    with open(path, "rb") as fh:
                        fh.seek(offset)
                        chunk = fh.read()
                except OSError:
                    chunk = b""
                if chunk:
                    wfile.write(chunk)
                    wfile.flush()
                    offset += len(chunk)
                    attempt = 0  # pod is chatty: snap back to the fast poll
                if done:
                    return  # terminal phase AND the tail fully drained
                backoff_sleep(poll, attempt, deadline=deadline)
                attempt += 1
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass  # client went away — normal follow termination

    # -------------------------------------------------------------- watch

    @staticmethod
    def _parse_watch_selector(raw: str):
        """labelSelector for watch streams: k=v | k==v (equality) | bare
        k (key-presence), comma-ANDed — the subset the hub can push down
        server-side. Returns (selector_or_None, error_or_None); k!=v (the
        list endpoint's negation form) is rejected up front because a
        stream cannot signal 400 after its headers go out."""
        if not raw:
            return None, None
        selector: dict[str, str | None] = {}
        for term in raw.split(","):
            term = term.strip()
            if not term:
                return None, "labelSelector has an empty term"
            if "!=" in term:
                return None, ("labelSelector negation (k!=v) is not "
                              "supported on watch streams")
            if "==" in term:
                k, _, v = term.partition("==")
            elif "=" in term:
                k, _, v = term.partition("=")
            else:
                k, v = term, None  # presence
            if not k:
                return None, "labelSelector term has an empty key"
            selector[k] = v
        return selector, None

    def stream_watch(self, wfile, kind: str, query: dict,
                     user: str = "", request_id: str = "") -> None:
        """Write an NDJSON watch stream for one kind until timeout/disconnect.
        Identified callers only see namespaces kfam lets them read. Every
        event line carries the stream's requestId (the trace-context
        carrier), so a client can attribute events to its own watch call.

        Keepalive contract: when no event has been written for
        keepaliveSeconds (default 10, clamp [0.5, 60]), a
        {"type": "KEEPALIVE"} line goes out instead — so a QUIET stream and
        a DEAD connection are distinguishable client-side (remote.py treats
        a stream silent past the keepalive budget as gone and relists)."""
        import queue as queue_mod
        import time

        from kubeflow_tpu.controller.kfam import can_read

        cluster = self.platform.cluster
        ns_filter = query.get("namespace", "")
        name_filter = query.get("name", "")
        # validated by _parse_watch_selector in the dispatch (a stream
        # cannot 400 after its headers went out); pushed down to the
        # store's watch hub together with the kind, so this stream's
        # buffer only ever holds events it would emit
        selector, _err = self._parse_watch_selector(
            query.get("labelSelector", ""))
        try:
            timeout_s = min(float(query.get("timeoutSeconds", "60")), 600.0)
        except ValueError:
            timeout_s = 60.0
        try:
            keepalive_s = min(
                max(float(query.get("keepaliveSeconds", "10")), 0.5), 60.0)
        except ValueError:
            keepalive_s = 10.0
        deadline = time.monotonic() + timeout_s

        def want(obj) -> bool:
            meta = getattr(obj, "metadata", None)
            if meta is None:
                return False
            if ns_filter and meta.namespace != ns_filter:
                return False
            if name_filter and meta.name != name_filter:
                return False
            if user and not can_read(cluster, meta.namespace, user):
                return False
            return True

        # server-side filtering end-to-end: the hub never buffers other
        # kinds (or non-matching labels) for this stream, so one slow REST
        # watcher of a quiet kind no longer pays for a pod storm
        q = cluster.watch(replay=True, kinds=(kind,),
                          label_selector=selector)
        last_write = time.monotonic()
        try:
            while time.monotonic() < deadline:
                # keepalive check BEFORE the blocking get, so a queue kept
                # busy by filtered-out events (other kinds/namespaces) still
                # honors the one-line-per-keepalive_s contract — an idle
                # stream on a churning cluster must not look dead
                if time.monotonic() - last_write >= keepalive_s:
                    record = {"type": "KEEPALIVE"}
                    if request_id:
                        record["requestId"] = request_id
                    wfile.write((json.dumps(record) + "\n").encode())
                    wfile.flush()
                    last_write = time.monotonic()
                try:
                    etype, ekind, obj = q.get(
                        timeout=min(0.5, keepalive_s / 2.0,
                                    max(deadline - time.monotonic(), 0.01))
                    )
                except queue_mod.Empty:
                    continue
                except WatchClosed:
                    # subscription died at the hub (GONE/closed) — end the
                    # stream cleanly; the client relists on reconnect, the
                    # same contract as the server-side timeout
                    break
                if ekind != kind or not want(obj):
                    continue
                record = {
                    "type": etype.name
                    if hasattr(etype, "name") else str(etype),
                    "object": _serialize(kind, obj),
                }
                if request_id:
                    record["requestId"] = request_id
                line = json.dumps(record) + "\n"
                wfile.write(line.encode())
                wfile.flush()
                last_write = time.monotonic()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass  # client went away — normal watch termination
        finally:
            cluster.unwatch(q)

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "PlatformServer":
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _dispatch(self, method):
                # every request gets an id (assigned here when the caller
                # didn't send one) — echoed on ALL responses and error
                # bodies, and stamped onto the request's trace span: this
                # is the trace-context carrier across the HTTP boundary
                rid = self.headers.get("X-Request-Id", "")
                if not rid:
                    import uuid

                    rid = uuid.uuid4().hex[:16]
                self._request_id = rid
                # watch requests stream — they never go through _reply
                parsed = urllib.parse.urlparse(self.path)
                query = dict(urllib.parse.parse_qsl(parsed.query))
                parts = [p for p in parsed.path.split("/") if p]
                if (
                    method == "GET"
                    and query.get("watch") in ("true", "1")
                    and len(parts) == 3
                    and parts[0] == "api" and parts[1] == "v1"
                ):
                    kind = parts[2]
                    if kind not in server.platform.cluster.KINDS:
                        self._reply(404, {"error": f"unknown kind {kind!r}"})
                        return
                    # selector validation must precede the 200: a stream
                    # cannot change its status code once headers are out
                    _sel, sel_err = server._parse_watch_selector(
                        query.get("labelSelector", ""))
                    if sel_err is not None:
                        self._reply(400, {"error": sel_err})
                        return
                    self.send_response(200)
                    self.send_header("Content-Type", "application/x-ndjson")
                    self.send_header("Transfer-Encoding", "identity")
                    self.send_header("Connection", "close")
                    self.send_header("X-Request-Id", rid)
                    self.end_headers()
                    from kubeflow_tpu.tracing import tracer_of

                    with tracer_of(server.platform).span(
                        "http.watch", kind=kind, request_id=rid,
                    ):
                        server.stream_watch(
                            self.wfile, kind, query,
                            user=self.headers.get("kubeflow-userid", ""),
                            request_id=rid,
                        )
                    return
                if (
                    method == "GET"
                    and query.get("follow") in ("true", "1")
                    and len(parts) == 6
                    and parts[:3] == ["api", "v1", "jobs"]
                    and parts[5] == "logs"
                ):
                    # everything that can fail is decided BEFORE the 200
                    # headers go out — a streaming response cannot change
                    # its status code later
                    err = _check_ns_access(
                        server.platform.cluster,
                        self.headers.get("kubeflow-userid", ""),
                        parts[3], "get")
                    if err is not None:
                        self._reply(*err)
                        return
                    if server.platform.cluster.get(
                            "jobs", f"{parts[3]}/{parts[4]}") is None:
                        self._reply(404, {"error":
                                          f"job {parts[3]}/{parts[4]} "
                                          "not found"})
                        return
                    pod_name = _pod_log_name(parts[4], query)
                    if pod_name is None:
                        self._reply(400, {"error":
                                          "replicaType/index must be a "
                                          "label and a number"})
                        return
                    try:
                        timeout_s = min(
                            max(float(query.get("timeoutSeconds", "3600")),
                                1.0), 86400.0)
                    except ValueError:
                        self._reply(400, {"error":
                                          "timeoutSeconds must be a "
                                          "number"})
                        return
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; charset=utf-8")
                    self.send_header("Transfer-Encoding", "identity")
                    self.send_header("Connection", "close")
                    self.send_header("X-Request-Id", rid)
                    self.end_headers()
                    server.stream_logs(self.wfile, parts[3], parts[4],
                                       pod_name, timeout_s)
                    return
                self._dispatch_plain(method)

            def _dispatch_plain(self, method):
                body = None
                length = int(self.headers.get("Content-Length", 0))
                if length:
                    try:
                        body = json.loads(self.rfile.read(length))
                    except json.JSONDecodeError as exc:
                        self._reply(400, {"error": f"bad json: {exc}"})
                        return
                # the span makes every cluster write this request performs
                # carry the request's context: downstream watch deliveries
                # and reconcile passes parent-link back to this API call
                from kubeflow_tpu.tracing import tracer_of

                with tracer_of(server.platform).span(
                    "http.request", method=method, path=self.path,
                    request_id=self._request_id,
                ) as sp:
                    try:
                        code, payload = server.handle(
                            method, self.path, body,
                            user=self.headers.get("kubeflow-userid", ""),
                        )
                    except ConflictError as exc:
                        code, payload = 409, {"error": str(exc)}
                    except Exception as exc:  # noqa: BLE001 — surface as 500
                        code, payload = 500, {
                            "error": f"{type(exc).__name__}: {exc}"}
                    sp.set_attribute("status", code)
                self._reply(code, payload)

            def _reply(self, code, payload):
                rid = getattr(self, "_request_id", "")
                if (rid and isinstance(payload, dict) and "error" in payload):
                    payload.setdefault("requestId", rid)
                if isinstance(payload, _Asset):
                    data, ctype = payload
                elif isinstance(payload, _Html):
                    data, ctype = payload.encode(), "text/html"
                elif isinstance(payload, str):
                    data, ctype = payload.encode(), "text/plain"
                else:
                    data, ctype = json.dumps(payload).encode(), "application/json"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                if rid:
                    self.send_header("X-Request-Id", rid)
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802
                self._dispatch("GET")

            def do_POST(self):  # noqa: N802
                self._dispatch("POST")

            def do_DELETE(self):  # noqa: N802
                self._dispatch("DELETE")

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
