"""ExperimentController — experiment/trial reconciliation.

Reference parity (unverified cites, SURVEY.md §2.4, §3.3): katib
pkg/controller.v1beta1/experiment/experiment_controller.go (creates trials
from suggestions, tracks optimal) + trial/trial_controller.go (watches the
underlying job, extracts the objective). One controller owns both loops here
because the Suggestion hop is in-process.

Trial jobs are ordinary TrainJobs reconciled by the same JobController as
user jobs — the sweep engine composes with, not bypasses, the control plane.
"""

from __future__ import annotations

import math
import statistics
import zlib
from typing import Callable

from kubeflow_tpu.api.serde import job_from_yaml
from kubeflow_tpu.api.validation import validate_job
from kubeflow_tpu.controller.base import ControllerBase
from kubeflow_tpu.controller.fakecluster import FakeCluster
from kubeflow_tpu.controller.jobcontroller import delete_job_cascade
from kubeflow_tpu.sweep.api import (
    Experiment,
    ExperimentCondition,
    ObjectiveType,
    OptimalTrial,
    ParameterAssignment,
    Trial,
    TrialCondition,
    TrialSpec,
    render_trial_spec,
    scalarized_objective,
)
from kubeflow_tpu.api.common import ObjectMeta, utcnow as _now
from kubeflow_tpu.sweep.collector import observation_from_log
from kubeflow_tpu.sweep.suggest import get_suggester

EXPERIMENT_LABEL = "kubeflow-tpu.org/experiment-name"


class ExperimentController(ControllerBase):
    """Reconciles experiments: suggest -> render -> launch -> observe."""

    WATCH_SELECTORS = {"experiments": None,
                       "trials": {EXPERIMENT_LABEL: None},
                       "jobs": {EXPERIMENT_LABEL: None},
                       "pods": {EXPERIMENT_LABEL: None}}

    ERROR_EVENT_KIND = "experiments"

    def __init__(
        self,
        cluster: FakeCluster,
        log_reader: Callable[[str, str], str],
        workers: int = 1,
        resync_period_s: float = 0.5,
        observation_db: str | None = None,
        suggestion_endpoint: str | None = None,
    ):
        # resync doubles as the early-stopping poller: running trials' live
        # logs are only re-examined on reconcile
        super().__init__(
            cluster, name="exp", workers=workers, resync_period_s=resync_period_s,
            wq_max_delay_s=5.0,
        )
        self.log_reader = log_reader
        # durable observation log (katib db-manager parity, sweep/store.py);
        # opened lazily so platforms that never sweep pay nothing
        self._observation_db = observation_db
        self._observations = None
        # None => in-process suggesters; an address restores katib's
        # suggestion-service-over-gRPC topology (sweep/rpc.py). Created
        # eagerly: reconcile workers run concurrently and a lazy init would
        # race/leak channels.
        self._suggestion_client = None
        if suggestion_endpoint:
            from kubeflow_tpu.sweep.rpc import SuggestionClient

            self._suggestion_client = SuggestionClient(suggestion_endpoint)
        # finished trials' logs are immutable: cache their objective
        # timelines so the medianstop hot path isn't O(trials) file reads
        self._timeline_cache: dict[str, list[float]] = {}
        # key -> uid so a delete-while-running can still evict its entries
        self._uid_by_key: dict[str, str] = {}
        self.metrics.update({
            "experiments_created_total": 0,
            "experiments_succeeded_total": 0,
            "experiments_failed_total": 0,
            "trials_created_total": 0,
            "trials_early_stopped_total": 0,
        })

    # -------------------------------------------------------------- informer

    def kind_filter(self, etype, kind: str, obj) -> str | None:
        if kind == "experiments":
            return self.cluster._key(obj)
        if kind in ("trials", "jobs", "pods"):
            exp_name = obj.metadata.labels.get(EXPERIMENT_LABEL)
            if exp_name:
                return f"{obj.metadata.namespace}/{exp_name}"
        return None

    def resync_keys(self):
        return [
            self.cluster._key(e)
            for e in self.cluster.list("experiments")
            if not e.status.is_finished
        ]

    # ------------------------------------------------------------- reconcile

    def reconcile(self, key: str) -> float | None:
        exp: Experiment | None = self.cluster.get("experiments", key, copy_obj=True)
        if exp is None:
            uid = self._uid_by_key.pop(key, None)
            if uid is not None:
                self._drop_timelines(uid)
            return None
        self._uid_by_key[key] = exp.metadata.uid
        st = exp.status
        entry = _exp_fingerprint(st)
        if st.condition == ExperimentCondition.CREATED and not st.start_time:
            # persist-then-emit: a conflicting/failing pass must not replay
            # the created counter/event
            st.start_time = _now()
            exp = self.cluster.update("experiments", exp)
            st = exp.status
            self.metrics["experiments_created_total"] += 1
            self.cluster.record_event("experiments", key, "ExperimentCreated", "created")

        trials = self._owned_trials(exp)
        if not trials and not st.is_finished:
            restored = self._restore_trials(exp)
            if restored:
                trials = self._owned_trials(exp)
        if st.is_finished:
            self._kill_running(exp, trials)
            return None

        # -- sync each trial with its underlying job
        for t in trials:
            if not t.status.is_finished:
                self._sync_trial(exp, t)
        trials = self._owned_trials(exp)

        # -- early stopping (medianstop)
        if exp.spec.early_stopping is not None:
            self._median_stop(exp, trials)
            trials = self._owned_trials(exp)

        # -- aggregate status
        finished = [t for t in trials if t.status.is_finished]
        succeeded = [t for t in trials if t.status.condition == TrialCondition.SUCCEEDED]
        failed = [
            t for t in trials
            if t.status.condition
            in (TrialCondition.FAILED, TrialCondition.METRICS_UNAVAILABLE)
        ]
        st.trials = len(trials)
        st.trials_running = len(trials) - len(finished)
        st.trials_succeeded = len(succeeded)
        st.trials_failed = len(failed)
        st.trials_early_stopped = sum(
            1 for t in trials if t.status.condition == TrialCondition.EARLY_STOPPED
        )
        best = self._optimal(exp, succeeded)
        if best is not None:
            st.current_optimal_trial = best
        st.pareto_front = self._pareto_front(exp, succeeded)

        # -- termination
        obj = exp.spec.objective
        # the goal reads the PRIMARY metric of the optimal trial (multi-
        # objective scalarization picks the trial; the goal stays a
        # primary-metric contract, matching katib's single-goal semantics)
        goal_met = (
            best is not None
            and obj.goal is not None
            and _better_or_equal(
                obj.type,
                best.observation.metric(obj.objective_metric_name).latest,
                obj.goal,
            )
        )
        if goal_met:
            return self._finish(
                exp, key, trials, ExperimentCondition.SUCCEEDED, "GoalReached"
            )
        # katib semantics: the experiment fails once the failed-trial count
        # REACHES maxFailedTrialCount (inclusive bound); 0 = fail-fast on the
        # first failure, negative = never fail on trial failures
        fc = exp.spec.max_failed_trial_count
        if fc >= 0 and len(failed) >= max(fc, 1):
            return self._finish(
                exp, key, trials, ExperimentCondition.FAILED, "MaxFailedTrialsReached"
            )
        if len(finished) >= exp.spec.max_trial_count:
            return self._finish(
                exp, key, trials, ExperimentCondition.SUCCEEDED, "MaxTrialsReached"
            )

        # -- spawn new trials up to parallelism
        active = len(trials) - len(finished)
        budget = min(
            exp.spec.parallel_trial_count - active,
            exp.spec.max_trial_count - len(trials),
        )
        created = 0
        if budget > 0:
            created = self._spawn_trials(exp, trials, budget)
            if created == 0 and active == 0:
                # search space exhausted (grid): wrap up with what we have
                return self._finish(
                    exp, key, trials, ExperimentCondition.SUCCEEDED, "SpaceExhausted"
                )
        if st.condition == ExperimentCondition.CREATED and trials:
            st.condition = ExperimentCondition.RUNNING
        if _exp_fingerprint(st) != entry:
            self.cluster.update("experiments", exp)
        return 0.2 if created else None

    # -------------------------------------------------- durable observations

    def _store(self):
        if self._observation_db and self._observations is None:
            from kubeflow_tpu.sweep.store import ObservationStore

            self._observations = ObservationStore(self._observation_db)
        return self._observations

    def _persist(self, exp: Experiment, trial: Trial) -> None:
        store = self._store()
        if store is not None and trial.status.is_finished:
            try:
                store.record(exp, trial)
            except Exception as exc:  # noqa: BLE001 — durability is best-effort
                self.cluster.record_event(
                    "experiments", self.cluster._key(exp), "ObservationStoreError",
                    f"{type(exc).__name__}: {exc}", type="Warning",
                )

    def _restore_trials(self, exp: Experiment) -> int:
        store = self._store()
        if store is None:
            return 0
        n = 0
        for t in store.restore(exp):
            try:
                self.cluster.create("trials", t)
                n += 1
            except KeyError:
                pass  # already present
        if n:
            self.cluster.record_event(
                "experiments", self.cluster._key(exp), "HistoryRestored",
                f"restored {n} finished trial(s) from the observation store",
            )
        return n

    def stop(self) -> None:
        super().stop()
        if self._observations is not None:
            self._observations.close()
            self._observations = None
        if self._suggestion_client is not None:
            self._suggestion_client.close()
            self._suggestion_client = None

    # ------------------------------------------------------------- sub-steps

    def _owned_trials(self, exp: Experiment) -> list[Trial]:
        return sorted(
            self.cluster.list(
                "trials",
                lambda t: t.metadata.labels.get(EXPERIMENT_LABEL)
                == exp.metadata.name
                and t.metadata.namespace == exp.metadata.namespace,
            ),
            key=lambda t: t.metadata.name,
        )

    def _sync_trial(self, exp: Experiment, trial: Trial) -> None:
        tkey = f"{trial.metadata.namespace}/{trial.metadata.name}"
        trial = self.cluster.get("trials", tkey, copy_obj=True)
        if trial is None:
            return
        job = self.cluster.get("jobs", tkey)
        changed = False
        if job is None:
            # Job vanished (TTL cleanup, manual delete) or was never admitted.
            # A finished run leaves its verdict in the log — recover it rather
            # than re-running a completed trial.
            obs = self._observe(exp, trial)
            obj_name = exp.spec.objective.objective_metric_name
            if obs.metric(obj_name) is not None:
                trial.status.condition = TrialCondition.SUCCEEDED
                trial.status.observation = obs
                trial.status.completion_time = _now()
                changed = True
            elif trial.status.condition == TrialCondition.CREATED:
                try:
                    self._create_trial_job(exp, trial)
                except Exception as exc:  # noqa: BLE001 — bad template => trial fails
                    trial.status.condition = TrialCondition.FAILED
                    trial.status.completion_time = _now()
                    self.cluster.record_event(
                        "trials", tkey, "TrialJobInvalid", str(exc), type="Warning"
                    )
                    changed = True
            else:
                trial.status.condition = TrialCondition.FAILED
                trial.status.completion_time = _now()
                self.cluster.record_event(
                    "trials", tkey, "TrialJobLost",
                    "underlying job disappeared without metrics", type="Warning",
                )
                changed = True
        elif job.status.is_succeeded:
            obs = self._observe(exp, trial)
            obj_name = exp.spec.objective.objective_metric_name
            if obs.metric(obj_name) is not None:
                trial.status.condition = TrialCondition.SUCCEEDED
            else:
                trial.status.condition = TrialCondition.METRICS_UNAVAILABLE
                self.cluster.record_event(
                    "trials", tkey, "MetricsUnavailable",
                    f"objective {obj_name!r} not found in trial log",
                    type="Warning",
                )
            trial.status.observation = obs
            trial.status.completion_time = _now()
            changed = True
        elif job.status.is_failed:
            trial.status.condition = TrialCondition.FAILED
            trial.status.observation = self._observe(exp, trial)
            trial.status.completion_time = _now()
            changed = True
        elif trial.status.condition == TrialCondition.CREATED:
            from kubeflow_tpu.api.common import JobConditionType

            if job.status.has_condition(JobConditionType.RUNNING):
                trial.status.condition = TrialCondition.RUNNING
                changed = True
        if changed:
            self.cluster.update("trials", trial)
            self._persist(exp, trial)

    def _observe(self, exp: Experiment, trial: Trial):
        obj = exp.spec.objective
        if exp.spec.metrics_source == "tfevents":
            from kubeflow_tpu.sweep.collector import observation_from_tfevents

            return observation_from_tfevents(
                self._tfevents_dir(exp, trial),
                obj.objective_metric_name, obj.collected_metric_names,
            )
        log = self.log_reader(
            f"{trial.metadata.name}-{exp.spec.metrics_replica_type}-0",
            trial.metadata.namespace,
        )
        return observation_from_log(
            log, obj.objective_metric_name, obj.collected_metric_names
        )

    @staticmethod
    def _tfevents_dir(exp: Experiment, trial: Trial) -> str:
        return exp.spec.tfevents_dir.replace("${trialName}", trial.metadata.name)

    def _median_stop(self, exp: Experiment, trials: list[Trial]) -> None:
        """medianstop parity: a running trial is killed when the running
        average of its objective history is strictly worse than the median of
        completed trials' averages truncated to the SAME number of
        observations — step alignment keeps warming-up trials (whose first
        epochs are always 'bad') from being culled unfairly."""
        es = exp.spec.early_stopping
        obj = exp.spec.objective
        done_timelines = [
            tl for t in trials
            if t.status.condition == TrialCondition.SUCCEEDED
            and (tl := self._done_timeline(exp, t))
        ]
        if len(done_timelines) < es.min_trials_required:
            return
        for t in trials:
            if t.status.is_finished:
                continue
            tv = self._objective_timeline(exp, t)
            if not tv:
                continue  # no signal yet
            k = len(tv)
            avg = sum(tv) / k
            median = statistics.median(
                sum(tl[:k]) / min(k, len(tl)) for tl in done_timelines
            )
            if _strictly_worse(obj.type, avg, median):
                tkey = f"{t.metadata.namespace}/{t.metadata.name}"
                # Never destroy finished work: if the underlying job (or its
                # metrics pod) already completed, let _sync_trial record the
                # real verdict instead of culling a done trial whose success
                # simply hasn't been synced yet.
                job = self.cluster.get("jobs", tkey)
                if job is not None and job.status.is_finished:
                    continue
                pod = self.cluster.get(
                    "pods",
                    f"{t.metadata.namespace}/{t.metadata.name}-"
                    f"{exp.spec.metrics_replica_type}-0",
                )
                if pod is not None and pod.status.phase.value in (
                    "Succeeded", "Failed"
                ):
                    continue
                self._delete_trial_job(t)
                tc = self.cluster.get("trials", tkey, copy_obj=True)
                if tc is None:
                    continue
                tc.status.condition = TrialCondition.EARLY_STOPPED
                tc.status.observation = self._observe(exp, t)
                tc.status.completion_time = _now()
                self.cluster.update("trials", tc)
                self._persist(exp, tc)
                self.metrics["trials_early_stopped_total"] += 1
                self.cluster.record_event(
                    "trials", tkey, "EarlyStopped",
                    f"avg {obj.objective_metric_name}={avg:.6g} over {k} "
                    f"observation(s) worse than median {median:.6g}",
                )

    def _objective_timeline(self, exp: Experiment, trial: Trial) -> list[float]:
        from kubeflow_tpu.sweep.collector import parse_metrics, parse_tfevents

        name = exp.spec.objective.objective_metric_name
        if exp.spec.metrics_source == "tfevents":
            return parse_tfevents(
                self._tfevents_dir(exp, trial), {name}
            ).get(name, [])
        log = self.log_reader(
            f"{trial.metadata.name}-{exp.spec.metrics_replica_type}-0",
            trial.metadata.namespace,
        )
        return parse_metrics(log, {name}).get(name, [])

    def _done_timeline(self, exp: Experiment, trial: Trial) -> list[float]:
        # keyed by experiment uid so a deleted-and-recreated experiment with
        # recycled trial names can never see the previous run's timelines
        key = f"{exp.metadata.uid}/{trial.metadata.namespace}/{trial.metadata.name}"
        tl = self._timeline_cache.get(key)
        if tl is None:
            tl = self._objective_timeline(exp, trial)
            if tl:
                self._timeline_cache[key] = tl
        return tl

    def _drop_timelines(self, uid: str) -> None:
        prefix = f"{uid}/"
        for k in [k for k in self._timeline_cache if k.startswith(prefix)]:
            del self._timeline_cache[k]

    def _optimal(self, exp: Experiment, succeeded: list[Trial]) -> OptimalTrial | None:
        """Best trial by the (scalarized, for multi-objective) objective —
        katib's currentOptimalTrial."""
        obj = exp.spec.objective
        best_t, best_v = None, None
        for t in succeeded:
            v = scalarized_objective(obj, t.status.observation)
            if v is None or math.isnan(v):
                continue
            if best_v is None or _strictly_better(obj.type, v, best_v):
                best_t, best_v = t, v
        if best_t is None:
            return None
        return OptimalTrial(
            trial_name=best_t.metadata.name,
            parameter_assignments=list(best_t.spec.parameter_assignments),
            observation=best_t.status.observation,
        )

    def _pareto_front(self, exp: Experiment,
                      succeeded: list[Trial]) -> list[OptimalTrial]:
        """Non-dominated succeeded trials over (primary + additional
        objectives); empty for single-objective experiments."""
        obj = exp.spec.objective
        if not obj.additional_objectives:
            return []
        terms = [(obj.objective_metric_name, obj.type)] + [
            (t.metric_name, t.type) for t in obj.additional_objectives]

        def vector(t: Trial) -> list[float] | None:
            vs = []
            for name, typ in terms:
                m = t.status.observation.metric(name)
                if m is None:
                    return None
                # orient every term as MAXIMIZE for the dominance test
                vs.append(m.latest if typ == ObjectiveType.MAXIMIZE
                          else -m.latest)
            return vs

        scored = [(t, vector(t)) for t in succeeded]
        scored = [(t, v) for t, v in scored
                  if v is not None and not any(math.isnan(x) for x in v)]

        def dominated(v, others):
            return any(
                all(o >= x for o, x in zip(w, v))
                and any(o > x for o, x in zip(w, v))
                for _, w in others)

        front = [
            OptimalTrial(
                trial_name=t.metadata.name,
                parameter_assignments=list(t.spec.parameter_assignments),
                observation=t.status.observation,
            )
            for t, v in scored
            if not dominated(v, [(u, w) for u, w in scored if u is not t])
        ]
        front.sort(key=lambda o: o.trial_name)
        return front

    def _spawn_trials(self, exp: Experiment, trials: list[Trial], count: int) -> int:
        obj = exp.spec.objective
        history = []
        for t in trials:
            # suggesters learn the SCALARIZED value under multi-objective
            # (one number, primary-oriented) — the same quantity optimal-
            # trial selection ranks by
            v = scalarized_objective(obj, t.status.observation)
            if v is not None:
                o = v
            elif t.status.is_finished:
                o = float("nan")  # finished without objective: ranks worst
            else:
                o = None  # still running
            history.append((t.assignments_dict(), o))
        seed = int(exp.spec.algorithm.settings.get(
            "seed", zlib.crc32(exp.metadata.name.encode()) & 0x7FFFFFFF
        ))
        if self._suggestion_client is not None:
            suggestions = self._suggestion_client.get_suggestions(
                exp.spec.algorithm.algorithm_name,
                exp.spec.parameters,
                history,
                count,
                settings=dict(exp.spec.algorithm.settings),
                objective_type=obj.type,
                seed=seed + len(trials),
            )
        else:
            suggester = get_suggester(
                exp.spec.algorithm.algorithm_name,
                exp.spec.parameters,
                seed=seed + len(trials),  # decorrelate successive passes
                objective_type=obj.type,
                settings=exp.spec.algorithm.settings,
            )
            suggestions = suggester.suggest(history, count)
        created = 0
        for a in suggestions:
            name = f"{exp.metadata.name}-{len(trials) + created:04d}"
            trial = Trial(
                metadata=ObjectMeta(
                    name=name,
                    namespace=exp.metadata.namespace,
                    labels={EXPERIMENT_LABEL: exp.metadata.name},
                ),
                spec=TrialSpec(
                    parameter_assignments=[
                        ParameterAssignment(name=k, value=v) for k, v in a.items()
                    ],
                    rendered_spec=render_trial_spec(
                        exp.spec.trial_template, a,
                        parameters=exp.spec.parameters),
                ),
            )
            try:
                self.cluster.create("trials", trial)
            except KeyError:
                continue  # name collision with a concurrent pass: skip
            self._create_trial_job(exp, trial)
            self.metrics["trials_created_total"] += 1
            created += 1
        return created

    def _create_trial_job(self, exp: Experiment, trial: Trial) -> None:
        from kubeflow_tpu.controller.profile import check_job_admission

        job = job_from_yaml(trial.spec.rendered_spec)
        job.metadata.name = trial.metadata.name
        job.metadata.namespace = trial.metadata.namespace
        job.metadata.labels[EXPERIMENT_LABEL] = exp.metadata.name
        validate_job(job)
        try:
            check_job_admission(self.cluster, job)
        except ValueError as exc:
            # namespace at its job quota: leave the trial pending; the next
            # sync retries once capacity frees up (quota = backpressure)
            self.cluster.record_event(
                "trials", f"{trial.metadata.namespace}/{trial.metadata.name}",
                "QuotaExceeded", str(exc), type="Warning",
            )
            return
        try:
            self.cluster.create("jobs", job)
        except KeyError:
            pass  # already exists

    def _delete_trial_job(self, trial: Trial) -> None:
        delete_job_cascade(
            self.cluster, trial.metadata.name, trial.metadata.namespace
        )

    def _kill_running(self, exp: Experiment, trials: list[Trial]) -> None:
        for t in trials:
            if t.status.is_finished:
                continue
            tkey = f"{t.metadata.namespace}/{t.metadata.name}"
            self._delete_trial_job(t)
            tc = self.cluster.get("trials", tkey, copy_obj=True)
            if tc is None:
                continue
            tc.status.condition = TrialCondition.EARLY_STOPPED
            tc.status.completion_time = _now()
            self.cluster.update("trials", tc)
            self._persist(exp, tc)

    def _finish(
        self,
        exp: Experiment,
        key: str,
        trials: list[Trial],
        cond: ExperimentCondition,
        reason: str,
    ) -> None:
        exp.status.condition = cond
        exp.status.message = reason
        exp.status.completion_time = _now()
        self.cluster.update("experiments", exp)
        if cond == ExperimentCondition.SUCCEEDED:
            self.metrics["experiments_succeeded_total"] += 1
        else:
            self.metrics["experiments_failed_total"] += 1
        self.cluster.record_event("experiments", key, reason, f"experiment {cond.value}")
        self._kill_running(exp, trials)
        self._drop_timelines(exp.metadata.uid)
        return None


# ---------------------------------------------------------------- comparators

def _strictly_better(t: ObjectiveType, a: float, b: float) -> bool:
    return a < b if t == ObjectiveType.MINIMIZE else a > b


def _strictly_worse(t: ObjectiveType, a: float, b: float) -> bool:
    return a > b if t == ObjectiveType.MINIMIZE else a < b


def _better_or_equal(t: ObjectiveType, a: float, b: float) -> bool:
    return a <= b if t == ObjectiveType.MINIMIZE else a >= b


def _exp_fingerprint(st) -> tuple:
    return (
        st.condition,
        st.trials,
        st.trials_running,
        st.trials_succeeded,
        st.trials_failed,
        st.trials_early_stopped,
        st.message,
        tuple(o.trial_name for o in st.pareto_front),
        st.current_optimal_trial.trial_name if st.current_optimal_trial else "",
        (
            tuple(
                (m.name, m.latest)
                for m in st.current_optimal_trial.observation.metrics
            )
            if st.current_optimal_trial
            else ()
        ),
    )


