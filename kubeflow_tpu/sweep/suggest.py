"""Suggestion algorithms: random, grid, TPE, CMA-ES, GP-Bayesian, hyperband.

Reference parity (unverified cites, SURVEY.md §2.4): katib
pkg/suggestion/v1beta1/{hyperopt,optuna,skopt,hyperband}/service.py behind
the Suggestion gRPC service. Here the algorithms are the same kind of code
(Python), minus the Deployment/gRPC hop: a Suggester is a pure function of
(space, history) -> assignments, which also makes it deterministic and
unit-testable.

TPE follows Bergstra et al.'s tree-structured Parzen estimator recipe
(split history at a quantile into good/bad, model each with a Parzen mixture,
maximize the good/bad density ratio over sampled candidates) implemented
with numpy only — independent per dimension, like hyperopt's default.

GP-Bayesian (skopt parity) fits a Matérn-5/2 Gaussian process on the unit
cube (one-hot categoricals) and maximizes expected improvement over random
candidate draws — numpy-only, no scipy/skopt dependency.

Hyperband replays successive-halving brackets from the trial history: rung-0
configs come from an inner suggester, higher rungs promote the top 1/eta by
objective at the next resource budget. Failed trials arrive as NaN
objectives (worst rank, never promoted) so a crashed trial cannot stall a
rung.
"""

from __future__ import annotations

import itertools
import math

import numpy as np

from kubeflow_tpu.sweep.api import (
    ObjectiveType,
    ParameterSpec,
    ParameterType,
)

# history entry: (assignments: dict[str, str], objective: float | None).
# None = still running; NaN = finished without a usable objective (failed).
History = list[tuple[dict[str, str], float | None]]


def _finite(history: History) -> History:
    return [
        (a, o) for a, o in history if o is not None and not math.isnan(o)
    ]


def _format(p: ParameterSpec, v: float) -> str:
    if p.parameter_type == ParameterType.INT:
        return str(int(round(v)))
    return f"{v:.6g}"


def _snap_step(p: ParameterSpec, v: float) -> float:
    """Quantize a numeric value onto the parameter's step grid."""
    fs = p.feasible_space
    if not fs.step:
        return v
    lo, hi, step = float(fs.min), float(fs.max), float(fs.step)
    return min(lo + round((v - lo) / step) * step, hi)


class RandomSuggester:
    def __init__(self, parameters: list[ParameterSpec], seed: int = 0):
        self.parameters = parameters
        self.rng = np.random.default_rng(seed)

    def suggest(self, history: History, count: int) -> list[dict[str, str]]:
        out = []
        for _ in range(count):
            a: dict[str, str] = {}
            for p in self.parameters:
                fs = p.feasible_space
                if p.parameter_type in (ParameterType.CATEGORICAL, ParameterType.DISCRETE):
                    a[p.name] = str(fs.list[self.rng.integers(len(fs.list))])
                else:
                    v = self.rng.uniform(float(fs.min), float(fs.max))
                    a[p.name] = _format(p, _snap_step(p, v))
            out.append(a)
        return out


def _axis_values(p: ParameterSpec, default_grid_points: int = 4) -> list[str]:
    """A parameter's discrete grid (categoricals verbatim; numerics on
    their step grid, or default_grid_points even samples)."""
    fs = p.feasible_space
    if p.parameter_type in (ParameterType.CATEGORICAL, ParameterType.DISCRETE):
        return [str(v) for v in fs.list]
    lo, hi = float(fs.min), float(fs.max)
    if fs.step:
        # epsilon keeps fp error from dropping the max boundary point
        # ((0.3-0.1)/0.1 == 1.9999... would otherwise lose 0.3)
        n = int(math.floor((hi - lo) / float(fs.step) + 1e-9)) + 1
        vals = [lo + i * float(fs.step) for i in range(n)]
    else:
        n = default_grid_points
        vals = [lo + (hi - lo) * i / (n - 1) for i in range(n)] if n > 1 else [lo]
    return [_format(p, v) for v in vals]


class GridSuggester:
    """Enumerates the cartesian grid in a stable order, skipping points
    already tried (reconcile is level-triggered: 'which points exist' is
    derived from history, no internal cursor)."""

    def __init__(self, parameters: list[ParameterSpec], seed: int = 0,
                 default_grid_points: int = 4):
        self.parameters = parameters
        self.default_grid_points = default_grid_points

    def _axis(self, p: ParameterSpec) -> list[str]:
        return _axis_values(p, self.default_grid_points)

    def suggest(self, history: History, count: int) -> list[dict[str, str]]:
        tried = {tuple(sorted(h[0].items())) for h in history}
        out = []
        axes = [self._axis(p) for p in self.parameters]
        for combo in itertools.product(*axes):
            a = {p.name: v for p, v in zip(self.parameters, combo)}
            if tuple(sorted(a.items())) in tried:
                continue
            out.append(a)
            if len(out) >= count:
                break
        return out

    def grid_size(self) -> int:
        return math.prod(len(self._axis(p)) for p in self.parameters)


class TPESuggester:
    def __init__(
        self,
        parameters: list[ParameterSpec],
        seed: int = 0,
        objective_type: ObjectiveType = ObjectiveType.MAXIMIZE,
        gamma: float = 0.25,
        n_candidates: int = 24,
        n_startup: int = 5,
    ):
        self.parameters = parameters
        self.rng = np.random.default_rng(seed)
        self.objective_type = objective_type
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.n_startup = n_startup
        self._random = RandomSuggester(parameters, seed=seed + 1)

    def suggest(self, history: History, count: int) -> list[dict[str, str]]:
        observed = _finite(history)
        if len(observed) < self.n_startup:
            return self._random.suggest(history, count)
        # Sort so "good" is always the head (minimize: ascending).
        sign = 1.0 if self.objective_type == ObjectiveType.MINIMIZE else -1.0
        ranked = sorted(observed, key=lambda h: sign * h[1])
        n_good = max(1, int(np.ceil(self.gamma * len(ranked))))
        good, bad = ranked[:n_good], ranked[n_good:] or ranked[:1]
        return [self._suggest_one(good, bad) for _ in range(count)]

    def _suggest_one(self, good: History, bad: History) -> dict[str, str]:
        a: dict[str, str] = {}
        for p in self.parameters:
            fs = p.feasible_space
            if p.parameter_type in (ParameterType.CATEGORICAL, ParameterType.DISCRETE):
                a[p.name] = self._categorical(p, good, bad)
            else:
                lo, hi = float(fs.min), float(fs.max)
                gv = np.array([float(h[0][p.name]) for h in good if p.name in h[0]])
                bv = np.array([float(h[0][p.name]) for h in bad if p.name in h[0]])
                if len(gv) == 0:
                    v = self.rng.uniform(lo, hi)
                else:
                    # Parzen bandwidth ~ range / sqrt(n)
                    bw = max((hi - lo) / max(np.sqrt(len(gv)), 1.0), 1e-12)
                    cand = self.rng.normal(
                        gv[self.rng.integers(len(gv), size=self.n_candidates)], bw
                    )
                    cand = np.clip(cand, lo, hi)
                    score = self._log_parzen(cand, gv, bw) - self._log_parzen(
                        cand, bv if len(bv) else gv, bw
                    )
                    v = float(cand[np.argmax(score)])
                a[p.name] = _format(p, _snap_step(p, v))
        return a

    def _categorical(self, p: ParameterSpec, good: History, bad: History) -> str:
        choices = [str(v) for v in p.feasible_space.list]
        # Laplace-smoothed good-frequency vs bad-frequency ratio sampling
        gcounts = np.array(
            [1.0 + sum(1 for h in good if h[0].get(p.name) == c) for c in choices]
        )
        bcounts = np.array(
            [1.0 + sum(1 for h in bad if h[0].get(p.name) == c) for c in choices]
        )
        w = gcounts / bcounts
        w = w / w.sum()
        return choices[self.rng.choice(len(choices), p=w)]

    @staticmethod
    def _log_parzen(x: np.ndarray, centers: np.ndarray, bw: float) -> np.ndarray:
        d = (x[:, None] - centers[None, :]) / bw
        log_k = -0.5 * d * d - np.log(bw * np.sqrt(2 * np.pi))
        m = log_k.max(axis=1, keepdims=True)
        return (m + np.log(np.exp(log_k - m).sum(axis=1, keepdims=True))).ravel() - np.log(
            len(centers)
        )


class CMAESSuggester:
    """(mu/mu_w, lambda)-CMA-ES over numeric parameters (katib's optuna
    cmaes parity). Reconciliation is stateless, so the strategy state
    (mean, step size, covariance) is REPLAYED from the observed history on
    every call: completed trials are consumed in creation order as
    generations of size lambda — deterministic and restart-safe.

    Categorical parameters are not supported (same restriction as upstream
    CMA-ES samplers); validate at experiment admission.
    """

    def __init__(
        self,
        parameters: list[ParameterSpec],
        seed: int = 0,
        objective_type: ObjectiveType = ObjectiveType.MAXIMIZE,
        popsize: int | None = None,
        sigma0: float = 0.3,
    ):
        for p in parameters:
            if p.parameter_type in (ParameterType.CATEGORICAL, ParameterType.DISCRETE):
                raise ValueError(
                    f"cmaes supports numeric parameters only; {p.name!r} is "
                    f"{p.parameter_type.value}"
                )
        self.parameters = parameters
        self.seed = seed
        self.objective_type = objective_type
        self.d = len(parameters)
        self.popsize = popsize if popsize is not None else (4 + int(3 * np.log(self.d)))
        if self.popsize < 2:
            raise ValueError(f"cmaes popsize must be >= 2, got {self.popsize}")
        self.sigma0 = sigma0
        # bounds are fixed at construction — parse once
        self._lo = np.array([float(p.feasible_space.min) for p in parameters])
        self._hi = np.array([float(p.feasible_space.max) for p in parameters])
        self._span = np.where(self._hi > self._lo, self._hi - self._lo, 1.0)

    # normalized [0,1]^d <-> parameter space

    def _to_unit(self, a: dict[str, str]) -> np.ndarray:
        x = np.array([float(a[p.name]) for p in self.parameters])
        return (x - self._lo) / self._span

    def _from_unit(self, u: np.ndarray) -> dict[str, str]:
        x = self._lo + np.clip(u, 0.0, 1.0) * (self._hi - self._lo)
        return {
            p.name: _format(p, _snap_step(p, float(v)))
            for p, v in zip(self.parameters, x)
        }

    def suggest(self, history: History, count: int) -> list[dict[str, str]]:
        d, lam = self.d, self.popsize
        mu = lam // 2
        w = np.log(mu + 0.5) - np.log(np.arange(1, mu + 1))
        w = w / w.sum()
        mu_eff = 1.0 / (w ** 2).sum()
        # standard CMA learning rates (Hansen's tutorial defaults)
        cc = (4 + mu_eff / d) / (d + 4 + 2 * mu_eff / d)
        cs = (mu_eff + 2) / (d + mu_eff + 5)
        c1 = 2 / ((d + 1.3) ** 2 + mu_eff)
        cmu = min(1 - c1, 2 * (mu_eff - 2 + 1 / mu_eff) / ((d + 2) ** 2 + mu_eff))
        damps = 1 + 2 * max(0.0, np.sqrt((mu_eff - 1) / (d + 1)) - 1) + cs
        chi_n = np.sqrt(d) * (1 - 1 / (4 * d) + 1 / (21 * d * d))

        mean = np.full(d, 0.5)
        sigma = self.sigma0
        C = np.eye(d)
        pc = np.zeros(d)
        ps = np.zeros(d)
        sign = 1.0 if self.objective_type == ObjectiveType.MINIMIZE else -1.0

        names = {p.name for p in self.parameters}
        observed = [
            (a, o) for a, o in _finite(history)
            if names <= set(a)  # tolerate foreign entries
        ]
        # replay complete generations
        for g in range(len(observed) // lam):
            gen = observed[g * lam:(g + 1) * lam]
            xs = np.stack([self._to_unit(a) for a, _ in gen])
            order = np.argsort([sign * o for _, o in gen])
            elite = xs[order[:mu]]
            old_mean = mean
            mean = w @ elite
            try:
                # inv(L) whitens C: inv(L) C inv(L)^T = I
                inv_sqrt_C = np.linalg.inv(np.linalg.cholesky(C))
            except np.linalg.LinAlgError:
                # fp drift made C non-PD: reset the covariance model rather
                # than brick every future replay of this history
                C = np.eye(d)
                inv_sqrt_C = np.eye(d)
            y = (mean - old_mean) / max(sigma, 1e-12)
            ps = (1 - cs) * ps + np.sqrt(cs * (2 - cs) * mu_eff) * (inv_sqrt_C @ y)
            h_sig = float(
                np.linalg.norm(ps)
                / np.sqrt(1 - (1 - cs) ** (2 * (g + 1)))
                < (1.4 + 2 / (d + 1)) * chi_n
            )
            pc = (1 - cc) * pc + h_sig * np.sqrt(cc * (2 - cc) * mu_eff) * y
            dz = (elite - old_mean) / max(sigma, 1e-12)
            C = (
                (1 - c1 - cmu) * C
                + c1 * (np.outer(pc, pc) + (1 - h_sig) * cc * (2 - cc) * C)
                + cmu * (dz.T * w) @ dz
            )
            C = (C + C.T) / 2  # keep symmetric under fp error
            sigma = sigma * np.exp((cs / damps) * (np.linalg.norm(ps) / chi_n - 1))
            sigma = float(np.clip(sigma, 1e-6, 1.0))

        # sample the next ask()s; rng keyed by how far the replay got so the
        # same history always yields the same suggestions
        rng = np.random.default_rng(self.seed + len(observed))
        try:
            A = np.linalg.cholesky(C)
        except np.linalg.LinAlgError:
            A = np.eye(d)
        out = []
        for _ in range(count):
            z = rng.standard_normal(d)
            out.append(self._from_unit(mean + sigma * (A @ z)))
        return out


class EvolutionSuggester:
    """Regularized (aging) evolution — the NAS workhorse (Real et al. 2019,
    AmoebaNet), and the platform-level analogue of katib's NAS suggestion
    services: architectures encode as ordinary categorical/int/double
    parameters (e.g. ops per block, widths, depths), so the same trial
    plumbing searches architecture space. ENAS/DARTS-style in-graph weight
    sharing is a model-side technique, not a controller one — what the
    platform owes is the evolutionary search loop.

    Replay semantics match CMA-ES/hyperband: the population is the last
    `populationSize` finished trials (aging = oldest die by construction);
    each suggestion tournament-selects a parent and mutates one parameter.
    """

    def __init__(
        self,
        parameters: list[ParameterSpec],
        seed: int = 0,
        objective_type: ObjectiveType = ObjectiveType.MAXIMIZE,
        population_size: int = 20,
        tournament_size: int = 5,
        mutation_rate: float = 0.0,  # 0 => exactly one parameter mutates
    ):
        self.parameters = parameters
        self.objective_type = objective_type
        self.population_size = population_size
        self.tournament_size = tournament_size
        self.mutation_rate = mutation_rate
        self.seed = seed

    def _mutate_one(self, a: dict[str, str], rng) -> dict[str, str]:
        out = dict(a)
        if self.mutation_rate > 0:
            chosen = [
                p for p in self.parameters if rng.random() < self.mutation_rate
            ] or [self.parameters[rng.integers(len(self.parameters))]]
        else:
            chosen = [self.parameters[rng.integers(len(self.parameters))]]
        for p in chosen:
            fs = p.feasible_space
            if p.parameter_type in (ParameterType.CATEGORICAL, ParameterType.DISCRETE):
                choices = [str(v) for v in fs.list if str(v) != out.get(p.name)]
                if choices:
                    out[p.name] = str(choices[rng.integers(len(choices))])
            else:
                lo, hi = float(fs.min), float(fs.max)
                # local gaussian move (10% of range), clipped to bounds
                cur = float(out.get(p.name, (lo + hi) / 2))
                v = float(np.clip(rng.normal(cur, 0.1 * (hi - lo)), lo, hi))
                out[p.name] = _format(p, _snap_step(p, v))
        return out

    def suggest(self, history: History, count: int) -> list[dict[str, str]]:
        observed = _finite(history)
        if len(observed) < self.tournament_size:
            # bootstrap stays replay-deterministic: a fresh rng keyed on the
            # history position, like the post-bootstrap path
            return RandomSuggester(
                self.parameters, seed=self.seed + len(history)
            ).suggest(history, count)
        # aging: only the newest population_size individuals survive
        population = observed[-self.population_size:]
        sign = 1.0 if self.objective_type == ObjectiveType.MINIMIZE else -1.0
        # rng keyed by replay position => deterministic, restart-safe
        rng = np.random.default_rng(self.seed + len(history))
        out = []
        for _ in range(count):
            k = min(self.tournament_size, len(population))
            contestants = [
                population[i]
                for i in rng.choice(len(population), size=k, replace=False)
            ]
            parent = min(contestants, key=lambda h: sign * h[1])
            out.append(self._mutate_one(parent[0], rng))
        return out


class GPBayesSuggester:
    """skopt-parity Bayesian optimization: Matérn-5/2 GP + expected
    improvement, numpy-only.

    Numeric parameters are normalized to [0,1]; categoricals one-hot encoded
    (scaled by 0.5 so a category flip is comparable to a half-range numeric
    move). EI is maximized over random candidate draws — cheap, and exact
    enough at sweep scale (katib's skopt service samples similarly).
    """

    def __init__(
        self,
        parameters: list[ParameterSpec],
        seed: int = 0,
        objective_type: ObjectiveType = ObjectiveType.MAXIMIZE,
        n_startup: int = 5,
        n_candidates: int = 256,
        length_scale: float = 0.25,
        noise: float = 1e-6,
        xi: float = 0.01,
    ):
        self.parameters = parameters
        self.rng = np.random.default_rng(seed)
        self.objective_type = objective_type
        self.n_startup = n_startup
        self.n_candidates = n_candidates
        self.length_scale = length_scale
        self.noise = noise
        self.xi = xi
        self._random = RandomSuggester(parameters, seed=seed + 1)

    def _encode(self, a: dict[str, str]) -> np.ndarray:
        parts = []
        for p in self.parameters:
            fs = p.feasible_space
            if p.parameter_type in (ParameterType.CATEGORICAL, ParameterType.DISCRETE):
                choices = [str(v) for v in fs.list]
                v = np.zeros(len(choices))
                if a.get(p.name) in choices:
                    v[choices.index(a[p.name])] = 0.5
                parts.append(v)
            else:
                lo, hi = float(fs.min), float(fs.max)
                span = (hi - lo) or 1.0
                parts.append(
                    np.array([(float(a.get(p.name, lo)) - lo) / span])
                )
        return np.concatenate(parts)

    def _kernel(self, A: np.ndarray, B: np.ndarray) -> np.ndarray:
        d = np.sqrt(
            np.maximum(
                ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1), 0.0
            )
        ) / self.length_scale
        return (1 + np.sqrt(5) * d + 5 * d * d / 3) * np.exp(-np.sqrt(5) * d)

    def suggest(self, history: History, count: int) -> list[dict[str, str]]:
        observed = _finite(history)
        if len(observed) < self.n_startup:
            return self._random.suggest(history, count)
        sign = 1.0 if self.objective_type == ObjectiveType.MINIMIZE else -1.0
        X = np.stack([self._encode(a) for a, _ in observed])
        y = np.array([sign * o for _, o in observed])  # GP minimizes
        y_mean, y_std = y.mean(), y.std() or 1.0
        yn = (y - y_mean) / y_std
        K = self._kernel(X, X) + self.noise * np.eye(len(X))
        try:
            L = np.linalg.cholesky(K)
        except np.linalg.LinAlgError:
            return self._random.suggest(history, count)
        alpha = np.linalg.solve(L.T, np.linalg.solve(L, yn))
        best = yn.min()

        cands = self._random.suggest(history, self.n_candidates)
        # dedupe against tried points (GP EI at a tried point is ~0 anyway,
        # but exact repeats waste trials)
        tried = {tuple(sorted(a.items())) for a, _ in observed}
        cands = [c for c in cands if tuple(sorted(c.items())) not in tried]
        if not cands:
            return self._random.suggest(history, count)
        Xc = np.stack([self._encode(c) for c in cands])
        Kc = self._kernel(Xc, X)
        mu = Kc @ alpha
        v = np.linalg.solve(L, Kc.T)
        var = np.maximum(
            np.diag(self._kernel(Xc, Xc)) - (v * v).sum(0), 1e-12
        )
        sd = np.sqrt(var)
        # expected improvement (minimization form), Phi/phi via erf
        z = (best - self.xi - mu) / sd
        Phi = 0.5 * (1 + np.vectorize(math.erf)(z / np.sqrt(2)))
        phi = np.exp(-0.5 * z * z) / np.sqrt(2 * np.pi)
        ei = (best - self.xi - mu) * Phi + sd * phi
        order = np.argsort(-ei)
        return [cands[i] for i in order[:count]]


class EnasSuggester:
    """ENAS-style controller (katib pkg/suggestion/v1beta1/nas/enas
    parity): a LEARNED policy proposes architectures across trials and is
    updated by policy gradient on their objectives — the reinforcement
    half of Pham et al.'s ENAS (weight sharing, the other half, lives in
    the trial workload: see train/oneshot.py's supernet). Upstream drives
    an LSTM over the decision sequence; here each architecture decision
    keeps its own softmax logits trained with REINFORCE against an
    exponential-moving-average baseline. Level-triggered like every
    suggester in this module: the policy is REPLAYED from history on each
    call, so identical history yields identical suggestions and the
    controller survives platform restarts for free.
    """

    def __init__(self, parameters: list[ParameterSpec], seed: int = 0,
                 objective_type: ObjectiveType = ObjectiveType.MAXIMIZE,
                 lr: float = 0.35, baseline_decay: float = 0.7,
                 temperature: float = 1.0, default_grid_points: int = 4):
        if temperature <= 0:
            raise ValueError(
                f"enas temperature must be positive, got {temperature} "
                "(it scales the sampling softmax; use a small value like "
                "0.1 for near-greedy proposals)")
        self.parameters = parameters
        self.axes = [_axis_values(p, default_grid_points)
                     for p in parameters]
        self.seed = seed
        self.sign = 1.0 if objective_type == ObjectiveType.MAXIMIZE else -1.0
        self.lr = lr
        self.baseline_decay = baseline_decay
        self.temperature = temperature

    def _policy(self, logits: np.ndarray) -> np.ndarray:
        return _softmax(logits / self.temperature)

    def _replay(self, history: History) -> list[np.ndarray]:
        logits = [np.zeros(len(ax)) for ax in self.axes]
        baseline: float | None = None
        for assignments, objective in _finite(history):
            matched = [
                (d, axis.index(assignments[p.name]))
                for d, (p, axis) in enumerate(
                    zip(self.parameters, self.axes))
                if assignments.get(p.name) in axis
            ]
            if len(matched) != len(self.parameters):
                # foreign/hand-injected trial (any dim off the policy
                # grid): the policy never produced it — neither gradient
                # NOR baseline may learn from it, even for the dims that
                # happen to lie on the grid
                continue
            reward = self.sign * objective
            adv = reward - (baseline if baseline is not None else reward)
            baseline = (reward if baseline is None else
                        self.baseline_decay * baseline
                        + (1.0 - self.baseline_decay) * reward)
            for d, idx in matched:
                # REINFORCE for the SAMPLING policy softmax(logits/T):
                # ∇_logits log π(idx) = (e_idx − π) / T
                grad = -self._policy(logits[d])
                grad[idx] += 1.0
                logits[d] += self.lr * adv * grad / self.temperature
        return logits

    def suggest(self, history: History, count: int) -> list[dict[str, str]]:
        logits = self._replay(history)
        # fresh draws each call, deterministic given (seed, history length)
        rng = np.random.default_rng((self.seed, len(history)))
        out = []
        for _ in range(count):
            a: dict[str, str] = {}
            for d, (p, axis) in enumerate(zip(self.parameters, self.axes)):
                a[p.name] = axis[rng.choice(len(axis), p=self._policy(logits[d]))]
            out.append(a)
        return out


def _softmax(x: np.ndarray) -> np.ndarray:
    e = np.exp(x - x.max())
    return e / e.sum()


class HyperbandSuggester:
    """Hyperband (successive halving) replayed from the trial history.

    One parameter is the *resource* (settings["resourceParameter"], e.g.
    epochs); its feasible min/max are the r/R budgets. Brackets run
    s_max..0; rung 0 of a bracket samples fresh configs from an inner
    suggester at the bracket's lowest budget, each higher rung re-runs the
    top 1/eta configs (by objective) at eta× the budget. The replay walks
    the (creation-ordered) history, so reconciliation stays stateless and
    restart-safe, like the CMA-ES replay.
    """

    def __init__(
        self,
        parameters: list[ParameterSpec],
        seed: int = 0,
        objective_type: ObjectiveType = ObjectiveType.MAXIMIZE,
        resource_parameter: str = "",
        eta: int = 3,
        inner: str = "random",
    ):
        if not resource_parameter:
            raise ValueError(
                "hyperband requires settings.resourceParameter naming the "
                "budget parameter (e.g. epochs)"
            )
        by_name = {p.name: p for p in parameters}
        if resource_parameter not in by_name:
            raise ValueError(
                f"resourceParameter {resource_parameter!r} is not an "
                f"experiment parameter"
            )
        rp = by_name[resource_parameter]
        if rp.parameter_type in (ParameterType.CATEGORICAL, ParameterType.DISCRETE):
            raise ValueError("the resource parameter must be numeric")
        self.resource = rp
        self.eta = eta
        self.objective_type = objective_type
        self.config_params = [p for p in parameters if p.name != rp.name]
        self._inner = get_suggester(
            inner, self.config_params, seed=seed, objective_type=objective_type
        )
        self.r_min = float(rp.feasible_space.min)
        self.r_max = float(rp.feasible_space.max)
        self.s_max = int(math.floor(
            math.log(max(self.r_max / max(self.r_min, 1e-12), 1.0), eta)
        ))

    # ------------------------------------------------------------- schedule

    def brackets(self) -> list[list[tuple[int, float]]]:
        """[(n_configs, budget) per rung] per bracket, s_max..0."""
        out = []
        for s in range(self.s_max, -1, -1):
            n = int(math.ceil((self.s_max + 1) / (s + 1) * self.eta ** s))
            r = self.r_max * self.eta ** (-s)
            rungs = []
            for i in range(s + 1):
                n_i = max(1, int(math.floor(n * self.eta ** (-i))))
                rungs.append((n_i, r * self.eta ** i))
            out.append(rungs)
        return out

    def total_trials(self) -> int:
        return sum(n for b in self.brackets() for n, _ in b)

    def _fmt_resource(self, budget: float) -> str:
        return _format(self.resource, _snap_step(self.resource, budget))

    def _config_key(self, a: dict[str, str]) -> tuple:
        return tuple(
            sorted((k, v) for k, v in a.items() if k != self.resource.name)
        )

    def suggest(self, history: History, count: int) -> list[dict[str, str]]:
        idx = 0
        sign = 1.0 if self.objective_type == ObjectiveType.MINIMIZE else -1.0
        for rungs in self.brackets():
            prev_rung: History = []
            for i, (n_i, budget) in enumerate(rungs):
                entries = history[idx: idx + n_i]
                if len(entries) < n_i:
                    missing = n_i - len(entries)
                    if i == 0:
                        fresh = self._inner.suggest(
                            [  # inner model learns from all finished trials
                                (a, o) for a, o in _finite(history)
                            ],
                            min(missing, count),
                        )
                        return [
                            {**a, self.resource.name: self._fmt_resource(budget)}
                            for a in fresh
                        ]
                    # promotion rung: requires the rung below fully observed
                    if any(o is None for _, o in prev_rung):
                        return []  # wait for stragglers
                    ranked = sorted(
                        prev_rung,
                        key=lambda h: (
                            math.inf if math.isnan(h[1]) else sign * h[1]
                        ),
                    )
                    started = {self._config_key(a) for a, _ in entries}
                    promos = []
                    for a, _ in ranked:
                        if self._config_key(a) in started:
                            continue
                        promos.append(
                            {**{k: v for k, v in a.items()
                                if k != self.resource.name},
                             self.resource.name: self._fmt_resource(budget)}
                        )
                        started.add(self._config_key(a))
                        if len(promos) >= min(missing, count):
                            break
                    return promos
                idx += n_i
                prev_rung = entries
        return []  # every bracket complete


def get_suggester(
    name: str,
    parameters: list[ParameterSpec],
    seed: int = 0,
    objective_type: ObjectiveType = ObjectiveType.MAXIMIZE,
    settings: dict[str, str] | None = None,
):
    settings = settings or {}
    if name == "random":
        return RandomSuggester(parameters, seed=seed)
    if name == "grid":
        return GridSuggester(
            parameters,
            seed=seed,
            default_grid_points=int(settings.get("defaultGridPoints", 4)),
        )
    if name == "tpe":
        return TPESuggester(
            parameters,
            seed=seed,
            objective_type=objective_type,
            gamma=float(settings.get("gamma", 0.25)),
            n_candidates=int(settings.get("nCandidates", 24)),
            n_startup=int(settings.get("nStartup", 5)),
        )
    if name == "cmaes":
        return CMAESSuggester(
            parameters,
            seed=seed,
            objective_type=objective_type,
            popsize=int(settings["popsize"]) if "popsize" in settings else None,
            sigma0=float(settings.get("sigma", 0.3)),
        )
    if name in ("bayesianoptimization", "gp", "skopt"):
        return GPBayesSuggester(
            parameters,
            seed=seed,
            objective_type=objective_type,
            n_startup=int(settings.get("nStartup", 5)),
            n_candidates=int(settings.get("nCandidates", 256)),
            length_scale=float(settings.get("lengthScale", 0.25)),
            xi=float(settings.get("xi", 0.01)),
        )
    if name in ("evolution", "nas"):
        return EvolutionSuggester(
            parameters,
            seed=seed,
            objective_type=objective_type,
            population_size=int(settings.get("populationSize", 20)),
            tournament_size=int(settings.get("tournamentSize", 5)),
            mutation_rate=float(settings.get("mutationRate", 0.0)),
        )
    if name == "hyperband":
        return HyperbandSuggester(
            parameters,
            seed=seed,
            objective_type=objective_type,
            resource_parameter=settings.get("resourceParameter", ""),
            eta=int(settings.get("eta", 3)),
            inner=settings.get("inner", "random"),
        )
    if name == "darts":
        raise ValueError(
            "darts is a one-shot IN-TRIAL search, not a trial-loop "
            "algorithm: run kubeflow_tpu.train.oneshot.darts_search inside "
            "a single trial (examples/darts_digits.py); for "
            "controller-driven NAS over trials use 'enas' or 'evolution'"
        )
    if name == "enas":
        return EnasSuggester(
            parameters,
            seed=seed,
            objective_type=objective_type,
            lr=float(settings.get("controllerLr", 0.35)),
            baseline_decay=float(settings.get("baselineDecay", 0.7)),
            temperature=float(settings.get("temperature", 1.0)),
            default_grid_points=int(settings.get("defaultGridPoints", 4)),
        )
    raise ValueError(
        f"unknown suggestion algorithm {name!r} "
        f"(random|grid|tpe|cmaes|bayesianoptimization|hyperband|evolution|"
        f"enas|darts)"
    )
