"""Hyperparameter sweep engine — Katib parity (SURVEY.md §2.4).

Experiment -> Suggestion -> Trial, where each trial is a rendered JAXJob
launched through the same control plane as any other job. Suggestion
algorithms are in-process (random/grid/TPE); metrics come from the
`name=value` stdout contract the trainer already emits (§5.5).
"""

from kubeflow_tpu.sweep.api import (
    AlgorithmSpec,
    EarlyStoppingSpec,
    Experiment,
    ExperimentSpec,
    ExperimentStatus,
    FeasibleSpace,
    Metric,
    Objective,
    ObjectiveType,
    Observation,
    ParameterAssignment,
    ParameterSpec,
    ParameterType,
    Trial,
    TrialSpec,
    TrialStatus,
    TrialTemplate,
    TrialParameterSpec,
)
from kubeflow_tpu.sweep.client import SweepClient
from kubeflow_tpu.sweep.collector import parse_metrics, observation_from_log
from kubeflow_tpu.sweep.controller import ExperimentController
from kubeflow_tpu.sweep.suggest import (
    GridSuggester,
    RandomSuggester,
    TPESuggester,
    get_suggester,
)

__all__ = [
    "AlgorithmSpec",
    "EarlyStoppingSpec",
    "Experiment",
    "ExperimentSpec",
    "ExperimentStatus",
    "ExperimentController",
    "FeasibleSpace",
    "GridSuggester",
    "Metric",
    "Objective",
    "ObjectiveType",
    "Observation",
    "ParameterAssignment",
    "ParameterSpec",
    "ParameterType",
    "RandomSuggester",
    "SweepClient",
    "TPESuggester",
    "Trial",
    "TrialSpec",
    "TrialStatus",
    "TrialTemplate",
    "TrialParameterSpec",
    "get_suggester",
    "observation_from_log",
    "parse_metrics",
]
