"""Sweep CR-equivalents: Experiment / Trial (+ embedded suggestion config).

Reference parity (unverified cites, SURVEY.md §2.4): katib
pkg/apis/controller/experiments/v1beta1/experiment_types.go and
trials/v1beta1/trial_types.go. The Suggestion CR is collapsed into the
experiment controller's in-process suggester — its gRPC boundary exists in
the reference because algorithms run as separate Deployments; here they are
library calls (the algorithms themselves are Python upstream too).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from kubeflow_tpu.api.common import ObjectMeta


class ParameterType(str, enum.Enum):
    DOUBLE = "double"
    INT = "int"
    CATEGORICAL = "categorical"
    DISCRETE = "discrete"


@dataclass
class FeasibleSpace:
    """Search domain for one parameter (min/max for numeric, list for
    categorical/discrete; step optionally quantizes numeric grids)."""

    min: str = ""
    max: str = ""
    list: list[str] = field(default_factory=lambda: [])
    step: str = ""


@dataclass
class ParameterCondition:
    """Gates a conditional parameter on a parent's value (hierarchical
    search spaces: e.g. moe_experts only matters when use_moe=true).
    Semantics are SMAC-style: suggesters always propose a value for every
    dimension (so learning algorithms see a fixed-dimensional space), but
    an INACTIVE parameter is dropped at trial-template render time —
    template lines whose placeholders are all inactive vanish from the
    rendered job."""

    parameter: str = ""            # parent ParameterSpec.name
    values: list[str] = field(default_factory=lambda: [])  # activating values


@dataclass
class ParameterSpec:
    name: str = ""
    parameter_type: ParameterType = ParameterType.DOUBLE
    feasible_space: FeasibleSpace = field(default_factory=FeasibleSpace)
    # None = unconditional (the common case)
    active_when: ParameterCondition | None = None


class ObjectiveType(str, enum.Enum):
    MINIMIZE = "minimize"
    MAXIMIZE = "maximize"


@dataclass
class ObjectiveTerm:
    """One extra objective for multi-objective experiments: collected like
    an additional metric, but it also steers optimal-trial selection
    (weighted scalarization) and the Pareto front."""

    metric_name: str = ""
    type: ObjectiveType = ObjectiveType.MAXIMIZE
    weight: float = 1.0


@dataclass
class Objective:
    type: ObjectiveType = ObjectiveType.MAXIMIZE
    # stop the experiment early once the best trial reaches this value;
    # with additional_objectives the goal still reads the PRIMARY metric
    goal: float | None = None
    objective_metric_name: str = ""
    additional_metric_names: list[str] = field(default_factory=lambda: [])
    # multi-objective: optimal trial = best weighted scalarization
    # (every term oriented into the primary type's direction);
    # status.pareto_front reports the non-dominated set
    additional_objectives: list[ObjectiveTerm] = field(
        default_factory=lambda: [])

    @property
    def collected_metric_names(self) -> list[str]:
        """Every non-primary metric the collector must gather: the
        additional metrics plus each additional objective's metric."""
        names = list(self.additional_metric_names)
        for term in self.additional_objectives:
            if term.metric_name not in names:
                names.append(term.metric_name)
        return names


@dataclass
class AlgorithmSpec:
    algorithm_name: str = "random"  # random | grid | tpe
    settings: dict[str, str] = field(default_factory=dict)


@dataclass
class EarlyStoppingSpec:
    """medianstop parity: kill running trials whose objective is worse than
    the median of completed trials (after min_trials_required complete)."""

    algorithm_name: str = "medianstop"
    min_trials_required: int = 3


@dataclass
class TrialParameterSpec:
    """Binds a ${trialParameters.<name>} placeholder to a search parameter."""

    name: str = ""
    description: str = ""
    reference: str = ""  # ParameterSpec.name this placeholder takes its value from


@dataclass
class TrialTemplate:
    """The job a trial runs: any TrainJob manifest (YAML) with
    ${trialParameters.x} placeholders — exactly how the reference launches
    TFJobs/PyTorchJobs from experiments, and how JAXJobs launch here."""

    trial_spec: str = ""  # YAML manifest with placeholders
    trial_parameters: list[TrialParameterSpec] = field(default_factory=lambda: [])


@dataclass
class ExperimentSpec:
    parameters: list[ParameterSpec] = field(default_factory=lambda: [])
    objective: Objective = field(default_factory=Objective)
    algorithm: AlgorithmSpec = field(default_factory=AlgorithmSpec)
    trial_template: TrialTemplate = field(default_factory=TrialTemplate)
    max_trial_count: int = 10
    parallel_trial_count: int = 3
    max_failed_trial_count: int = 3
    early_stopping: EarlyStoppingSpec | None = None
    # metrics are read from this replica's log (worker-0 by default)
    metrics_replica_type: str = "worker"
    # "stdout" (name=value log lines) or "tfevents" (TensorBoard event files
    # under tfevents_dir — katib's tfevent-metricscollector parity)
    metrics_source: str = "stdout"
    # tfevents source: dir pattern, ${trialName} substituted per trial; the
    # trial template should point KFTPU_EVENT_DIR at the same place
    tfevents_dir: str = ""
    # katib resumePolicy: "LongRunning" allows resume_experiment() to raise
    # maxTrialCount on a finished experiment and continue (durable
    # observations make the suggester's history survive); "Never" forbids it
    resume_policy: str = "LongRunning"


@dataclass
class ParameterAssignment:
    name: str = ""
    value: str = ""


@dataclass
class Metric:
    name: str = ""
    latest: float = 0.0
    min: float = 0.0
    max: float = 0.0


@dataclass
class Observation:
    metrics: list[Metric] = field(default_factory=lambda: [])

    def metric(self, name: str) -> Metric | None:
        for m in self.metrics:
            if m.name == name:
                return m
        return None


class TrialCondition(str, enum.Enum):
    CREATED = "Created"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    EARLY_STOPPED = "EarlyStopped"
    METRICS_UNAVAILABLE = "MetricsUnavailable"


@dataclass
class TrialSpec:
    parameter_assignments: list[ParameterAssignment] = field(default_factory=lambda: [])
    # fully-rendered manifest (template with assignments substituted)
    rendered_spec: str = ""


@dataclass
class TrialStatus:
    condition: TrialCondition = TrialCondition.CREATED
    observation: Observation = field(default_factory=Observation)
    start_time: str = ""
    completion_time: str = ""

    @property
    def is_finished(self) -> bool:
        return self.condition in (
            TrialCondition.SUCCEEDED,
            TrialCondition.FAILED,
            TrialCondition.EARLY_STOPPED,
            TrialCondition.METRICS_UNAVAILABLE,
        )


@dataclass
class Trial:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: TrialSpec = field(default_factory=TrialSpec)
    status: TrialStatus = field(default_factory=TrialStatus)
    kind: str = "Trial"
    api_version: str = "kubeflow-tpu.org/v1beta1"

    def assignments_dict(self) -> dict[str, str]:
        return {a.name: a.value for a in self.spec.parameter_assignments}


class ExperimentCondition(str, enum.Enum):
    CREATED = "Created"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


@dataclass
class OptimalTrial:
    trial_name: str = ""
    parameter_assignments: list[ParameterAssignment] = field(default_factory=lambda: [])
    observation: Observation = field(default_factory=Observation)


@dataclass
class ExperimentStatus:
    condition: ExperimentCondition = ExperimentCondition.CREATED
    trials: int = 0
    trials_running: int = 0
    trials_succeeded: int = 0
    trials_failed: int = 0
    trials_early_stopped: int = 0
    current_optimal_trial: OptimalTrial | None = None
    # multi-objective experiments: the non-dominated succeeded trials
    # (empty for single-objective)
    pareto_front: list[OptimalTrial] = field(default_factory=lambda: [])
    start_time: str = ""
    completion_time: str = ""
    message: str = ""

    @property
    def is_finished(self) -> bool:
        return self.condition in (
            ExperimentCondition.SUCCEEDED,
            ExperimentCondition.FAILED,
        )


@dataclass
class Experiment:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ExperimentSpec = field(default_factory=ExperimentSpec)
    status: ExperimentStatus = field(default_factory=ExperimentStatus)
    kind: str = "Experiment"
    api_version: str = "kubeflow-tpu.org/v1beta1"


def inactive_parameters(parameters: list[ParameterSpec],
                        assignments: dict[str, str]) -> set[str]:
    """Names of conditional parameters whose gate is NOT satisfied by this
    trial's assignments (see ParameterCondition semantics)."""
    out = set()
    for p in parameters:
        cond = p.active_when
        if cond is None:
            continue
        if assignments.get(cond.parameter) not in cond.values:
            out.add(p.name)
    return out


def scalarized_objective(obj: Objective, observation: Observation
                         ) -> float | None:
    """The value optimal-trial selection ranks by, oriented in the PRIMARY
    objective's direction (so the existing type-aware comparators apply).

    Single-objective: the primary metric itself. Multi-objective: primary
    + Σ weight·metric for each additional term, each term sign-flipped
    when its direction opposes the primary's. A finished trial missing any
    term ranks worst (nan)."""
    primary = observation.metric(obj.objective_metric_name)
    if primary is None:
        return None
    total = primary.latest
    for term in obj.additional_objectives:
        m = observation.metric(term.metric_name)
        if m is None:
            return float("nan")
        sign = 1.0 if term.type == obj.type else -1.0
        total += sign * term.weight * m.latest
    return total


def render_trial_spec(template: TrialTemplate, assignments: dict[str, str],
                      parameters: list[ParameterSpec] | None = None) -> str:
    """Substitute ${trialParameters.<name>} placeholders (katib's
    trialTemplate substitution contract).

    Conditional spaces: when `parameters` is given, placeholders bound to
    INACTIVE search parameters take their line with them — any template
    line that contains only inactive placeholders (of the lines that
    contain placeholders at all) is removed, so a conditional CLI flag or
    env entry vanishes instead of rendering `--flag=`."""
    out = template.trial_spec
    inactive = (inactive_parameters(parameters, assignments)
                if parameters is not None else set())
    dead_tokens, live_tokens = [], []
    for tp in template.trial_parameters:
        ref = tp.reference or tp.name
        token = "${trialParameters." + tp.name + "}"
        if ref in inactive:
            dead_tokens.append(token)
            continue
        live_tokens.append(token)
        value = assignments.get(ref)
        if value is None:
            raise ValueError(
                f"trial parameter {tp.name!r} references unknown search "
                f"parameter {tp.reference!r}"
            )
        out = out.replace(token, value)
    if dead_tokens:
        # a line mixing an inactive placeholder with an ACTIVE one has no
        # safe rendering (dropping it loses the active substitution;
        # keeping it leaves a raw placeholder) — template authors must put
        # conditional flags on their own line, enforced loudly. Live
        # placeholders are already substituted in `out`, so detect the mix
        # on the ORIGINAL template's lines.
        for line in template.trial_spec.split("\n"):
            if (any(t in line for t in dead_tokens)
                    and any(t in line for t in live_tokens)):
                raise ValueError(
                    "conditional parameter placeholder shares a template "
                    f"line with an active one: {line.strip()!r} — put "
                    "conditional flags/envs on their own line")
        kept = [line for line in out.split("\n")
                if not any(t in line for t in dead_tokens)]
        out = "\n".join(kept)
    return out


def validate_experiment(exp: Experiment) -> Experiment:
    """Admission checks (katib experiment webhook parity)."""
    if not exp.metadata.name:
        raise ValueError("experiment: metadata.name is required")
    if not exp.spec.parameters:
        raise ValueError("experiment: at least one search parameter required")
    names = set()
    for p in exp.spec.parameters:
        if not p.name or p.name in names:
            raise ValueError(f"experiment: duplicate/empty parameter name {p.name!r}")
        names.add(p.name)
        fs = p.feasible_space
        if p.parameter_type in (ParameterType.DOUBLE, ParameterType.INT):
            if fs.min == "" or fs.max == "":
                raise ValueError(f"parameter {p.name}: numeric space needs min/max")
            if float(fs.min) > float(fs.max):
                raise ValueError(f"parameter {p.name}: min > max")
        else:
            if not fs.list:
                raise ValueError(f"parameter {p.name}: categorical space needs list")
    by_name = {p.name: p for p in exp.spec.parameters}
    for p in exp.spec.parameters:
        cond = p.active_when
        if cond is None:
            continue
        parent = by_name.get(cond.parameter)
        if parent is None or parent is p:
            raise ValueError(
                f"parameter {p.name}: active_when.parameter "
                f"{cond.parameter!r} must name another experiment parameter")
        if parent.active_when is not None:
            raise ValueError(
                f"parameter {p.name}: active_when parent {cond.parameter!r} "
                "is itself conditional — only one level of nesting is "
                "supported")
        if not cond.values:
            raise ValueError(
                f"parameter {p.name}: active_when.values must be non-empty")
        if parent.parameter_type in (ParameterType.CATEGORICAL,
                                     ParameterType.DISCRETE):
            unknown = [v for v in cond.values
                       if v not in parent.feasible_space.list]
            if unknown:
                raise ValueError(
                    f"parameter {p.name}: active_when.values {unknown} not "
                    f"in parent {cond.parameter!r}'s feasible list")
    if not exp.spec.objective.objective_metric_name:
        raise ValueError("experiment: objective.objectiveMetricName required")
    for term in exp.spec.objective.additional_objectives:
        if not term.metric_name:
            raise ValueError(
                "experiment: additional_objectives entries need metricName")
        if term.metric_name == exp.spec.objective.objective_metric_name:
            raise ValueError(
                f"experiment: additional objective {term.metric_name!r} "
                "duplicates the primary objective")
    algo = exp.spec.algorithm.algorithm_name
    if algo == "darts":
        raise ValueError(
            "experiment: darts is a one-shot IN-TRIAL search, not a "
            "trial-loop algorithm — run "
            "kubeflow_tpu.train.oneshot.darts_search inside a single "
            "trial (examples/darts_digits.py); for controller-driven NAS "
            "over trials use 'enas' or 'evolution'"
        )
    if algo not in (
        "random", "grid", "tpe", "cmaes",
        "bayesianoptimization", "gp", "skopt", "hyperband",
        "evolution", "nas", "enas",
    ):
        raise ValueError(
            f"experiment: unknown algorithm {algo!r} "
            f"(random|grid|tpe|cmaes|bayesianoptimization|hyperband|"
            f"evolution|enas)"
        )
    if algo == "hyperband":
        rp = exp.spec.algorithm.settings.get("resourceParameter", "")
        by_name = {p.name: p for p in exp.spec.parameters}
        if rp not in by_name:
            raise ValueError(
                "experiment: hyperband needs settings.resourceParameter "
                "naming one of the experiment parameters"
            )
        if by_name[rp].parameter_type in (
            ParameterType.CATEGORICAL, ParameterType.DISCRETE
        ):
            raise ValueError(
                "experiment: the hyperband resource parameter must be numeric"
            )
    if algo == "cmaes":
        for p in exp.spec.parameters:
            if p.parameter_type in (ParameterType.CATEGORICAL, ParameterType.DISCRETE):
                raise ValueError(
                    f"experiment: cmaes supports numeric parameters only; "
                    f"{p.name!r} is {p.parameter_type.value}"
                )
        pop = exp.spec.algorithm.settings.get("popsize")
        if pop is not None:
            try:
                pop_i = int(pop)
            except ValueError:
                raise ValueError(
                    f"experiment: cmaes popsize must be an integer, got {pop!r}"
                ) from None
            if pop_i < 2:
                raise ValueError("experiment: cmaes popsize must be >= 2")
        sigma = exp.spec.algorithm.settings.get("sigma")
        if sigma is not None:
            try:
                sigma_f = float(sigma)
            except ValueError:
                raise ValueError(
                    f"experiment: cmaes sigma must be a number, got {sigma!r}"
                ) from None
            if sigma_f <= 0:
                raise ValueError("experiment: cmaes sigma must be > 0")
    if exp.spec.resume_policy not in ("LongRunning", "Never"):
        raise ValueError(
            f"spec.resumePolicy: unknown policy {exp.spec.resume_policy!r} "
            f"(LongRunning | Never)"
        )
    if exp.spec.max_trial_count < 1 or exp.spec.parallel_trial_count < 1:
        raise ValueError("experiment: trial counts must be >= 1")
    if not exp.spec.trial_template.trial_spec:
        raise ValueError("experiment: trialTemplate.trialSpec required")
    if exp.spec.metrics_source not in ("stdout", "tfevents"):
        raise ValueError(
            f"experiment: metricsSource {exp.spec.metrics_source!r} "
            f"(stdout|tfevents)"
        )
    if exp.spec.metrics_source == "tfevents" and not exp.spec.tfevents_dir:
        raise ValueError("experiment: tfevents metricsSource needs tfeventsDir")
    for tp in exp.spec.trial_template.trial_parameters:
        ref = tp.reference or tp.name
        if ref not in names:
            raise ValueError(
                f"trialParameter {tp.name!r} references unknown parameter {ref!r}"
            )
    return exp
