"""Durable sweep observation store — katib db-manager parity.

Reference parity (unverified cites, SURVEY.md §2.4): katib's metrics
collector pushes ReportObservationLog over gRPC to cmd/db-manager, which
persists observations in MySQL so experiment history survives controller
restarts. Here finished trials are recorded into the native C++ metadata
store (native/src/metastore.cc — the same store pipelines use for lineage),
keyed by experiment spec fingerprint so a restarted platform that re-submits
the SAME experiment resumes with its full trial history instead of re-running
completed trials.
"""

from __future__ import annotations

import hashlib
import json

from kubeflow_tpu.api.common import ObjectMeta
from kubeflow_tpu.sweep.api import (
    Experiment,
    Metric,
    Observation,
    ParameterAssignment,
    Trial,
    TrialCondition,
    TrialSpec,
    TrialStatus,
)

TRIAL_TYPE = "sweep.trial"


def experiment_fingerprint(exp: Experiment) -> str:
    """Stable hash of the search definition: same spec => same history."""
    from kubeflow_tpu.api.serde import to_dict

    spec = to_dict(exp.spec)
    return hashlib.sha256(
        json.dumps(spec, sort_keys=True).encode()
    ).hexdigest()[:16]


class ObservationStore:
    def __init__(self, path: str):
        from kubeflow_tpu.native import MetadataStore

        self._ms = MetadataStore(path)
        # name -> execution id, so repeated record() calls update in place
        self._ids: dict[str, int] = {
            r["name"]: int(r["id"])
            for r in self._ms.list_executions(TRIAL_TYPE)
        }

    def close(self) -> None:
        self._ms.close()

    @staticmethod
    def _name(exp: Experiment, trial_name: str) -> str:
        return f"{exp.metadata.namespace}/{exp.metadata.name}/{trial_name}"

    def record(self, exp: Experiment, trial: Trial) -> None:
        """Persist a finished trial (idempotent upsert by name)."""
        name = self._name(exp, trial.metadata.name)
        props = json.dumps({
            "fingerprint": experiment_fingerprint(exp),
            "trial": trial.metadata.name,
            "assignments": trial.assignments_dict(),
            "metrics": [
                {"name": m.name, "latest": m.latest, "min": m.min, "max": m.max}
                for m in trial.status.observation.metrics
            ],
            "completion_time": trial.status.completion_time,
        })
        self._ids[name] = self._ms.put_execution(
            TRIAL_TYPE, name, state=trial.status.condition.value, props=props,
            id=self._ids.get(name, 0),
        )

    def restore(self, exp: Experiment) -> list[Trial]:
        """Rebuild finished Trial objects recorded for this experiment.

        Only records whose spec fingerprint matches are returned: a deleted-
        and-recreated experiment with a different search space starts fresh.
        """
        prefix = f"{exp.metadata.namespace}/{exp.metadata.name}/"
        fp = experiment_fingerprint(exp)
        out = []
        for rec in self._ms.list_executions(TRIAL_TYPE):
            if not rec["name"].startswith(prefix):
                continue
            try:
                props = json.loads(rec["props"])
            except json.JSONDecodeError:
                continue
            if props.get("fingerprint") != fp:
                continue
            out.append(Trial(
                metadata=ObjectMeta(
                    name=props["trial"],
                    namespace=exp.metadata.namespace,
                    labels={"kubeflow-tpu.org/experiment-name": exp.metadata.name},
                ),
                spec=TrialSpec(
                    parameter_assignments=[
                        ParameterAssignment(name=k, value=v)
                        for k, v in props.get("assignments", {}).items()
                    ],
                ),
                status=TrialStatus(
                    condition=TrialCondition(rec["state"]),
                    observation=Observation(metrics=[
                        Metric(**m) for m in props.get("metrics", [])
                    ]),
                    completion_time=props.get("completion_time", ""),
                ),
            ))
        return sorted(out, key=lambda t: t.metadata.name)
