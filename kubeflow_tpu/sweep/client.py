"""SweepClient — KatibClient parity (create_experiment / tune / wait).

Reference parity (unverified cites, SURVEY.md §2.4): katib
sdk/python/v1beta1 KatibClient.{create_experiment, tune, get_experiment,
wait_for_experiment_condition, get_optimal_hyperparameters}. `tune()` wraps a
plain Python function into a trial job by templating its source into a
generated script — the same trick the reference SDK uses to containerize a
function, minus the container image.
"""

from __future__ import annotations

import inspect
import json
import sys
import textwrap
from pathlib import Path

from kubeflow_tpu.api.common import ObjectMeta
from kubeflow_tpu.utils.retry import BackoffPolicy, poll_until
from kubeflow_tpu.sweep.api import (
    ExperimentCondition,
    AlgorithmSpec,
    EarlyStoppingSpec,
    Experiment,
    ExperimentSpec,
    Objective,
    ObjectiveType,
    ParameterSpec,
    ParameterType,
    Trial,
    TrialParameterSpec,
    TrialTemplate,
    validate_experiment,
)

_CAST = {
    ParameterType.DOUBLE: "float",
    ParameterType.INT: "int",
    ParameterType.CATEGORICAL: "str",
    ParameterType.DISCRETE: "str",
}


class SweepClient:
    def __init__(self, platform, work_dir: str = ".kubeflow_tpu/sweeps"):
        self.platform = platform
        self.cluster = platform.cluster
        self.work_dir = Path(work_dir)

    # ------------------------------------------------------------------ CRUD

    def create_experiment(self, exp: Experiment) -> Experiment:
        validate_experiment(exp)
        return self.cluster.create("experiments", exp)

    def get_experiment(self, name: str, namespace: str = "default") -> Experiment | None:
        return self.cluster.get("experiments", f"{namespace}/{name}")

    def list_trials(self, name: str, namespace: str = "default") -> list[Trial]:
        return sorted(
            self.cluster.list(
                "trials",
                lambda t: t.metadata.labels.get("kubeflow-tpu.org/experiment-name")
                == name
                and t.metadata.namespace == namespace,
            ),
            key=lambda t: t.metadata.name,
        )

    def delete_experiment(self, name: str, namespace: str = "default") -> None:
        from kubeflow_tpu.controller.jobcontroller import delete_job_cascade

        for t in self.list_trials(name, namespace):
            delete_job_cascade(self.cluster, t.metadata.name, namespace)
            self.cluster.delete("trials", f"{namespace}/{t.metadata.name}")
        self.cluster.delete("experiments", f"{namespace}/{name}")

    def resume_experiment(
        self, name: str, max_trial_count: int, namespace: str = "default"
    ) -> Experiment:
        """Resume a SUCCEEDED experiment with a larger trial budget (katib
        resumePolicy=LongRunning semantics): the terminal condition is
        cleared and the controller keeps suggesting — its history (all prior
        trials + durable observations) carries over, so a Bayesian/TPE
        suggester continues from everything already learned. FAILED
        experiments are not resumable: the controller would re-fail them on
        the unchanged failed-trial budget before any new trial ran."""

        def mutate(exp: Experiment) -> None:
            if exp.spec.resume_policy == "Never":
                raise ValueError(
                    f"experiment {name} has resumePolicy=Never; cannot resume"
                )
            if not exp.status.is_finished:
                raise ValueError(f"experiment {name} is still running")
            if exp.status.condition == ExperimentCondition.FAILED:
                raise ValueError(
                    f"experiment {name} finished FAILED; only Succeeded "
                    f"experiments resume (the failed-trial budget already "
                    f"tripped and would re-finish it immediately)"
                )
            if exp.status.message in ("GoalReached", "SpaceExhausted"):
                # the controller would re-finish on the unchanged condition
                # before spawning anything — resuming is a silent no-op
                raise ValueError(
                    f"experiment {name} finished via "
                    f"{exp.status.message}; a larger trial budget cannot "
                    f"produce more trials (clear objective.goal or widen "
                    f"the search space instead)"
                )
            finished = sum(
                1 for t in self.list_trials(name, namespace)
                if t.status.is_finished
            )
            if max_trial_count <= finished:
                raise ValueError(
                    f"maxTrialCount {max_trial_count} must exceed the "
                    f"{finished} trials already finished"
                )
            exp.spec.max_trial_count = max_trial_count
            exp.status.condition = ExperimentCondition.RUNNING
            exp.status.completion_time = ""
            exp.status.message = f"resumed with maxTrialCount={max_trial_count}"

        return self.cluster.read_modify_write(
            "experiments", f"{namespace}/{name}", mutate, backoff_s=0.05
        )

    # ---------------------------------------------------------------- status

    def wait_for_experiment(
        self, name: str, namespace: str = "default", timeout_s: float = 300.0,
        poll_s: float = 0.2,
    ) -> Experiment:
        def finished() -> Experiment | None:
            exp = self.get_experiment(name, namespace)
            return exp if exp is not None and exp.status.is_finished else None

        return poll_until(
            finished,
            timeout_s=timeout_s,
            policy=BackoffPolicy(base_s=0.02, max_s=poll_s, jitter=0.5),
            describe=f"experiment {namespace}/{name} finished",
        )

    def get_optimal_hyperparameters(
        self, name: str, namespace: str = "default"
    ) -> dict[str, str]:
        exp = self.get_experiment(name, namespace)
        if exp is None or exp.status.current_optimal_trial is None:
            return {}
        return {
            a.name: a.value
            for a in exp.status.current_optimal_trial.parameter_assignments
        }

    # ------------------------------------------------------------------ tune

    def tune(
        self,
        name: str,
        objective_fn,
        parameters: list[ParameterSpec],
        objective_metric: str,
        objective_type: ObjectiveType = ObjectiveType.MAXIMIZE,
        goal: float | None = None,
        algorithm: str = "random",
        algorithm_settings: dict[str, str] | None = None,
        max_trial_count: int = 10,
        parallel_trial_count: int = 3,
        max_failed_trial_count: int = 3,
        early_stopping: EarlyStoppingSpec | None = None,
        namespace: str = "default",
    ) -> Experiment:
        """Sweep a plain Python function.

        `objective_fn(**params)` must print metrics in `name=value` form
        (metrics_lib.emit does). Its source is templated into a generated
        trial script; parameters arrive via a TRIAL_PARAMETERS JSON env var
        rendered from ${trialParameters.*} placeholders.
        """
        self.work_dir.mkdir(parents=True, exist_ok=True)
        src = textwrap.dedent(inspect.getsource(objective_fn))
        casts = {p.name: _CAST[p.parameter_type] for p in parameters}
        # filename carries namespace + content hash: a re-tune with a changed
        # objective (or a same-named tune in another namespace) must never
        # overwrite the script that live trials are executing
        import hashlib

        digest = hashlib.sha256(src.encode()).hexdigest()[:12]
        script = self.work_dir / f"{namespace}-{name}-{digest}-trial.py"
        script.write_text(
            src
            + textwrap.dedent(
                f"""
                if __name__ == "__main__":
                    import json, os
                    _casts = {casts!r}
                    _raw = json.loads(os.environ["TRIAL_PARAMETERS"])
                    _params = {{
                        k: {{"float": float, "int": int, "str": str}}[_casts[k]](v)
                        for k, v in _raw.items()
                    }}
                    {objective_fn.__name__}(**_params)
                """
            )
        )
        params_json = json.dumps(
            {p.name: "${trialParameters." + p.name + "}" for p in parameters}
        )
        trial_spec = {
            "apiVersion": "kubeflow-tpu.org/v1",
            "kind": "JAXJob",
            "spec": {
                "replicaSpecs": {
                    "worker": {
                        "replicas": 1,
                        "template": {
                            "container": {
                                "command": [sys.executable, str(script.resolve())],
                                "env": {"TRIAL_PARAMETERS": params_json},
                            }
                        },
                    }
                }
            },
        }
        import yaml

        exp = Experiment(
            metadata=ObjectMeta(name=name, namespace=namespace),
            spec=ExperimentSpec(
                parameters=parameters,
                objective=Objective(
                    type=objective_type,
                    goal=goal,
                    objective_metric_name=objective_metric,
                ),
                algorithm=AlgorithmSpec(
                    algorithm_name=algorithm, settings=algorithm_settings or {}
                ),
                trial_template=TrialTemplate(
                    trial_spec=yaml.safe_dump(trial_spec, sort_keys=False),
                    trial_parameters=[
                        TrialParameterSpec(name=p.name, reference=p.name)
                        for p in parameters
                    ],
                ),
                max_trial_count=max_trial_count,
                parallel_trial_count=parallel_trial_count,
                max_failed_trial_count=max_failed_trial_count,
                early_stopping=early_stopping,
            ),
        )
        return self.create_experiment(exp)
