"""gRPC control plane for sweeps — suggestion service + db-manager parity.

Reference parity (unverified cites, SURVEY.md §2.3/§2.4): katib runs one
suggestion Deployment per experiment behind gRPC `GetSuggestions` /
`ValidateAlgorithmSettings` (pkg/apis/manager/v1beta1/api.proto) and a
db-manager gRPC facade over the observation log. Both surfaces exist here
over the same wire protocol: protobuf messages (protos/sweep.proto compiled
with protoc) and grpcio, with service methods wired via
`method_handlers_generic_handler` — the image ships no grpc_tools codegen
plugin, and the hand wiring is ~20 lines.

The ExperimentController uses suggesters in-process by default (the gRPC
hop existed upstream because algorithms ran in separate Deployments);
pointing it at `suggestion_endpoint` restores the remote topology.
"""

from __future__ import annotations

import math
from concurrent import futures

import grpc

from kubeflow_tpu.protos import sweep_pb2 as pb
from kubeflow_tpu.sweep.api import (
    FeasibleSpace,
    ObjectiveType,
    ParameterSpec,
    ParameterType,
)
from kubeflow_tpu.sweep.suggest import get_suggester

SUGGESTION_SERVICE = "kubeflow_tpu.sweep.Suggestion"
DBMANAGER_SERVICE = "kubeflow_tpu.sweep.DBManager"


# ------------------------------------------------------------- proto <-> api

def _param_from_pb(p: pb.Parameter) -> ParameterSpec:
    return ParameterSpec(
        name=p.name,
        parameter_type=ParameterType(p.type),
        feasible_space=FeasibleSpace(
            min=p.min, max=p.max, list=list(p.list), step=p.step
        ),
    )


def _history_from_pb(entries) -> list[tuple[dict[str, str], float | None]]:
    out = []
    for e in entries:
        a = {x.name: x.value for x in e.assignments}
        if e.failed:
            out.append((a, float("nan")))
        elif e.has_objective:
            out.append((a, e.objective))
        else:
            out.append((a, None))
    return out


def history_to_pb(history) -> list[pb.HistoryEntry]:
    out = []
    for a, o in history:
        e = pb.HistoryEntry(
            assignments=[pb.Assignment(name=k, value=v) for k, v in a.items()]
        )
        if o is None:
            e.has_objective = False
        elif isinstance(o, float) and math.isnan(o):
            e.failed = True
        else:
            e.has_objective = True
            e.objective = float(o)
        out.append(e)
    return out


# ------------------------------------------------------------------ services

class SuggestionService:
    """katib suggestion-service parity: stateless, algorithm picked per call."""

    def GetSuggestions(self, req: pb.GetSuggestionsRequest, ctx):
        try:
            suggester = get_suggester(
                req.algorithm,
                [_param_from_pb(p) for p in req.parameters],
                seed=int(req.seed),
                objective_type=ObjectiveType(req.objective_type or "maximize"),
                settings=dict(req.settings),
            )
            suggestions = suggester.suggest(
                _history_from_pb(req.history), int(req.count)
            )
        except (ValueError, KeyError) as exc:
            ctx.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))
        return pb.GetSuggestionsReply(suggestions=[
            pb.AssignmentSet(assignments=[
                pb.Assignment(name=k, value=v) for k, v in a.items()
            ])
            for a in suggestions
        ])

    def ValidateAlgorithmSettings(self, req, ctx):
        try:
            get_suggester(
                req.algorithm,
                [_param_from_pb(p) for p in req.parameters],
                settings=dict(req.settings),
            )
        except (ValueError, KeyError) as exc:
            return pb.ValidateAlgorithmSettingsReply(ok=False, message=str(exc))
        return pb.ValidateAlgorithmSettingsReply(ok=True)


class DBManagerService:
    """katib db-manager parity over the durable observation store."""

    def __init__(self, observation_db: str):
        from kubeflow_tpu.sweep.store import ObservationStore

        self._store = ObservationStore(observation_db)

    def ReportObservation(self, req: pb.ReportObservationRequest, ctx):
        import json

        name = f"{req.namespace}/{req.experiment}/{req.trial}"
        props = json.dumps({
            "fingerprint": req.fingerprint,
            "trial": req.trial,
            "assignments": {a.name: a.value for a in req.assignments},
            "metrics": [
                {"name": m.name, "latest": m.latest, "min": m.min, "max": m.max}
                for m in req.metrics
            ],
            "completion_time": req.completion_time,
        })
        self._store._ids[name] = self._store._ms.put_execution(
            "sweep.trial", name, state=req.condition, props=props,
            id=self._store._ids.get(name, 0),
        )
        return pb.Empty()

    def GetObservations(self, req: pb.GetObservationsRequest, ctx):
        import json

        prefix = f"{req.namespace}/{req.experiment}/"
        out = []
        for rec in self._store._ms.list_executions("sweep.trial"):
            if not rec["name"].startswith(prefix):
                continue
            try:
                props = json.loads(rec["props"])
            except json.JSONDecodeError:
                continue
            if req.fingerprint and props.get("fingerprint") != req.fingerprint:
                continue
            out.append(pb.TrialObservation(
                trial=props.get("trial", ""),
                condition=rec["state"],
                assignments=[
                    pb.Assignment(name=k, value=v)
                    for k, v in props.get("assignments", {}).items()
                ],
                metrics=[pb.Metric(**m) for m in props.get("metrics", [])],
                completion_time=props.get("completion_time", ""),
            ))
        return pb.GetObservationsReply(
            trials=sorted(out, key=lambda t: t.trial)
        )

    def close(self) -> None:
        self._store.close()


# ------------------------------------------------------------------- wiring

def _unary(fn, req_cls):
    return grpc.unary_unary_rpc_method_handler(
        fn,
        request_deserializer=req_cls.FromString,
        response_serializer=lambda m: m.SerializeToString(),
    )


def serve(
    port: int = 0,
    host: str = "127.0.0.1",
    observation_db: str | None = None,
    max_workers: int = 4,
):
    """Start the gRPC server; returns (server, address, dbmanager|None)."""
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    sugg = SuggestionService()
    server.add_generic_rpc_handlers((
        grpc.method_handlers_generic_handler(SUGGESTION_SERVICE, {
            "GetSuggestions": _unary(
                sugg.GetSuggestions, pb.GetSuggestionsRequest
            ),
            "ValidateAlgorithmSettings": _unary(
                sugg.ValidateAlgorithmSettings,
                pb.ValidateAlgorithmSettingsRequest,
            ),
        }),
    ))
    db = None
    if observation_db:
        db = DBManagerService(observation_db)
        server.add_generic_rpc_handlers((
            grpc.method_handlers_generic_handler(DBMANAGER_SERVICE, {
                "ReportObservation": _unary(
                    db.ReportObservation, pb.ReportObservationRequest
                ),
                "GetObservations": _unary(
                    db.GetObservations, pb.GetObservationsRequest
                ),
            }),
        ))
    bound = server.add_insecure_port(f"{host}:{port}")
    server.start()
    return server, f"{host}:{bound}", db


class SuggestionClient:
    """Typed client over the suggestion + db-manager services."""

    def __init__(self, address: str):
        self._chan = grpc.insecure_channel(address)
        self._get = self._chan.unary_unary(
            f"/{SUGGESTION_SERVICE}/GetSuggestions",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.GetSuggestionsReply.FromString,
        )
        self._validate = self._chan.unary_unary(
            f"/{SUGGESTION_SERVICE}/ValidateAlgorithmSettings",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.ValidateAlgorithmSettingsReply.FromString,
        )
        self._report = self._chan.unary_unary(
            f"/{DBMANAGER_SERVICE}/ReportObservation",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.Empty.FromString,
        )
        self._observations = self._chan.unary_unary(
            f"/{DBMANAGER_SERVICE}/GetObservations",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.GetObservationsReply.FromString,
        )

    def get_suggestions(
        self,
        algorithm: str,
        parameters: list[ParameterSpec],
        history,
        count: int,
        settings: dict[str, str] | None = None,
        objective_type: ObjectiveType = ObjectiveType.MAXIMIZE,
        seed: int = 0,
    ) -> list[dict[str, str]]:
        req = pb.GetSuggestionsRequest(
            algorithm=algorithm,
            parameters=[_param_to_pb(p) for p in parameters],
            history=history_to_pb(history),
            count=count,
            settings=settings or {},
            objective_type=objective_type.value,
            seed=seed,
        )
        reply = self._get(req)
        return [
            {a.name: a.value for a in s.assignments} for s in reply.suggestions
        ]

    def validate(self, algorithm: str, parameters, settings=None):
        reply = self._validate(pb.ValidateAlgorithmSettingsRequest(
            algorithm=algorithm,
            parameters=[_param_to_pb(p) for p in parameters],
            settings=settings or {},
        ))
        return reply.ok, reply.message

    def report_observation(self, namespace, experiment, trial, condition,
                           assignments, metrics, fingerprint="",
                           completion_time=""):
        self._report(pb.ReportObservationRequest(
            namespace=namespace, experiment=experiment, trial=trial,
            condition=condition, fingerprint=fingerprint,
            assignments=[
                pb.Assignment(name=k, value=v) for k, v in assignments.items()
            ],
            metrics=[pb.Metric(**m) for m in metrics],
            completion_time=completion_time,
        ))

    def get_observations(self, namespace, experiment, fingerprint=""):
        reply = self._observations(pb.GetObservationsRequest(
            namespace=namespace, experiment=experiment, fingerprint=fingerprint,
        ))
        return [
            {
                "trial": t.trial,
                "condition": t.condition,
                "assignments": {a.name: a.value for a in t.assignments},
                "metrics": [
                    {"name": m.name, "latest": m.latest, "min": m.min,
                     "max": m.max}
                    for m in t.metrics
                ],
            }
            for t in reply.trials
        ]

    def close(self) -> None:
        self._chan.close()


def _param_to_pb(p: ParameterSpec) -> pb.Parameter:
    fs = p.feasible_space
    return pb.Parameter(
        name=p.name, type=p.parameter_type.value,
        list=list(fs.list), min=fs.min, max=fs.max, step=fs.step,
    )
