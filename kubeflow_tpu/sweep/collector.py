"""Metrics collection from trial logs — the sidecar-collector analogue.

Reference parity (unverified cites, SURVEY.md §2.4): katib's mutating pod
webhook injects a sidecar that tails stdout and regex-parses `metric=value`
pairs into the observation log (pkg/webhook/v1beta1/pod/inject_webhook.go,
cmd/metricscollector/v1beta1/file-metricscollector). Here there is no
sidecar to inject: the pod runtime already captures every pod's stdout to a
log file, and the collector parses it post-hoc (or live, for early
stopping) with the same regex contract.

The trainer's metrics_lib.emit prints exactly this format
(`step=120 loss=0.41 accuracy=0.88 ...`), so in-tree models are collectable
with zero configuration.
"""

from __future__ import annotations

import re

from kubeflow_tpu.sweep.api import Metric, Observation

# katib's file-metricscollector default filter, era-dependent:
# ([\w|-]+)\s*=\s*((-?\d+)(\.\d+)?([Ee][+-]?\d+)?) — extended with [./] in
# names for namespaced metrics like eval/loss.
METRIC_RE = re.compile(
    r"([\w./|-]+)\s*=\s*([+-]?\d+(?:\.\d+)?(?:[Ee][+-]?\d+)?)(?![\w.])"
)


def parse_metrics(text: str, names: set[str] | None = None) -> dict[str, list[float]]:
    """All `name=value` observations in log order, optionally filtered to
    `names`. Returns {metric: [v0, v1, ...]} timelines."""
    out: dict[str, list[float]] = {}
    for line in text.splitlines():
        for m in METRIC_RE.finditer(line):
            name, val = m.group(1), m.group(2)
            if names is not None and name not in names:
                continue
            try:
                out.setdefault(name, []).append(float(val))
            except ValueError:
                continue
    return out


def observation_from_log(
    text: str, objective_metric: str, additional: list[str] | None = None
) -> Observation:
    """Build a trial Observation (latest/min/max per metric) from a log."""
    names = {objective_metric, *(additional or [])}
    timelines = parse_metrics(text, names)
    return _observation(timelines)


def _observation(timelines: dict[str, list[float]]) -> Observation:
    obs = Observation()
    for name in sorted(timelines):
        vals = timelines[name]
        obs.metrics.append(
            Metric(name=name, latest=vals[-1], min=min(vals), max=max(vals))
        )
    return obs


# ---------------------------------------------------------------- tfevents

def parse_tfevents_points(
    logdir: str, names: set[str] | None = None
) -> dict[str, list[tuple[int, float]]]:
    """Step-ordered (step, value) pairs per scalar tag — the point-preserving
    sibling of parse_tfevents (the tbviewer charts need real step x-axes)."""
    import os

    from tensorboard.backend.event_processing.event_file_loader import (
        EventFileLoader,
    )

    points: dict[str, list[tuple[int, float]]] = {}
    if not os.path.isdir(logdir):
        return {}
    files = sorted(
        os.path.join(root, f)
        for root, _, fs in os.walk(logdir)
        for f in fs
        if "tfevents" in f
    )
    for path in files:
        for ev in EventFileLoader(path).Load():
            for val in ev.summary.value:
                if names is not None and val.tag not in names:
                    continue
                if val.HasField("simple_value"):
                    v = float(val.simple_value)
                elif val.HasField("tensor") and val.tensor.float_val:
                    v = float(val.tensor.float_val[0])
                else:
                    continue
                points.setdefault(val.tag, []).append((ev.step, v))
    # stable key-sort: duplicate steps (restarted runs re-logging a step)
    # keep write order, so "latest" stays the newest write, not the largest
    # value; NaNs never enter the comparison
    return {
        t: sorted(p, key=lambda q: q[0]) for t, p in points.items()
    }


def parse_tfevents(logdir: str, names: set[str] | None = None) -> dict[str, list[float]]:
    """Scalar timelines from a tfevents dir (katib's tfevent-metricscollector
    parity, cmd/metricscollector/v1beta1/tfevent-metricscollector). Handles
    both simple_value and tensor-encoded scalars; step-ordered."""
    return {
        tag: [v for _, v in pts]
        for tag, pts in parse_tfevents_points(logdir, names).items()
    }


def observation_from_tfevents(
    logdir: str, objective_metric: str, additional: list[str] | None = None
) -> Observation:
    names = {objective_metric, *(additional or [])}
    return _observation(parse_tfevents(logdir, names))
