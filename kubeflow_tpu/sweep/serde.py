"""Experiment/Trial YAML round-trip (katib CR manifest parity).

Reuses the generic camelCase dataclass codec from api/serde.py so sweep
manifests look like the reference's Experiment CRs (samples/ has fixtures).
"""

from __future__ import annotations

import yaml

from kubeflow_tpu.api.serde import _from_dict, to_dict
from kubeflow_tpu.sweep.api import Experiment, Trial


def experiment_to_dict(exp: Experiment) -> dict:
    d = to_dict(exp)
    d.pop("kind", None)
    d.pop("apiVersion", None)
    if exp.status.condition.value == "Created" and not exp.status.start_time:
        d.pop("status", None)
    return {"apiVersion": exp.api_version, "kind": exp.kind, **d}


def experiment_to_yaml(exp: Experiment) -> str:
    return yaml.safe_dump(experiment_to_dict(exp), sort_keys=False)


def experiment_from_dict(data: dict) -> Experiment:
    body = {k: v for k, v in data.items() if k not in ("kind", "apiVersion")}
    return _from_dict(Experiment, body)


def experiment_from_yaml(text: str) -> Experiment:
    return experiment_from_dict(yaml.safe_load(text))


def trial_to_dict(t: Trial) -> dict:
    d = to_dict(t)
    d.pop("kind", None)
    d.pop("apiVersion", None)
    return {"apiVersion": t.api_version, "kind": t.kind, **d}


def trial_from_dict(data: dict) -> Trial:
    body = {k: v for k, v in data.items() if k not in ("kind", "apiVersion")}
    return _from_dict(Trial, body)
