"""GPT — decoder-only causal LM, the long-context flagship.

The reference platform ships no models (kubeflow/examples supplies encoder
images — SURVEY.md L6); this family exists because long-context training is
first-class here (SURVEY.md §5.7): causal ring attention shards the sequence
over the `context` axis with GLOBAL-position masking (parallel/ring_attention
.py), so a sequence 8x one device's memory trains with the same module.

Architecture: pre-LN transformer decoder (GPT-2 shape), learned OR rotary
positions (GPTConfig.position_embedding — rope has no position table),
optional grouped-query attention (num_kv_heads), weight-tied LM head,
bf16 compute / f32 params. TP/FSDP via the same declarative
PARTITION_RULES mechanism as BERT.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from kubeflow_tpu.models.bert import (
    ACT_SPEC,
    VocabEmbed,
    _resolve_attention,
    constrain,
)
from kubeflow_tpu.parallel.mesh import AXIS_FSDP, AXIS_MODEL

from kubeflow_tpu.parallel.moe import MOE_PARTITION_RULES, MoeMlp

PARTITION_RULES: list[tuple[str, P]] = [
    (r"(query|key|value)/kernel$", P(AXIS_FSDP, AXIS_MODEL)),
    (r"attn_out/kernel$", P(AXIS_MODEL, AXIS_FSDP)),
    (r"(mlp_up|mlp_gate)/kernel$", P(AXIS_FSDP, AXIS_MODEL)),
    (r"mlp_down/kernel$", P(AXIS_MODEL, AXIS_FSDP)),
    (r"token_embed/embedding$", P(AXIS_MODEL, AXIS_FSDP)),
    (r"lm_head/kernel$", P(AXIS_FSDP, AXIS_MODEL)),
    (r"position_embed/embedding$", P(None, AXIS_FSDP)),
    *MOE_PARTITION_RULES,
]


@dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    # grouped-query attention (Llama/Mistral shape): K/V projected to this
    # many heads, each shared by num_heads/num_kv_heads query heads. 0 =
    # num_heads (MHA); 1 = multi-query. The KV cache shrinks by the same
    # ratio — the direct lever on decode, which is HBM-bandwidth-bound.
    num_kv_heads: int = 0
    # "learned" (GPT-2 absolute embeddings) | "rope" (rotary, the
    # Llama/Mistral scheme: positions enter as Q/K rotations per layer,
    # no position table — decode rotates by the cache index, so the
    # pattern extrapolates with sequence position)
    position_embedding: str = "learned"
    rope_theta: float = 10000.0
    # rolling decode cache (Mistral serving): with a sliding window, the
    # KV cache can be a ring buffer of this many slots instead of a full
    # (max_len)-deep buffer — decode attention bandwidth and cache memory
    # scale with the capacity, not the context budget. Prompts must fit
    # capacity - window + 1 positions (trace-time check); 0 = full cache.
    kv_cache_capacity: int = 0
    # sliding-window attention (Mistral): each query attends to at most
    # the previous `attention_window` positions (itself included). 0 =
    # full causal. Composes with GQA + rope on EVERY path since r4:
    # dense, decode, flash (whole out-of-window KV blocks skipped,
    # O(L·W)), ring (hop count shrinks to ceil(window/L_loc)+1), ulysses
    attention_window: int = 0
    mlp_dim: int = 3072
    max_len: int = 1024
    dropout_rate: float = 0.1
    dtype: Any = jnp.float32
    attention: str = "dense"  # dense | ring | ulysses | flash
    attention_block: int = 128
    # Llama/Mistral-shape knobs (GPTConfig.llama() sets all four):
    #   norm       "layernorm" (GPT-2) | "rmsnorm" (scale-only, no mean
    #              subtraction — cheaper on TPU: one reduction, no bias add)
    #   activation "gelu" (single up-projection) | "swiglu"
    #              (silu(gate)·up — two up-projections; mlp_dim is the
    #              intermediate width in both cases)
    #   use_bias   False drops bias from every projection and LayerNorm
    #   tie_embeddings  False reads logits from a separate lm_head matmul
    #              instead of token_embed.attend (Llama unties; GPT-2 ties)
    norm: str = "layernorm"
    activation: str = "gelu"
    use_bias: bool = True
    tie_embeddings: bool = True
    # norm epsilon (flax default 1e-6); HF checkpoints vary (Llama-2 uses
    # 1e-5) and the importer threads the checkpoint's value for parity
    norm_eps: float = 1e-6
    # rematerialize each block on backward (jax.checkpoint): activation
    # memory drops from O(layers x seq x hidden) to O(seq x hidden) at the
    # cost of one extra forward — the standard long-context HBM lever
    remat: bool = False
    # MoE decoder (Mixtral shape): 0 = dense MLP; >0 replaces every block's
    # MLP with a MoeMlp of this many experts over the `expert` mesh axis
    # (parallel/moe.py — same dispatch as the BERT encoder)
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0

    def __post_init__(self):
        if self.hidden_size % self.num_heads:
            raise ValueError(
                f"hidden_size {self.hidden_size} not divisible by "
                f"num_heads {self.num_heads}"
            )
        if self.num_kv_heads and (
                self.num_kv_heads < 0
                or self.num_heads % self.num_kv_heads):
            raise ValueError(
                f"num_kv_heads {self.num_kv_heads} must be a positive "
                f"divisor of num_heads {self.num_heads} (or 0 for MHA)"
            )
        if self.position_embedding not in ("learned", "rope"):
            raise ValueError(
                f"position_embedding {self.position_embedding!r} "
                "(learned|rope)")
        if self.position_embedding == "rope":
            if (self.hidden_size // self.num_heads) % 2:
                raise ValueError(
                    "rope needs an even head_dim "
                    f"(got {self.hidden_size // self.num_heads})")
        if self.attention_window:
            if self.attention_window < 1:
                raise ValueError(
                    f"attention_window {self.attention_window} must be "
                    ">= 1 (or 0 for full causal)")
            if self.attention not in ("dense", "flash", "ring", "ulysses"):
                raise ValueError(
                    "attention_window composes with dense/flash/ring/"
                    f"ulysses + decode (got attention={self.attention!r})")
        if self.kv_cache_capacity:
            if not self.attention_window:
                raise ValueError(
                    "kv_cache_capacity (rolling decode cache) requires "
                    "attention_window — without a window, arbitrarily old "
                    "keys stay visible and may never be evicted")
            if self.kv_cache_capacity < self.attention_window:
                raise ValueError(
                    f"kv_cache_capacity {self.kv_cache_capacity} < "
                    f"attention_window {self.attention_window}: a slot "
                    "would be evicted while still inside every query's "
                    "window")
            if self.kv_cache_capacity >= self.max_len:
                raise ValueError(
                    f"kv_cache_capacity {self.kv_cache_capacity} >= "
                    f"max_len {self.max_len}: rolling would only cost "
                    "masking math — leave it 0 for the plain full cache")
        if self.moe_experts and self.moe_top_k > self.moe_experts:
            raise ValueError(
                f"moe_top_k {self.moe_top_k} > moe_experts "
                f"{self.moe_experts}"
            )
        if self.norm not in ("layernorm", "rmsnorm"):
            raise ValueError(f"norm {self.norm!r} is not layernorm|rmsnorm")
        if self.activation not in ("gelu", "swiglu"):
            raise ValueError(
                f"activation {self.activation!r} is not gelu|swiglu")

    @staticmethod
    def small(**kw) -> "GPTConfig":
        return GPTConfig(**kw)  # GPT-2 small shape

    @staticmethod
    def tiny(**kw) -> "GPTConfig":
        d = dict(vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
                 mlp_dim=128, max_len=256)
        d.update(kw)
        return GPTConfig(**d)

    @staticmethod
    def llama(**kw) -> "GPTConfig":
        """Llama/Mistral-shaped decoder: RMSNorm, SwiGLU, rope, GQA-ready,
        bias-free, untied head. Defaults to a test-sized shape; pass real
        dims for production (Mistral-7B ≈ hidden 4096, layers 32, heads
        32, num_kv_heads 8, mlp_dim 14336, attention_window 4096)."""
        d = dict(vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
                 num_kv_heads=2, mlp_dim=176, max_len=256,
                 norm="rmsnorm", activation="swiglu", use_bias=False,
                 tie_embeddings=False, position_embedding="rope",
                 dropout_rate=0.0)
        d.update(kw)
        return GPTConfig(**d)


# shared with the context-parallel attention paths (parallel/rope.py);
# re-exported here as the family's public name
from kubeflow_tpu.parallel.rope import apply_rope  # noqa: E402


def causal_dense_attention(q, k, v, bias, dropout_rng=None, dropout_rate=0.0,
                           block=None, window: int = 0):
    """Reference causal softmax attention (numerics baseline for tests).
    window > 0 adds Mistral-style sliding-window masking: query i sees
    keys in (i - window, i]."""
    depth = q.shape[-1]
    s = jnp.einsum("blhd,bmhd->bhlm", q, k) / jnp.sqrt(depth).astype(q.dtype)
    if bias is not None:
        s = s + bias
    lq, lk = q.shape[1], k.shape[1]
    mask = jnp.tril(jnp.ones((lq, lk), bool))
    if window:
        rows = jnp.arange(lq)[:, None]
        cols = jnp.arange(lk)[None, :]
        mask = mask & (rows - cols < window)
    s = jnp.where(mask[None, None], s.astype(jnp.float32), -1e9)
    probs = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    if dropout_rng is not None and dropout_rate > 0.0:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = probs * keep / (1.0 - dropout_rate)
    return jnp.einsum("bhlm,bmhd->blhd", probs, v)


def _decoder_norm(c: "GPTConfig", name: str):
    """The block norm: LayerNorm (GPT-2) or scale-only RMSNorm (Llama)."""
    if c.norm == "rmsnorm":
        return nn.RMSNorm(dtype=c.dtype, name=name, epsilon=c.norm_eps)
    return nn.LayerNorm(dtype=c.dtype, name=name, use_bias=c.use_bias,
                        epsilon=c.norm_eps)


class CausalSelfAttention(nn.Module):
    cfg: GPTConfig

    @nn.compact
    def __call__(self, x, bias, train: bool, decode: bool = False):
        c = self.cfg
        head_dim = c.hidden_size // c.num_heads
        kv_heads = c.num_kv_heads or c.num_heads
        heads = lambda n, name: nn.DenseGeneral(  # noqa: E731
            (n, head_dim), dtype=c.dtype, name=name, use_bias=c.use_bias
        )
        q = heads(c.num_heads, "query")(x)
        k = heads(kv_heads, "key")(x)
        v = heads(kv_heads, "value")(x)
        if decode:
            y = self._cached_attention(q, k, v)
        else:
            rope_inside = (c.position_embedding == "rope"
                           and c.attention in ("ring", "ulysses"))
            if c.position_embedding == "rope" and not rope_inside:
                # dense/flash see the full local sequence: rotate here.
                # ring/ulysses shard the sequence — THEY rotate, by global
                # position, inside their shard regions
                pos = jnp.arange(q.shape[1])
                q = apply_rope(q, pos, c.rope_theta)
                k = apply_rope(k, pos, c.rope_theta)
            if kv_heads != c.num_heads:
                # training path: broadcast KV groups up to full heads (the
                # parameter + cache savings stand; the attention kernels
                # stay single-shape). Decode keeps the grouped einsum and
                # the small cache — that's where the bandwidth win lives.
                group = c.num_heads // kv_heads
                k = jnp.repeat(k, group, axis=2)
                v = jnp.repeat(v, group, axis=2)
            rng = (self.make_rng("dropout")
                   if train and c.dropout_rate > 0 else None)
            if c.attention == "dense":
                y = causal_dense_attention(
                    q, k, v, bias, dropout_rng=rng,
                    dropout_rate=c.dropout_rate if train else 0.0,
                    window=c.attention_window,
                )
            else:
                attn_fn = _resolve_attention(c.attention)
                kw = ({"rope_theta": c.rope_theta} if rope_inside else {})
                if c.attention_window:
                    kw["window"] = c.attention_window
                y = attn_fn(q, k, v, bias, dropout_rng=None, dropout_rate=0.0,
                            block=c.attention_block, causal=True, **kw)
        return nn.DenseGeneral(
            c.hidden_size, axis=(-2, -1), dtype=c.dtype, name="attn_out",
            use_bias=c.use_bias,
        )(y)

    def _cached_attention(self, q, k, v):
        """KV-cache attention — ONE static-shape code path for both prefill
        (L = prompt length) and decode (L = 1), the TPU-idiomatic
        autoregressive loop: the cache is a fixed (B, max_len, KVH, D)
        buffer, new K/V write at the running index via
        dynamic_update_slice, and every step attends over the full buffer
        under a position mask — no shape ever depends on how many tokens
        have been generated, so XLA compiles exactly two executables
        (prefill + decode step). Under GQA (KVH < H) the query heads fold
        into (KVH, group) and the einsums contract against the small cache
        directly — the repeated-KV tensor is never materialized."""
        c = self.cfg
        b, l, h, d = q.shape
        kvh = k.shape[2]
        # Rolling cache (kv_cache_capacity with a sliding window): the
        # buffer is a ring of C slots instead of max_len — decode
        # attention bandwidth and cache memory scale with C. Capacity
        # math: a block write of L positions evicts positions <= last - C,
        # and the earliest query in the block still needs back to
        # cur - window + 1, so C >= window + L - 1 keeps every visible
        # key (checked below at trace time).
        C = c.kv_cache_capacity or c.max_len
        rolling = C < c.max_len
        ck = self.variable(
            "cache", "cached_key",
            lambda: jnp.zeros((b, C, kvh, d), c.dtype))
        cv = self.variable(
            "cache", "cached_value",
            lambda: jnp.zeros((b, C, kvh, d), c.dtype))
        # PER-ROW index (B,): in-flight rows may sit at different depths
        # (continuous batching, serving/continuous.py); uniform decode
        # (generate/speculative) is the all-rows-equal special case
        idx = self.variable(
            "cache", "cache_index", lambda: jnp.zeros((b,), jnp.int32))
        cur = idx.value                                  # (B,)
        q_pos = cur[:, None] + jnp.arange(l)[None, :]    # (B, L)
        if c.position_embedding == "rope":
            # rotate by ABSOLUTE position before the cache write: cached
            # keys carry their rotation, so one decode step only rotates
            # the new (q, k) pair
            q = apply_rope(q, q_pos, c.rope_theta)
            k = apply_rope(k, q_pos, c.rope_theta)
        if rolling and l > C - c.attention_window + 1:
            raise ValueError(
                f"prompt/block of {l} positions exceeds the rolling "
                f"cache's budget (capacity {C} - window "
                f"{c.attention_window} + 1 = {C - c.attention_window + 1})"
                " — raise kv_cache_capacity")
        if l == 1:
            # decode step: batched scatter — each row writes at ITS slot
            rows = jnp.arange(b)
            ck.value = ck.value.at[rows, cur % C].set(k[:, 0])
            cv.value = cv.value.at[rows, cur % C].set(v[:, 0])
        elif rolling:
            # prefill onto the ring: slots may wrap; l <= C (from the
            # budget check), so the l slots are distinct
            slots = (cur[0] + jnp.arange(l)) % C
            ck.value = ck.value.at[:, slots].set(k)
            cv.value = cv.value.at[:, slots].set(v)
        else:
            # block write (L > 1) at PER-ROW depths: a vmapped per-row
            # dynamic_update_slice — all-rows-equal prefill (generate,
            # engine admission) is the special case, and rows at DIFFERENT
            # depths (the continuous engine's speculative verify pass,
            # serving/continuous.py) write each at their own index
            def row_write(buf, kv, start):
                return jax.lax.dynamic_update_slice(buf, kv, (start, 0, 0))

            ck.value = jax.vmap(row_write)(ck.value, k, cur)
            cv.value = jax.vmap(row_write)(cv.value, v, cur)
        idx.value = cur + l
        qg = q.reshape(b, l, kvh, h // kvh, d)
        s = jnp.einsum("blkgd,bmkd->bkglm", qg, ck.value).astype(jnp.float32)
        s = s / jnp.sqrt(jnp.float32(d))
        if rolling:
            # slot j holds the NEWEST position p ≡ j (mod C) this row has
            # written: p_j = last - ((last - j) mod C); unwritten slots
            # reconstruct to p_j < 0. Visible = written AND causal AND
            # inside the window. (Incompatible with speculative rewind:
            # after a rewind, slot identity is ambiguous — speculative
            # rejects rolling configs.)
            j = jnp.arange(C)
            last = (cur + l - 1)[:, None]                # (B, 1)
            p_j = last - ((last - j[None, :]) % C)       # (B, C)
            visible = (
                (p_j[:, None, :] >= 0)
                & (p_j[:, None, :] <= q_pos[:, :, None])
                & (q_pos[:, :, None] - p_j[:, None, :] < c.attention_window)
            )
        else:
            k_pos = jnp.arange(C)                        # (max_len,)
            # causal + not-yet-written mask in one comparison: a key
            # position is visible iff it <= this query's position
            # (unwritten slots are all > that row's cur + l - 1 by
            # construction). A sliding window additionally hides keys
            # older than window-1 positions.
            visible = k_pos[None, None, :] <= q_pos[:, :, None]
            if c.attention_window:
                visible = visible & (
                    q_pos[:, :, None] - k_pos[None, None, :]
                    < c.attention_window)
        s = jnp.where(visible[:, None, None], s, -1e9)
        p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
        y = jnp.einsum("bkglm,bmkd->blkgd", p, cv.value)
        return y.reshape(b, l, h, d)


class GPTBlock(nn.Module):
    """Pre-LN decoder block (GPT-2 residual structure)."""

    cfg: GPTConfig

    @nn.compact
    def __call__(self, x, bias, train: bool, decode: bool = False):
        c = self.cfg
        y = CausalSelfAttention(c, name="attention")(
            _decoder_norm(c, "ln_attn")(x), bias, train,
            decode=decode,
        )
        y = nn.Dropout(c.dropout_rate, deterministic=not train)(y)
        x = constrain(x + y, ACT_SPEC)
        h = _decoder_norm(c, "ln_mlp")(x)
        if c.moe_experts:
            # short decode blocks route DROPLESS (no capacity, row-
            # independent) so KV-cache decode — solo, continuous-batched,
            # or speculative verify — never couples rows through the drop
            # pattern; long blocks (prompt prefill) keep routed dispatch
            # (dense-all-experts at L=1k would multiply prefill MLP FLOPs
            # by E/k). MOE_DROPLESS_MAX_LEN is module-level (defined
            # below; resolved at call time).
            h = MoeMlp(
                hidden_size=c.hidden_size, mlp_dim=c.mlp_dim,
                num_experts=c.moe_experts, top_k=c.moe_top_k,
                capacity_factor=c.moe_capacity_factor, dtype=c.dtype,
                activation=c.activation, use_bias=c.use_bias,
                name="moe",
            )(h, dropless=decode and x.shape[1] <= MOE_DROPLESS_MAX_LEN)
        elif c.activation == "swiglu":
            # Llama MLP: silu(gate)·up, both width mlp_dim, then down
            gate = nn.Dense(c.mlp_dim, dtype=c.dtype, use_bias=c.use_bias,
                            name="mlp_gate")(h)
            up = nn.Dense(c.mlp_dim, dtype=c.dtype, use_bias=c.use_bias,
                          name="mlp_up")(h)
            h = nn.Dense(c.hidden_size, dtype=c.dtype, use_bias=c.use_bias,
                         name="mlp_down")(nn.silu(gate) * up)
        else:
            h = nn.gelu(nn.Dense(c.mlp_dim, dtype=c.dtype,
                                 use_bias=c.use_bias, name="mlp_up")(h))
            h = nn.Dense(c.hidden_size, dtype=c.dtype, use_bias=c.use_bias,
                         name="mlp_down")(h)
        h = nn.Dropout(c.dropout_rate, deterministic=not train)(h)
        return constrain(x + h, ACT_SPEC)


class GPTLM(nn.Module):
    """Causal language model: logits over the next token at every position.

    __call__(input_ids (B, L)) -> (B, L, vocab) f32 logits; pad positions
    carry a large negative additive bias so they are never attended to.
    """

    cfg: GPTConfig
    pad_token_id: int = 0

    @nn.compact
    def __call__(self, input_ids, train: bool = False, decode: bool = False):
        c = self.cfg
        token_embed = VocabEmbed(
            c.vocab_size, c.hidden_size, dtype=c.dtype, name="token_embed"
        )
        x = token_embed(input_ids)
        if decode:
            # autoregressive mode: positions continue from the PER-ROW
            # running offset (rows at different depths under continuous
            # batching); attention masking is positional via the KV cache
            # (generation prompts are unpadded — see generate())
            b = input_ids.shape[0]
            pidx = self.variable(
                "cache", "pos_index", lambda: jnp.zeros((b,), jnp.int32))
            pos = pidx.value[:, None] + jnp.arange(input_ids.shape[1])[None, :]
            pidx.value = pidx.value + input_ids.shape[1]
            bias = None
        else:
            pos = jnp.arange(input_ids.shape[1])[None, :]
            mask = input_ids != self.pad_token_id
            bias = jnp.where(mask[:, None, None, :], 0.0, -1e9).astype(c.dtype)
        if c.position_embedding == "learned":
            x = x + VocabEmbed(c.max_len, c.hidden_size, dtype=c.dtype,
                               name="position_embed")(pos)
        # rope: positions enter per-layer as Q/K rotations — no table
        x = nn.Dropout(c.dropout_rate, deterministic=not train)(x)
        x = constrain(x, ACT_SPEC)
        # remat never wraps the decode path: generation is forward-only and
        # its cache writes must not re-execute
        block_cls = (
            nn.remat(GPTBlock, static_argnums=(3, 4))
            if (c.remat and not decode) else GPTBlock
        )
        for i in range(c.num_layers):
            x = block_cls(c, name=f"layer_{i}")(x, bias, train, decode)
        x = _decoder_norm(c, "ln_final")(x)
        if c.tie_embeddings:
            logits = token_embed.attend(x)  # weight-tied head (GPT-2)
        else:
            logits = nn.Dense(c.vocab_size, dtype=c.dtype, use_bias=False,
                              name="lm_head")(x)  # untied (Llama)
        return logits.astype(jnp.float32)


GPTLM.PARTITION_RULES = PARTITION_RULES
# bf16-by-default (trainer.resolve_compute_dtype): transformer LM matmuls
# are MXU-bound — on accelerator backends the Trainer flips the module's
# compute dtype to this unless the user pins compute_dtype explicitly
GPTLM.PREFERRED_COMPUTE_DTYPE = jnp.bfloat16


# Decode blocks at or under this many tokens route MoE DROPLESS (dense
# all-experts — row-independent, exact for continuous batching and
# speculative verify); longer blocks (prompt prefill) keep the routed
# capacity dispatch whose FLOPs scale with top_k, not num_experts. The
# engine prefills batch-1, so routed prefill is trivially row-independent
# there, and solo generate() takes the identical branch per shape — the
# engine-equals-solo exactness contract holds on both sides of the
# threshold.
MOE_DROPLESS_MAX_LEN = 16


def set_cache_indices(cache: dict, values=None, active=None) -> dict:
    """Rewrite every layer's per-row cache_index (and the LM's pos_index).

    The ONE owner of the cache-index contract (speculative rewind, the
    continuous engine's row parking and spec-round rewind all route here —
    three hand-rolled copies diverged before). values: scalar or (B,)
    replacement; None keeps the existing value. active: (B,) bool mask —
    rows where it is False park at 0 (so free rows' garbage decode can
    never creep an index past max_len)."""
    def fix(path, leaf):
        name = getattr(path[-1], "key", path[-1]) if path else ""
        if name in ("cache_index", "pos_index"):
            vals = (leaf if values is None else jnp.broadcast_to(
                jnp.asarray(values), leaf.shape).astype(leaf.dtype))
            if active is not None:
                vals = jnp.where(active, vals, 0)
            return vals
        return leaf

    return jax.tree_util.tree_map_with_path(fix, cache)


def gather_kv_rows(cache: dict, starts, window: int) -> dict:
    """Gather every cached_key/cached_value leaf's per-row slice
    ``[starts[b] : starts[b] + window]`` -> {'/'-joined leaf path:
    (B, window, kv_heads, head_dim)}.

    The read-side twin of the per-row block write: rows sit at different
    depths (continuous batching), so the gather is a vmapped per-row
    dynamic_slice at each row's own start — ONE dispatch per tick
    regardless of row count. The serving engine uses it to extract the
    decode step's freshly-written K/V for the paged pool's per-row block
    chains (serving/fleet/pagedkv.py); `window` is static (T decode
    steps, or gamma+1 for a speculative round), so jitting the caller
    yields one executable per window length."""
    starts = jnp.asarray(starts, jnp.int32)
    out: dict = {}

    def walk(tree, prefix=""):
        if isinstance(tree, dict):
            for k in sorted(tree):
                walk(tree[k], f"{prefix}/{k}")
            return
        name = prefix.rsplit("/", 1)[-1]
        if name in ("cached_key", "cached_value"):
            def row(buf, s, _w=window):
                return jax.lax.dynamic_slice(
                    buf, (s,) + (0,) * (buf.ndim - 1),
                    (_w,) + buf.shape[1:])

            out[prefix] = jax.vmap(row)(tree, starts)

    walk(cache)
    return out


def eos_id_array(eos_token_id):
    """Normalize an eos spec — int, or a sequence of stop ids (Llama-3
    instruct checkpoints stop on any of several) — to a 1-D int32 array,
    or None. The FIRST id is the canonical clamp token every decode path
    emits after a row finishes."""
    if eos_token_id is None:
        return None
    ids = jnp.atleast_1d(jnp.asarray(eos_token_id, jnp.int32))
    if ids.size == 0:
        return None
    return ids


def generate(
    model: GPTLM,
    variables: dict,
    prompt_ids: jax.Array,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int = 0,
    rng: jax.Array | None = None,
    eos_token_id=None,
) -> jax.Array:
    """Autoregressive generation with the KV cache — fully jittable.

    prompt_ids: (B, prompt_len) int32, UNPADDED (all prompts same length;
    generation-time position masking is by cache index, not pad id).
    Returns (B, max_new_tokens) int32. temperature == 0 -> greedy;
    otherwise categorical over logits/temperature, restricted to the top_k
    logits when top_k > 0. Static shapes throughout: ONE prefill executable
    + ONE decode-step executable inside a lax.scan, the TPU decode shape.
    The LM's max_len bounds prompt_len + max_new_tokens.

    eos_token_id: per-row early stop under static shapes — an int or a
    sequence of stop ids (any of which finishes the row; Llama-3-style).
    Once a row emits a stop id, every later position in that row is the
    FIRST stop id (callers trim at the first occurrence). The decode
    loop still runs max_new_tokens steps (TPU-idiomatic: no
    data-dependent trip count), but finished rows feed the clamp token
    forward so their cache stays consistent with the clamped output.
    """
    b, prompt_len = prompt_ids.shape
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if prompt_len + max_new_tokens > model.cfg.max_len:
        raise ValueError(
            f"prompt {prompt_len} + {max_new_tokens} new tokens exceeds "
            f"max_len {model.cfg.max_len}"
        )
    if temperature == 0.0:
        rng = jax.random.PRNGKey(0)  # unused; keeps the scan carry uniform
    elif rng is None:
        raise ValueError("sampling (temperature > 0) needs an rng")

    def sample(logits, key):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits = logits / jnp.float32(temperature)
        if top_k > 0:
            kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
        return jax.random.categorical(key, logits).astype(jnp.int32)

    # prefill: one pass over the whole prompt builds the cache
    logits, cache = model.apply(
        variables, prompt_ids, decode=True, mutable=["cache"]
    )
    rng, key = jax.random.split(rng)
    tok = sample(logits[:, -1], key)
    stops = eos_id_array(eos_token_id)
    done0 = (jnp.full((b,), False) if stops is None
             else jnp.isin(tok, stops))

    def step(carry, _):
        cache, tok, rng, done = carry
        logits, cache = model.apply(
            {**variables, **cache}, tok[:, None], decode=True,
            mutable=["cache"],
        )
        rng, key = jax.random.split(rng)
        nxt = sample(logits[:, 0], key)
        if stops is not None:
            nxt = jnp.where(done, stops[0], nxt)
            done = done | jnp.isin(nxt, stops)
        return (cache, nxt, rng, done), tok

    (_, last, _, _), toks = jax.lax.scan(
        step, (cache, tok, rng, done0), None, length=max_new_tokens - 1
    )
    out = jnp.concatenate([toks, last[None]], axis=0)
    return out.T  # (B, max_new_tokens)


def beam_search(
    model: GPTLM,
    variables: dict,
    prompt_ids: jax.Array,
    max_new_tokens: int,
    num_beams: int = 4,
) -> tuple[jax.Array, jax.Array]:
    """Beam-search decoding with the KV cache — fully jittable, static
    shapes (beams ride the batch dim; each step reorders the cache rows by
    beam parent with a batched take).

    Returns (ids (B, max_new_tokens), scores (B,)) for the best beam per
    input, scores being exact sequence log-probs. All beams decode exactly
    max_new_tokens tokens (no EOS), so no length penalty is offered — with
    equal lengths it could never change the winner. Unpadded prompts, as
    in generate()."""
    b, prompt_len = prompt_ids.shape
    k = num_beams
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if prompt_len + max_new_tokens > model.cfg.max_len:
        raise ValueError(
            f"prompt {prompt_len} + {max_new_tokens} new tokens exceeds "
            f"max_len {model.cfg.max_len}"
        )

    # prefill ONCE per input, then expand the cache to (B*K) rows — the
    # K beams of an input are identical until the first top-k, so running
    # K prompt copies through the model would waste (K-1)/K of the prefill
    logits, cache = model.apply(
        variables, prompt_ids, decode=True, mutable=["cache"]
    )
    cache = jax.tree.map(
        lambda a: jnp.repeat(a, k, axis=0) if a.ndim and a.shape[0] == b
        else a,
        cache,
    )
    log_p = jnp.repeat(
        jax.nn.log_softmax(logits[:, -1].astype(jnp.float32)), k, axis=0
    )                                                          # (B*K, V)
    # all beams of an input start identical, so all but beam 0 get -inf
    # initial score (else top-k picks K copies of the same continuation)
    vocab = log_p.shape[-1]
    init_mask = jnp.where(jnp.arange(k) == 0, 0.0, -jnp.inf)   # (K,)
    scores = jnp.tile(init_mask, (b,))                         # (B*K,)

    def step(carry, _):
        cache, scores, tok_prev = carry
        logits, cache = model.apply(
            {**variables, **cache}, tok_prev[:, None], decode=True,
            mutable=["cache"],
        )
        log_p = jax.nn.log_softmax(logits[:, 0].astype(jnp.float32))
        total = scores[:, None] + log_p                        # (B*K, V)
        joint = total.reshape(b, k * vocab)
        top_scores, top_idx = jax.lax.top_k(joint, k)          # (B, K)
        parent = top_idx // vocab                              # beam index
        tok = (top_idx % vocab).astype(jnp.int32)              # (B, K)
        # flat row index of each new beam's parent
        rows = (jnp.arange(b)[:, None] * k + parent).reshape(b * k)
        cache = jax.tree.map(
            lambda a: jnp.take(a, rows, axis=0) if a.ndim and
            a.shape[0] == b * k else a,
            cache,
        )
        return (cache, top_scores.reshape(b * k),
                tok.reshape(b * k)), (tok.reshape(b * k), rows)

    # first real step consumes the prefill logits: fold it into the scan by
    # seeding tok_prev from the prefill distribution
    total0 = scores[:, None] + log_p
    joint0 = total0.reshape(b, k * vocab)
    s0, i0 = jax.lax.top_k(joint0, k)
    parent0 = (jnp.arange(b)[:, None] * k + i0 // vocab).reshape(b * k)
    tok0 = (i0 % vocab).astype(jnp.int32).reshape(b * k)
    cache = jax.tree.map(
        lambda a: jnp.take(a, parent0, axis=0) if a.ndim and
        a.shape[0] == b * k else a,
        cache,
    )
    (cache, scores, last), (toks, parents) = jax.lax.scan(
        step, (cache, s0.reshape(b * k), tok0), None,
        length=max_new_tokens - 1,
    )
    # backtrack: walk parent pointers from the best final beam
    all_toks = jnp.concatenate([tok0[None], toks], axis=0)     # (T, B*K)
    all_parents = jnp.concatenate(
        [jnp.arange(b * k)[None], parents], axis=0
    )                                                          # (T, B*K)
    best = jnp.argmax(scores.reshape(b, k), axis=-1)           # (B,)
    row = jnp.arange(b) * k + best

    def back(row, t_arr):
        seq = jnp.zeros((all_toks.shape[0],), jnp.int32)

        def body(i, carry):
            row, seq = carry
            t = all_toks.shape[0] - 1 - i
            seq = seq.at[t].set(t_arr[t, row])
            row = all_parents[t, row]
            return (row, seq)

        _, seq = jax.lax.fori_loop(0, all_toks.shape[0], body, (row, seq))
        return seq

    out = jax.vmap(lambda r: back(r, all_toks))(row)           # (B, T)
    return out, jnp.take(scores, row)


def causal_lm_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Next-token cross entropy; labels == input_ids (the shift happens
    here), pad labels (0) are masked out of the mean."""
    import optax

    shift_logits = logits[:, :-1]
    shift_labels = labels[:, 1:]
    per_tok = optax.softmax_cross_entropy_with_integer_labels(
        shift_logits, shift_labels
    )
    w = (shift_labels != 0).astype(jnp.float32)
    return (per_tok * w).sum() / jnp.maximum(w.sum(), 1.0)


def causal_lm_eval_metrics(logits: jax.Array, labels: jax.Array):
    """Per-example (next-token loss, next-token accuracy) — the eval twin of
    causal_lm_loss, shifted the same way so eval measures what training
    optimizes (Trainer eval_metrics_fn contract)."""
    import optax

    shift_logits = logits[:, :-1]
    shift_labels = labels[:, 1:]
    per_tok = optax.softmax_cross_entropy_with_integer_labels(
        shift_logits, shift_labels
    )
    w = (shift_labels != 0).astype(jnp.float32)
    denom = jnp.maximum(w.sum(-1), 1.0)
    per_ex = (per_tok * w).sum(-1) / denom
    acc = (
        ((jnp.argmax(shift_logits, -1) == shift_labels) * w).sum(-1) / denom
    )
    return per_ex, acc
