"""BERT encoder family — north-star config #3 (BASELINE.md: BERT-base steps/sec).

Reference parity: the reference fine-tunes BERT via Horovod user images under
MPIJob (SURVEY.md §3.2); here the encoder is in-tree and every parallelism
axis is first-class:

  - TP (Megatron-style) is *declarative*: PARTITION_RULES map param paths to
    PartitionSpecs over the mesh's (fsdp, model) axes; XLA's SPMD partitioner
    inserts the all-gathers/reduce-scatters — no hand-written collectives.
  - Activation shardings are pinned at the residual stream via
    with_sharding_constraint (P(("data","fsdp"), None, None)) so the
    partitioner never materializes a replicated (B, L, H) tensor.
  - Attention is pluggable (`attention=`): "dense" (this file),
    "ring" / "ulysses" (kubeflow_tpu.parallel.ring_attention) for context
    parallelism over the `context` mesh axis.
  - bf16 compute / f32 params; static seq_len; padding via pad_token_id==0
    derived inside the model, so the data pipeline ships one int32 array.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from kubeflow_tpu.utils import compat
from kubeflow_tpu.parallel.mesh import (
    AXIS_CONTEXT,
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_MODEL,
)
from kubeflow_tpu.parallel.sharding import BATCH_AXES
from kubeflow_tpu.parallel.moe import MOE_PARTITION_RULES, MoeMlp

# Param-path regex -> PartitionSpec. fsdp shards the "long" dim that the
# model axis leaves free; tiny params (LayerNorm, biases) replicate via the
# default heuristic in parallel/sharding.py.
PARTITION_RULES: list[tuple[str, P]] = [
    (r"(query|key|value)/kernel$", P(AXIS_FSDP, AXIS_MODEL)),
    (r"attn_out/kernel$", P(AXIS_MODEL, AXIS_FSDP)),
    (r"mlp_up/kernel$", P(AXIS_FSDP, AXIS_MODEL)),
    (r"mlp_down/kernel$", P(AXIS_MODEL, AXIS_FSDP)),
    (r"token_embed/embedding$", P(AXIS_MODEL, AXIS_FSDP)),
    (r"(position_embed|type_embed)/embedding$", P(None, AXIS_FSDP)),
    (r"pooler/kernel$", P(AXIS_FSDP, AXIS_MODEL)),
    (r"mlm_dense/kernel$", P(AXIS_FSDP, AXIS_MODEL)),
    *MOE_PARTITION_RULES,
]

# residual-stream activation layout: batch over data-like axes (expert
# parallelism subdivides data parallelism — parallel/moe.py), hidden
# replicated
ACT_SPEC = P((AXIS_DATA, AXIS_FSDP, AXIS_EXPERT), AXIS_CONTEXT, None)


def constrain(x: jax.Array, spec: P) -> jax.Array:
    """Sharding pin that is a no-op when no ambient mesh is set."""
    if compat.get_abstract_mesh().empty:
        return x
    return jax.lax.with_sharding_constraint(x, spec)


class VocabEmbed(nn.Embed):
    """nn.Embed that lowers the lookup to a one-hot matmul when the ambient
    mesh shards the vocab dim over `model` (TP).

    A plain gather over a vocab-sharded table cannot be partitioned by XLA's
    SPMD pass — it falls back to rematerializing the full table on every
    device (the round-1 "Involuntary full rematerialization" cliff). The
    one-hot contraction is the Megatron/maxtext recipe: the table stays put,
    XLA inserts one psum over `model`, and the matmul rides the MXU.
    """

    def __call__(self, inputs: jax.Array) -> jax.Array:
        mesh = compat.get_abstract_mesh()
        if mesh.empty:
            return super().__call__(inputs)
        (table,) = compat.promote_dtype(self, self.embedding,
                                        dtype=self.dtype, inexact=False)
        if mesh.shape.get(AXIS_MODEL, 1) > 1:
            onehot = jax.nn.one_hot(inputs, self.num_embeddings, dtype=table.dtype)
            return jnp.dot(onehot, table)
        # No vocab-dim sharding: all-gather any feature shards up-front (the
        # FSDP gather-at-use contract) so the take sees a replicated operand
        # and the partitioner never warns about resharding gather output.
        table = constrain(table, P(None, None))
        return jnp.take(table, inputs, axis=0)


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    max_len: int = 512
    dropout_rate: float = 0.1
    pad_token_id: int = 0
    dtype: Any = jnp.float32
    attention: str = "dense"  # dense | ring | ulysses
    attention_block: int = 128  # ring attention KV block size
    # rematerialize each encoder block on backward (jax.checkpoint) — the
    # long-context HBM lever (activation memory O(seq·hidden), one extra
    # forward)
    remat: bool = False
    # MoE: 0 = dense MLP; >0 replaces every MLP with a MoeMlp of this many
    # experts, dispatched over the `expert` mesh axis (parallel/moe.py)
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0

    def __post_init__(self):
        # fail fast on malformed architectures: NAS sweeps feed these fields
        # from search spaces, and a non-dividing head count would silently
        # train a truncated model (head_dim floor-divides)
        if self.hidden_size % self.num_heads:
            raise ValueError(
                f"hidden_size {self.hidden_size} not divisible by "
                f"num_heads {self.num_heads}"
            )

    @staticmethod
    def base(**kw) -> "BertConfig":
        return BertConfig(**kw)

    @staticmethod
    def tiny(**kw) -> "BertConfig":
        d = dict(vocab_size=1024, hidden_size=64, num_layers=2, num_heads=4,
                 mlp_dim=128, max_len=128)
        d.update(kw)
        return BertConfig(**d)


# (B, H, L_q, L_k) attention scores: batch over the canonical data-like axes
# (sharding.BATCH_AXES — one definition, so specs cannot drift when a
# data-like axis is added), heads over `model`, query positions over
# `context` (matching ACT_SPEC's L sharding; the key dim is reduced by the
# softmax and stays gathered, the best dense attention can do under SP).
# Pinned explicitly because inside remat/scan regions (pipeline stages) the
# partitioner otherwise picks a different sharding for the forward residual
# than the backward wants, triggering an involuntary full-remat reshard of
# the scores gradient at the shard_map boundary.
SCORES_SPEC = P(BATCH_AXES, AXIS_MODEL, AXIS_CONTEXT, None)


def dense_attention(q, k, v, bias, dropout_rng=None, dropout_rate=0.0, block=None):
    """Reference softmax attention: (B, L, H, D) tensors, additive bias."""
    depth = q.shape[-1]
    scores = jnp.einsum("blhd,bmhd->bhlm", q, k) / jnp.sqrt(depth).astype(q.dtype)
    if bias is not None:
        scores = scores + bias
    scores = constrain(scores, SCORES_SPEC)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_rng is not None and dropout_rate > 0.0:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = probs * keep / (1.0 - dropout_rate)
    return jnp.einsum("bhlm,bmhd->blhd", probs, v)


def _resolve_attention(kind: str) -> Callable:
    if kind == "dense":
        return dense_attention
    if kind in ("ring", "ulysses", "flash"):
        from kubeflow_tpu.parallel import ring_attention as ra

        return {
            "ring": ra.ring_attention,
            "ulysses": ra.ulysses_attention,
            "flash": ra.flash_attention,
        }[kind]
    raise ValueError(f"unknown attention kind {kind!r}")


class SelfAttention(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, mask, train: bool):
        c = self.cfg
        head_dim = c.hidden_size // c.num_heads
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (c.num_heads, head_dim), dtype=c.dtype, name=name
        )
        q, k, v = dense("query")(x), dense("key")(x), dense("value")(x)
        # additive bias from padding mask: (B, 1, 1, L)
        bias = jnp.where(mask[:, None, None, :], 0.0, -1e9).astype(c.dtype)
        rng = self.make_rng("dropout") if train and c.dropout_rate > 0 else None
        attn_fn = _resolve_attention(c.attention)
        y = attn_fn(q, k, v, bias, dropout_rng=rng,
                    dropout_rate=c.dropout_rate if train else 0.0,
                    block=c.attention_block)
        y = nn.DenseGeneral(
            c.hidden_size, axis=(-2, -1), dtype=c.dtype, name="attn_out"
        )(y)
        return y


class BertLayer(nn.Module):
    """Post-LN transformer block (original BERT residual structure)."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, x, mask, train: bool):
        c = self.cfg
        y = SelfAttention(c, name="attention")(x, mask, train)
        y = nn.Dropout(c.dropout_rate, deterministic=not train)(y)
        x = nn.LayerNorm(dtype=c.dtype, name="ln_attn")(x + y)
        x = constrain(x, ACT_SPEC)
        if c.moe_experts:
            y = MoeMlp(
                hidden_size=c.hidden_size, mlp_dim=c.mlp_dim,
                num_experts=c.moe_experts, top_k=c.moe_top_k,
                capacity_factor=c.moe_capacity_factor, dtype=c.dtype,
                name="moe",
            )(x)
        else:
            y = nn.Dense(c.mlp_dim, dtype=c.dtype, name="mlp_up")(x)
            y = nn.gelu(y)
            y = nn.Dense(c.hidden_size, dtype=c.dtype, name="mlp_down")(y)
        y = nn.Dropout(c.dropout_rate, deterministic=not train)(y)
        x = nn.LayerNorm(dtype=c.dtype, name="ln_mlp")(x + y)
        return constrain(x, ACT_SPEC)


class BertEmbeddings(nn.Module):
    """Token + position + type embeddings with the post-embedding LN.

    token_embed can be a shared nn.Embed (weight tying with an MLM head).
    Split out of BertEncoder so the pipeline-parallel model (bert_pp.py) can
    run it outside the stage ring (boundary stages replicate, the stack
    pipelines — the maxtext recipe).
    """

    cfg: BertConfig
    token_embed: Any = None

    @nn.compact
    def __call__(self, input_ids, train: bool = False, token_type_ids=None):
        c = self.cfg
        embed_mod = self.token_embed or VocabEmbed(
            c.vocab_size, c.hidden_size, dtype=c.dtype, name="token_embed"
        )
        embed = embed_mod(input_ids)
        pos = jnp.arange(input_ids.shape[1])[None, :]
        embed = embed + VocabEmbed(c.max_len, c.hidden_size, dtype=c.dtype,
                                   name="position_embed")(pos)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        embed = embed + VocabEmbed(2, c.hidden_size, dtype=c.dtype,
                                   name="type_embed")(token_type_ids)
        x = nn.LayerNorm(dtype=c.dtype, name="ln_embed")(embed)
        x = nn.Dropout(c.dropout_rate, deterministic=not train)(x)
        return constrain(x, ACT_SPEC)


class BertEncoder(nn.Module):
    """Embeddings + transformer stack; returns (B, L, H) hidden states."""

    cfg: BertConfig
    token_embed: Any = None

    @nn.compact
    def __call__(self, input_ids, train: bool = False, token_type_ids=None):
        c = self.cfg
        mask = input_ids != c.pad_token_id
        x = BertEmbeddings(c, token_embed=self.token_embed, name="embeddings")(
            input_ids, train, token_type_ids
        )
        layer_cls = (
            nn.remat(BertLayer, static_argnums=(3,)) if c.remat else BertLayer
        )
        for i in range(c.num_layers):
            x = layer_cls(c, name=f"layer_{i}")(x, mask, train)
        return x


class BertForSequenceClassification(nn.Module):
    """[CLS]-pooled classifier — the north-star fine-tune head."""

    cfg: BertConfig
    num_classes: int = 2

    @nn.compact
    def __call__(self, input_ids, train: bool = False):
        x = BertEncoder(self.cfg, name="encoder")(input_ids, train)
        cls = x[:, 0]
        pooled = jnp.tanh(nn.Dense(self.cfg.hidden_size, dtype=self.cfg.dtype,
                                   name="pooler")(cls))
        pooled = nn.Dropout(self.cfg.dropout_rate, deterministic=not train)(pooled)
        logits = nn.Dense(self.num_classes, dtype=self.cfg.dtype,
                          name="classifier")(pooled)
        return logits.astype(jnp.float32)


class BertForMaskedLM(nn.Module):
    """MLM head with tied input embeddings (pretraining parity)."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, train: bool = False):
        c = self.cfg
        token_embed = VocabEmbed(
            c.vocab_size, c.hidden_size, dtype=c.dtype, name="token_embed"
        )
        x = BertEncoder(c, token_embed=token_embed, name="encoder")(input_ids, train)
        x = nn.gelu(nn.Dense(c.hidden_size, dtype=c.dtype, name="mlm_dense")(x))
        x = nn.LayerNorm(dtype=c.dtype, name="mlm_ln")(x)
        logits = token_embed.attend(x)  # tied output projection
        logits = logits + self.param(
            "mlm_bias", nn.initializers.zeros, (c.vocab_size,)
        ).astype(c.dtype)
        return logits.astype(jnp.float32)


# the Trainer picks TP rules up from the model class (trainer.py); the
# encoder family is MXU-heavy, so AUTO compute dtype resolves to bf16 on
# accelerator backends (trainer.resolve_compute_dtype)
for _cls in (BertEncoder, BertForSequenceClassification, BertForMaskedLM):
    _cls.PARTITION_RULES = PARTITION_RULES
    _cls.PREFERRED_COMPUTE_DTYPE = jnp.bfloat16


# --------------------------------------------------------------- MLM training

from kubeflow_tpu.train.data import IGNORE_LABEL  # noqa: E402 — shared sentinel


def masked_lm_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """BERT pretraining objective: cross entropy at masked positions only.

    labels: (B, L) int — original token ids at masked positions,
    IGNORE_LABEL elsewhere (train/data.py mask_tokens_for_mlm builds them).
    """
    import optax

    w = (labels != IGNORE_LABEL).astype(jnp.float32)
    safe = jnp.where(labels == IGNORE_LABEL, 0, labels)
    per_tok = optax.softmax_cross_entropy_with_integer_labels(logits, safe)
    return (per_tok * w).sum() / jnp.maximum(w.sum(), 1.0)


def masked_lm_eval_metrics(logits: jax.Array, labels: jax.Array):
    """Per-example (masked loss, masked accuracy) — Trainer eval contract."""
    import optax

    w = (labels != IGNORE_LABEL).astype(jnp.float32)
    safe = jnp.where(labels == IGNORE_LABEL, 0, labels)
    per_tok = optax.softmax_cross_entropy_with_integer_labels(logits, safe)
    denom = jnp.maximum(w.sum(-1), 1.0)
    per_ex = (per_tok * w).sum(-1) / denom
    acc = ((jnp.argmax(logits, -1) == safe) * w).sum(-1) / denom
    return per_ex, acc
