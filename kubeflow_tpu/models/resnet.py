"""ResNet family — north-star config #2 (BASELINE.md: ResNet-50 images/sec/chip).

TPU-first choices:
  - channels-last NHWC (XLA's native conv layout on TPU; MXU tiles want the
    channel dim innermost),
  - bf16 compute / f32 params via the `dtype` attr (trainer casts inputs),
  - BatchNorm under jit SPMD: the batch axis is sharded over the mesh's
    data axes, so the mean/var reductions XLA inserts are *global* psums —
    sync-BN for free, no NCCL sync-BN plumbing like the reference's user
    images (kubeflow/examples resnet — SURVEY.md L6) need,
  - static shapes everywhere; stride/padding arithmetic resolved at trace.

Parity target: the reference platform launches torchvision/TF ResNet-50 user
images under TFJob/PyTorchJob (SURVEY.md §2.2 data-parallel row); here the
model is in-tree so every parallelism axis can be tested end-to-end.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with projection shortcut on shape change."""

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.act(self.norm()(y))
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = self.act(self.norm()(y))
        y = self.conv(self.filters * 4, (1, 1))(y)
        # zero-init the last BN scale: residual branch starts as identity,
        # the standard trick for stable large-batch training
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), strides=(self.strides, self.strides)
            )(residual)
            residual = self.norm()(residual)
        return self.act(residual + y)


class BasicBlock(nn.Module):
    """3x3 -> 3x3 block (ResNet-18/34)."""

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(x)
        y = self.act(self.norm()(y))
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), strides=(self.strides, self.strides)
            )(residual)
            residual = self.norm()(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """Configurable ResNet over NHWC images.

    stage_sizes/block pick the variant; `small_inputs` swaps the 7x7/stride-2
    stem + maxpool for a 3x3 stem (CIFAR/MNIST-scale images).
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.float32
    small_inputs: bool = False
    # Conv lowering: "xla" = lax conv HLO, "im2col" = slices+matmul
    # (models/conv.py — param-compatible), "auto" = im2col only when the
    # backend registers as the legacy "axon" name. The r2 "convs run 200x
    # below matmul" reading was per-dispatch-floor pollution: r3's fused
    # device-born steps ran FASTER through lax.conv (docs/perf.md), and
    # the live chip registers backend "tpu", so auto == xla there.
    # probe_resnet.py carries the per-shape A/B that settles it for good.
    conv_impl: str = "auto"

    def _conv_cls(self) -> ModuleDef:
        impl = self.conv_impl
        if impl == "auto":
            import jax

            impl = "im2col" if jax.default_backend() == "axon" else "xla"
        if impl == "im2col":
            from kubeflow_tpu.models.conv import ConvCompat

            return ConvCompat  # Im2ColConv under the flax name "Conv"
        if impl == "xla":
            return nn.Conv
        raise ValueError(f"unknown conv_impl {self.conv_impl!r}")

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 2:  # flat grayscale vectors (mnist-style fixtures)
            side = int(x.shape[-1] ** 0.5)
            x = x.reshape((x.shape[0], side, side, 1))
        conv = partial(self._conv_cls(), use_bias=False, dtype=self.dtype)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )
        x = x.astype(self.dtype)
        if self.small_inputs:
            x = conv(self.width, (3, 3), name="conv_init")(x)
        else:
            x = conv(self.width, (7, 7), strides=(2, 2), name="conv_init")(x)
        x = nn.relu(norm(name="bn_init")(x))
        if not self.small_inputs:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(
                    filters=self.width * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                )(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=(3, 4, 23, 3), block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=(3, 8, 36, 3), block_cls=BottleneckBlock)
