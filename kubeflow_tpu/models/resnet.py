"""ResNet family — north-star config #2 (BASELINE.md: ResNet-50 images/sec/chip).

TPU-first choices:
  - channels-last NHWC (XLA's native conv layout on TPU; MXU tiles want the
    channel dim innermost),
  - bf16 compute / f32 params via the `dtype` attr (trainer casts inputs),
  - BatchNorm under jit SPMD: the batch axis is sharded over the mesh's
    data axes, so the mean/var reductions XLA inserts are *global* psums —
    sync-BN for free, no NCCL sync-BN plumbing like the reference's user
    images (kubeflow/examples resnet — SURVEY.md L6) need,
  - static shapes everywhere; stride/padding arithmetic resolved at trace.

Parity target: the reference platform launches torchvision/TF ResNet-50 user
images under TFJob/PyTorchJob (SURVEY.md §2.2 data-parallel row); here the
model is in-tree so every parallelism axis can be tested end-to-end.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


def s2d_pack(x):
    """Space-to-depth 2x2 pack: (B, H, W, C) -> (B, H/2, W/2, 4C).

    Channel order: c' = di*2C + dj*C + c for the (di, dj) sub-pixel — the
    layout `stem_weights_7x7_to_s2d` assumes. The packed stem trades the
    lane-starved K=49*3=147 stem GEMM for a lane-denser K=16*12=192 one
    with identical FLOPs (probe_resnet.py section B measures the win)."""
    b, h, w, c = x.shape
    return x.reshape(b, h // 2, 2, w // 2, 2, c).transpose(
        0, 1, 3, 2, 4, 5).reshape(b, h // 2, w // 2, 4 * c)


def stem_weights_7x7_to_s2d(w7):
    """EXACT weight transform: 7x7/s2 SAME stem kernel -> the equivalent
    4x4/s1 kernel over the s2d-packed input.

    On an even input, SAME for k=7/s=2 pads (2, 3); the 7x7 kernel
    embeds in an 8x8/s2 kernel with a trailing zero row/col
    (w8[:7, :7] = w7, taps at rows 2i-2 .. 2i+5 with the +5 tap zero).
    An 8x8/s2 conv equals a 4x4/s1 conv on the packed input with
    w4[u, v, di*2C+dj*C+c, o] = w8[2u+di, 2v+dj, c, o] and packed
    padding (1, 2), output exactly H/2 — so logits match the 7x7 model
    to dtype rounding (pinned by tests/test_models_resnet.py)."""
    kh, kw, cin, cout = w7.shape
    assert (kh, kw) == (7, 7), w7.shape
    w8 = jnp.zeros((8, 8, cin, cout), w7.dtype).at[:7, :7].set(w7)
    # split each 8-tap axis a = 2u + di into (u, di)
    w4 = w8.reshape(4, 2, 4, 2, cin, cout).transpose(0, 2, 1, 3, 4, 5)
    return w4.reshape(4, 4, 4 * cin, cout)


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with projection shortcut on shape change."""

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.act(self.norm()(y))
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = self.act(self.norm()(y))
        y = self.conv(self.filters * 4, (1, 1))(y)
        # zero-init the last BN scale: residual branch starts as identity,
        # the standard trick for stable large-batch training
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), strides=(self.strides, self.strides)
            )(residual)
            residual = self.norm()(residual)
        return self.act(residual + y)


class BasicBlock(nn.Module):
    """3x3 -> 3x3 block (ResNet-18/34)."""

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(x)
        y = self.act(self.norm()(y))
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), strides=(self.strides, self.strides)
            )(residual)
            residual = self.norm()(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """Configurable ResNet over NHWC images.

    stage_sizes/block pick the variant; `small_inputs` swaps the 7x7/stride-2
    stem + maxpool for a 3x3 stem (CIFAR/MNIST-scale images).
    """

    #: MXU-heavy: the Trainer's AUTO compute dtype resolves to bf16 on
    #: accelerator backends (trainer.resolve_compute_dtype clones the
    #: module with `dtype` flipped; params stay f32)
    PREFERRED_COMPUTE_DTYPE = jnp.bfloat16

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.float32
    small_inputs: bool = False
    # Conv lowering: "xla" = lax conv HLO, "im2col" = slices+matmul
    # (models/conv.py — param-compatible), "auto" = im2col only when the
    # backend registers as the legacy "axon" name. The r2 "convs run 200x
    # below matmul" reading was per-dispatch-floor pollution: r3's fused
    # device-born steps ran FASTER through lax.conv (docs/perf.md), and
    # the live chip registers backend "tpu", so auto == xla there.
    # probe_resnet.py carries the per-shape A/B that settles it per shape.
    # PER-STAGE override: a sequence of 5 impls (stem, stage1..stage4) —
    # e.g. ("im2col", "xla", "xla", "xla", "xla") — so a probe verdict
    # like "im2col wins only at the lane-starved shapes" is shippable as
    # a config flip, no model surgery.
    conv_impl: str | Sequence[str] = "auto"
    # Stem variant: "7x7" = canonical 7x7/s2 + maxpool; "s2d" = space-to-
    # depth 2x2 pack + 4x4/s1 conv (+ the same maxpool) — identical math
    # under `stem_weights_7x7_to_s2d` (exact, tested), lane-denser GEMM
    # (K 147 -> 192). Shipped as config so a probe_resnet verdict flips
    # the bench via KFT_RESNET_STEM with zero code change.
    stem: str = "7x7"

    def _impl_for(self, stage: int) -> str:
        """stage 0 = stem, 1..4 = residual stages."""
        impl = self.conv_impl
        if not isinstance(impl, str):
            impl = impl[stage]
        if impl == "auto":
            import jax

            impl = "im2col" if jax.default_backend() == "axon" else "xla"
        return impl

    def _conv_cls(self, stage: int = 0) -> ModuleDef:
        impl = self._impl_for(stage)
        if impl == "im2col":
            from kubeflow_tpu.models.conv import ConvCompat

            return ConvCompat  # Im2ColConv under the flax name "Conv"
        if impl == "xla":
            return nn.Conv
        raise ValueError(f"unknown conv_impl {self.conv_impl!r}")

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 2:  # flat grayscale vectors (mnist-style fixtures)
            side = int(x.shape[-1] ** 0.5)
            x = x.reshape((x.shape[0], side, side, 1))
        stem_conv = partial(self._conv_cls(0), use_bias=False,
                            dtype=self.dtype)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )
        x = x.astype(self.dtype)
        if self.small_inputs:
            x = stem_conv(self.width, (3, 3), name="conv_init")(x)
        elif self.stem == "s2d":
            x = s2d_pack(x)
            # SAME for k=4/s=1 pads (1,2) — exactly the 7x7/s2 SAME
            # receptive field (see stem_weights_7x7_to_s2d); default
            # padding keeps the stem compatible with ConvCompat/im2col,
            # which supports SAME only. Output is H/2 x W/2.
            x = stem_conv(self.width, (4, 4), name="conv_init")(x)
        elif self.stem == "7x7":
            x = stem_conv(self.width, (7, 7), strides=(2, 2),
                          name="conv_init")(x)
        else:
            raise ValueError(f"unknown stem {self.stem!r}")
        x = nn.relu(norm(name="bn_init")(x))
        if not self.small_inputs:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(self.stage_sizes):
            conv = partial(self._conv_cls(i + 1), use_bias=False,
                           dtype=self.dtype)
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(
                    filters=self.width * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                )(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=(3, 4, 23, 3), block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=(3, 8, 36, 3), block_cls=BottleneckBlock)
