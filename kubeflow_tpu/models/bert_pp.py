"""Pipeline-parallel BERT — the encoder stack over the `pipeline` mesh axis.

Reference parity: the reference pipelines models only inside user images
(DeepSpeed/Megatron under PyTorchJob/MPIJob — SURVEY.md §2.2 PP row, §7
hard-part 3); here PP is in-tree and composes with the Trainer.

Layout (the maxtext recipe): embeddings and the classifier head are
replicated over the `pipeline` axis and run outside the ring — they are
cheap and their activation shapes differ from the stack's. The homogeneous
transformer stack is split into `num_stages` chunks whose params are stacked
on a leading stage axis sharded over `pipeline`; microbatches circulate via
ppermute (parallel/pipeline.py). TP/FSDP/context shardings inside each stage
stay fully automatic — the same PARTITION_RULES as dense BERT apply, lifted
onto the stacked stage dim.

This class is a flax-like duck type (init/apply/__call__) rather than an
nn.Module: the ring runs under a partial-manual shard_map, which is cleaner
composed functionally than through lifted flax transforms.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from kubeflow_tpu.models.bert import (
    ACT_SPEC,
    PARTITION_RULES,
    BertConfig,
    BertEmbeddings,
    BertLayer,
    constrain,
)
from kubeflow_tpu.parallel.pipeline import gpipe, lift_pipeline_rules

# dense rules lifted onto stacked stage params (leading `pipeline` dim),
# plus a catch-all so every stage param is at least stage-sharded
PP_PARTITION_RULES: list[tuple[str, P]] = lift_pipeline_rules(PARTITION_RULES)


class _Stage(nn.Module):
    """One pipeline stage: a chunk of BertLayers.

    BertConfig.remat is intentionally not re-applied per layer here: the
    gpipe ring already jax.checkpoint's the WHOLE stage body (pipeline.py
    remat=True), which subsumes per-layer remat — only stage-boundary
    activations survive the forward either way."""

    cfg: BertConfig
    layers_per_stage: int

    @nn.compact
    def __call__(self, x, mask, train: bool = False):
        for i in range(self.layers_per_stage):
            x = BertLayer(self.cfg, name=f"layer_{i}")(x, mask, train)
        return x


class _Head(nn.Module):
    """[CLS] pooler + classifier (outside the ring)."""

    cfg: BertConfig
    num_classes: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        # batch-pin the CLS slice: its backward (a pad into the ring-exit
        # cotangent) otherwise inherits the pooler kernel's hidden sharding
        # and full-remats at the pipeline shard_map boundary
        cls = constrain(x[:, 0], P((*ACT_SPEC[0],), None))
        pooled = jnp.tanh(nn.Dense(self.cfg.hidden_size, dtype=self.cfg.dtype,
                                   name="pooler")(cls))
        pooled = nn.Dropout(self.cfg.dropout_rate, deterministic=not train)(pooled)
        logits = nn.Dense(self.num_classes, dtype=self.cfg.dtype,
                          name="classifier")(pooled)
        return logits.astype(jnp.float32)


class BertPipelineClassifier:
    """Drop-in for BertForSequenceClassification with a pipelined stack.

    Trainer-compatible duck type: init(rng, x, train=...) -> variables,
    apply(variables, x, rngs=..., train=...) -> logits.
    """

    PARTITION_RULES = PP_PARTITION_RULES

    def __init__(
        self,
        cfg: BertConfig,
        num_classes: int = 2,
        num_stages: int = 2,
        n_micro: int | None = None,
    ):
        if cfg.num_layers % num_stages:
            raise ValueError(
                f"num_layers {cfg.num_layers} not divisible by "
                f"num_stages {num_stages}"
            )
        self.cfg = cfg
        self.num_classes = num_classes
        self.num_stages = num_stages
        # 2 microbatches per stage keeps the GPipe bubble under 1/3
        self.n_micro = n_micro or 2 * num_stages
        self._embed = BertEmbeddings(cfg)
        self._stage = _Stage(cfg, cfg.num_layers // num_stages)
        self._head = _Head(cfg, num_classes)

    # Trainer introspects __call__ for the `train` kwarg
    def __call__(self, input_ids, train: bool = False):  # pragma: no cover
        raise NotImplementedError("use .apply()")

    # ------------------------------------------------------------------ init

    def init(self, rng, input_ids, train: bool = False) -> dict:
        e_rng, s_rng, h_rng, d_rng = jax.random.split(rng, 4)
        c = self.cfg
        ev = self._embed.init({"params": e_rng, "dropout": d_rng},
                              input_ids, False)
        x = jnp.zeros(
            (input_ids.shape[0], input_ids.shape[1], c.hidden_size), c.dtype
        )
        mask = jnp.ones(input_ids.shape, bool)

        def one_stage(r):
            return self._stage.init({"params": r, "dropout": d_rng},
                                    x, mask, False)["params"]

        stage_params = jax.vmap(one_stage)(
            jax.random.split(s_rng, self.num_stages)
        )
        hv = self._head.init({"params": h_rng, "dropout": d_rng}, x, False)
        return {
            "params": {
                "embeddings": ev["params"],
                "stages": stage_params,
                "head": hv["params"],
            }
        }

    # ----------------------------------------------------------------- apply

    def apply(self, variables, input_ids, rngs=None, train: bool = False,
              mutable=None, **_ignored):
        out, aux = self._apply(variables, input_ids, rngs=rngs, train=train)
        if mutable is not None:
            # flax contract: apply with `mutable` returns (out, updates); the
            # Trainer folds every 'losses' leaf into the objective
            upd = {"losses": {"moe_aux": aux}} if aux is not None else {}
            return out, upd
        return out

    def _apply(self, variables, input_ids, rngs=None, train: bool = False):
        p = variables["params"]
        c = self.cfg
        rngs = rngs or {}
        drop = rngs.get("dropout")
        mask = input_ids != c.pad_token_id
        x = self._embed.apply(
            {"params": p["embeddings"]}, input_ids, train,
            rngs={"dropout": drop} if (train and drop is not None) else {},
        )
        # the ring (and its transpose psums) runs in f32: a low-precision
        # all-reduce at the shard_map boundary trips XLA's AllReducePromotion
        # pass (CHECK crash); stages still compute in the model dtype
        x = x.astype(jnp.float32)

        moe = bool(c.moe_experts)

        def stage_fn(sp, act, *, stage, rng):
            h, m = act[0], act[1]
            srngs = {"dropout": rng} if (train and rng is not None) else {}
            h, upd = self._stage.apply(
                {"params": sp}, h.astype(c.dtype), m > 0, train, rngs=srngs,
                mutable=["losses"],
            )
            h = constrain(h.astype(jnp.float32), ACT_SPEC)
            if not moe:
                return (h, m)
            # MoE aux loss rides the ring as a per-example accumulator leaf
            # ((B,) f32, same shape at every boundary — the gpipe contract):
            # each stage adds ITS sown aux for THIS microbatch; the bubble's
            # zero-fed microbatches are discarded with the rest of outbuf.
            aux = sum(jax.tree.leaves(upd.get("losses", {})), 0.0)
            return (h, m, act[2] + jnp.asarray(aux, jnp.float32))

        act0 = (x, mask.astype(jnp.int8))
        if moe:
            act0 = (*act0, jnp.zeros((x.shape[0],), jnp.float32))
        out = gpipe(
            stage_fn,
            p["stages"],
            act0,
            self.n_micro,
            rng=drop if train else None,
        )
        # Pin the ring-exit activation to the canonical batch-sharded layout:
        # without this the head's backward hands the ring a hidden-sharded
        # cotangent and the partitioner full-remats it at the shard_map
        # boundary (the composed-mesh involuntary-remat warning).
        hid = constrain(out[0], ACT_SPEC)
        logits = self._head.apply(
            {"params": p["head"]}, hid, train,
            rngs={"dropout": drop} if (train and drop is not None) else {},
        )
        # mean over examples == mean over microbatches of the per-microbatch
        # aux sum — the same scale dense BERT's summed sow leaves carry
        return logits, (out[2].mean() if moe else None)
