"""ViT — vision transformer classifier (Dosovitskiy et al. 2020).

The reference platform ships no models (user images supply them — SURVEY.md
L6); this family exists because patch-embedding + encoder turns IMAGE
workloads into the shape TPUs like best: one big (B, N_patches, H) matmul
stream onto the MXU instead of the conv lowering this backend runs at
0.3-0.6 TFLOP/s (docs/perf.md item 4) — ViT is the performance-first
alternative to ResNet here, not just zoo breadth.

Reuses the BERT encoder block (models/bert.py BertLayer) with an all-ones
mask, so TP/FSDP PARTITION_RULES, pluggable attention (dense or flash —
NOT ring/ulysses: the sequence is num_patches + 1 CLS, always odd, so it
cannot divide a context axis), and activation pinning come for free; the
patch embed is a single DenseGeneral over flattened patches (a reshape +
matmul — no conv op).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from kubeflow_tpu.models.bert import (
    ACT_SPEC,
    PARTITION_RULES,
    BertConfig,
    BertLayer,
    constrain,
)


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    dropout_rate: float = 0.1
    dtype: Any = jnp.float32
    # dense | flash (seq = patches + CLS is odd — context-parallel ring/
    # ulysses cannot shard it; flash takes the ragged-tail fallback)
    attention: str = "dense"
    attention_block: int = 128

    def __post_init__(self):
        if self.image_size % self.patch_size:
            raise ValueError(
                f"image_size {self.image_size} not divisible by "
                f"patch_size {self.patch_size}"
            )
        if self.hidden_size % self.num_heads:
            raise ValueError(
                f"hidden_size {self.hidden_size} not divisible by "
                f"num_heads {self.num_heads}"
            )

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    def encoder_config(self) -> BertConfig:
        """The BertLayer-compatible view of this config (seq = patches+CLS)."""
        return BertConfig(
            vocab_size=2,  # unused by the encoder blocks
            hidden_size=self.hidden_size,
            num_layers=self.num_layers,
            num_heads=self.num_heads,
            mlp_dim=self.mlp_dim,
            max_len=self.num_patches + 1,
            dropout_rate=self.dropout_rate,
            dtype=self.dtype,
            attention=self.attention,
            attention_block=self.attention_block,
        )

    @staticmethod
    def base(**kw) -> "ViTConfig":
        return ViTConfig(**kw)  # ViT-B/16 shape

    @staticmethod
    def tiny(**kw) -> "ViTConfig":
        d = dict(image_size=32, patch_size=8, num_classes=10, hidden_size=64,
                 num_layers=2, num_heads=4, mlp_dim=128)
        d.update(kw)
        return ViTConfig(**d)


class ViTClassifier(nn.Module):
    """images (B, H, W, C) -> class logits (B, num_classes) f32.

    PARTITION_RULES are BERT's (set below): the encoder params match the
    same suffixes; patch_embed/head fall to the replicate/fsdp heuristic.
    """

    cfg: ViTConfig

    @nn.compact
    def __call__(self, images, train: bool = False):
        c = self.cfg
        b, h, w, ch = images.shape
        p = c.patch_size
        if (h, w) != (c.image_size, c.image_size):
            raise ValueError(
                f"expected {c.image_size}x{c.image_size} images, got {h}x{w}"
            )
        # patchify as reshape+transpose, embed as ONE matmul (MXU-native;
        # never a conv op on this backend)
        x = images.astype(c.dtype).reshape(b, h // p, p, w // p, p, ch)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, c.num_patches, p * p * ch)
        x = nn.Dense(c.hidden_size, dtype=c.dtype, name="patch_embed")(x)

        cls = self.param(
            "cls_token", nn.initializers.zeros, (1, 1, c.hidden_size),
            jnp.float32,
        )
        x = jnp.concatenate(
            [jnp.broadcast_to(cls, (b, 1, c.hidden_size)).astype(c.dtype), x],
            axis=1,
        )
        pos = self.param(
            "position_embed", nn.initializers.normal(stddev=0.02),
            (1, c.num_patches + 1, c.hidden_size), jnp.float32,
        )
        x = x + pos.astype(c.dtype)
        x = nn.Dropout(c.dropout_rate, deterministic=not train)(x)
        x = constrain(x, ACT_SPEC)

        ecfg = self.cfg.encoder_config()
        mask = jnp.ones((b, c.num_patches + 1), bool)  # no padding in images
        for i in range(c.num_layers):
            x = BertLayer(ecfg, name=f"layer_{i}")(x, mask, train)
        x = nn.LayerNorm(dtype=c.dtype, name="ln_final")(x)
        logits = nn.Dense(c.num_classes, dtype=c.dtype, name="head")(x[:, 0])
        return logits.astype(jnp.float32)


ViTClassifier.PARTITION_RULES = PARTITION_RULES
# MXU-heavy: AUTO compute dtype resolves to bf16 on accelerator backends
ViTClassifier.PREFERRED_COMPUTE_DTYPE = jnp.bfloat16
