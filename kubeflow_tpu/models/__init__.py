"""In-tree model library (the reference ships these as example images —
kubeflow/examples mnist / resnet / bert, SURVEY.md L6).

Models are flax modules with logical-axis param annotations so the same
module runs 1-device or sharded over the mesh's model/fsdp axes.
"""

from kubeflow_tpu.models.bert import (
    BertConfig,
    BertEncoder,
    BertForMaskedLM,
    BertForSequenceClassification,
)
from kubeflow_tpu.models.bert_pp import BertPipelineClassifier
from kubeflow_tpu.models.gpt_pp import GPTPipelineLM
from kubeflow_tpu.models.gpt import (
    GPTConfig,
    GPTLM,
    causal_lm_eval_metrics,
    causal_lm_loss,
)
from kubeflow_tpu.models.mnist import MnistCNN, MnistMLP
from kubeflow_tpu.models.vit import ViTClassifier, ViTConfig
from kubeflow_tpu.models.resnet import (
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
    s2d_pack,
    stem_weights_7x7_to_s2d,
)

__all__ = [
    "BertConfig",
    "BertEncoder",
    "BertForMaskedLM",
    "BertForSequenceClassification",
    "BertPipelineClassifier",
    "GPTConfig",
    "GPTLM",
    "causal_lm_loss",
    "causal_lm_eval_metrics",
    "MnistMLP",
    "MnistCNN",
    "GPTPipelineLM",
    "ViTClassifier",
    "ViTConfig",
    "ResNet",
    "ResNet18",
    "ResNet34",
    "ResNet50",
    "ResNet101",
    "ResNet152",
    "s2d_pack",
    "stem_weights_7x7_to_s2d",
]
