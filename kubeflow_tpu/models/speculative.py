"""Speculative decoding — draft-accelerated generation, target-exact.

Decode is HBM-bandwidth-bound: every generated token streams the whole
model once (models/gpt.py#generate). Speculative decoding (Leviathan et
al. 2023 / Chen et al. 2023 pattern) amortizes that: a small DRAFT model
proposes `gamma` tokens autoregressively, then the TARGET model scores
all of them in ONE forward pass (a gamma+1-token prefill over the KV
cache — MXU-shaped work instead of gamma bandwidth-bound steps) and
accepts the longest prefix it agrees with, emitting its own correction
token at the first disagreement. Two modes, both target-exact for ANY
draft (a bad draft only costs speed, never correctness — pinned by
tests): greedy (temperature 0, acceptance is argmax-match, output IS the
target's greedy decode) and rejection SAMPLING (temperature > 0,
acceptance probability min(1, p_t/p_d) with residual resampling — the
output DISTRIBUTION equals sampling the target directly).

TPU-first shape: `gamma` is static, every round is the same two
executables (draft scan + target prefill), and the variable accepted
length only moves the CACHE INDEX — stale cache rows past the index are
invisible by construction (the position mask attends only to
k_pos <= q_pos), so "rewinding" after a rejection is one scalar write,
no buffer surgery. The outer loop is a lax.while_loop on tokens
generated; everything jits once.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from kubeflow_tpu.models.gpt import GPTLM, set_cache_indices

# Rewind/advance every layer's cache_index (and the LM's pos_index) —
# the whole cost of rejecting speculated tokens. One shared owner of the
# index-rewrite contract (models/gpt.py); batch-1 here, so one scalar
# fills every row.
_set_cache_index = set_cache_indices


def speculative_generate(
    target: GPTLM,
    target_variables: dict,
    draft: GPTLM,
    draft_variables: dict,
    prompt_ids: jax.Array,
    max_new_tokens: int,
    gamma: int = 4,
    eos_token_id: int | None = None,
    temperature: float = 0.0,
    rng: jax.Array | None = None,
):
    """Speculative decoding. Returns (tokens (1, max_new_tokens), stats
    dict with 'rounds' and 'drafted_accepted').

    temperature == 0 (default): greedy — acceptance is argmax-match, the
    output is EXACTLY the target model's greedy decode for ANY draft.

    temperature > 0: SPECULATIVE SAMPLING (Leviathan/Chen rejection
    scheme, needs `rng`) — proposal x_i ~ p_draft is accepted with
    probability min(1, p_target(x_i)/p_draft(x_i)); the first rejection
    resamples from the normalized residual max(0, p_target − p_draft),
    and an all-accepted round samples the bonus token from p_target.
    The OUTPUT DISTRIBUTION equals sampling the target directly — for
    any draft — though individual draws differ from generate()'s
    (different uses of the key). Pinned statistically in tests plus the
    draft==target invariant (every proposal accepted).

    Batch size 1 (rows diverge in accepted length; a batched variant
    needs per-row cache indices — serving/continuous.py has the rowwise
    greedy version). The draft must share the target's vocabulary;
    nothing else — architectures, sizes, and even weights may differ
    arbitrarily.

    eos_token_id mirrors generate()'s contract: once EOS lands in the
    emitted prefix the loop stops (no more speculation rounds for a
    sequence the target has finished) and every position after the first
    EOS is clamped to EOS — callers trim at the first occurrence, and
    the output past EOS matches generate(..., eos_token_id=...) exactly.
    """
    b, prompt_len = prompt_ids.shape
    if b != 1:
        raise ValueError(
            f"speculative_generate is batch-1 (got batch {b}): accepted "
            "length diverges per row; run rows as separate calls")
    if max_new_tokens < 1:
        raise ValueError(
            f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    sampling = temperature > 0.0
    if sampling and rng is None:
        raise ValueError("speculative sampling (temperature > 0) needs rng")
    if rng is None:
        rng = jax.random.PRNGKey(0)  # carried but unused in greedy mode
    for m, name in ((target, "target"), (draft, "draft")):
        if prompt_len + max_new_tokens + gamma + 1 > m.cfg.max_len:
            raise ValueError(
                f"{name}.cfg.max_len {m.cfg.max_len} < prompt {prompt_len} "
                f"+ max_new_tokens {max_new_tokens} + gamma+1 {gamma + 1}")
        if getattr(m.cfg, "kv_cache_capacity", 0):
            raise ValueError(
                f"{name} uses a rolling KV cache (kv_cache_capacity) — "
                "speculative rewind makes ring-slot identity ambiguous "
                "(a rewound index cannot tell stale newer writes from "
                "valid older ones); serve rolling models without a draft")

    # prefill both caches over the prompt; first token comes from the
    # target alone (same as plain greedy/sampled decode)
    t_logits, t_cache = target.apply(
        target_variables, prompt_ids, decode=True, mutable=["cache"])
    _, d_cache = draft.apply(
        draft_variables, prompt_ids, decode=True, mutable=["cache"])
    rng, first_key = jax.random.split(rng)
    if sampling:
        first = jax.random.categorical(
            first_key, t_logits[:, -1] / jnp.float32(temperature)
        ).astype(jnp.int32)                                    # (1,)
    else:
        first = jnp.argmax(t_logits[:, -1], axis=-1).astype(jnp.int32)

    buf0 = jnp.zeros((max_new_tokens + gamma + 1,), jnp.int32)
    buf0 = buf0.at[0].set(first[0])

    def draft_step(carry, _):
        cache, tok, key = carry
        logits, cache = draft.apply(
            {**draft_variables, **cache}, tok[:, None], decode=True,
            mutable=["cache"])
        row = logits[:, -1]                                # (1, V)
        if sampling:
            key, k = jax.random.split(key)
            scaled = row / jnp.float32(temperature)
            nxt = jax.random.categorical(k, scaled).astype(jnp.int32)
            probs = jax.nn.softmax(scaled, axis=-1)[0]     # (V,)
        else:
            nxt = jnp.argmax(row, axis=-1).astype(jnp.int32)
            probs = jnp.zeros((row.shape[-1],), jnp.float32)  # unused
        return (cache, nxt, key), (nxt, probs)

    def round_body(state):
        buf, n, t_cache, d_cache, rounds, accepted_total, rng = state
        last = buf[n - 1][None]                                # (1,)
        rng, d_key, u_key, c_key = jax.random.split(rng, 4)
        # --- draft proposes gamma tokens ------------------------------
        (d_cache, p_last, _), (proposals, d_probs) = jax.lax.scan(
            draft_step, (d_cache, last, d_key), None, length=gamma)
        proposals = proposals[:, 0]                            # (gamma,)
        # one extra draft step writes p_gamma into the draft cache (its
        # proposal is discarded) so an all-accepted round leaves no
        # unwritten row below the advanced cache index
        (d_cache, _, _), _ = draft_step((d_cache, p_last, d_key), None)
        # --- target scores last + ALL proposals in ONE pass -----------
        inp = jnp.concatenate([last, proposals])[None, :]   # (1, gamma+1)
        logits, t_cache_adv = target.apply(
            {**target_variables, **t_cache}, inp, decode=True,
            mutable=["cache"])
        if sampling:
            # Leviathan/Chen rejection: accept x_i with prob
            # min(1, p_t(x_i)/p_d(x_i)); first rejection resamples from
            # the normalized residual max(0, p_t − p_d); an all-accepted
            # round samples the bonus token from p_t — output
            # distribution == sampling the target directly.
            p_t = jax.nn.softmax(
                logits[0] / jnp.float32(temperature), axis=-1
            )                                               # (gamma+1, V)
            pt_x = jnp.take_along_axis(
                p_t[:gamma], proposals[:, None], axis=-1)[:, 0]
            pd_x = jnp.take_along_axis(
                d_probs, proposals[:, None], axis=-1)[:, 0]
            u = jax.random.uniform(u_key, (gamma,))
            ok = u < jnp.minimum(1.0, pt_x / jnp.maximum(pd_x, 1e-30))
            agree = jnp.cumprod(ok.astype(jnp.int32))
            a = agree.sum()                 # accepted draft tokens
            residual = jnp.clip(p_t[:gamma] - d_probs, 0.0)
            rs = residual.sum(-1, keepdims=True)
            # rejection at i implies p_t[i] != p_d[i] somewhere, so
            # rs > 0 there; the where guards fp underflow only
            res_norm = jnp.where(rs > 0, residual / jnp.maximum(rs, 1e-30),
                                 p_t[:gamma])
            corr_rows = jnp.concatenate([res_norm, p_t[gamma:]], axis=0)
            corr = jax.random.categorical(
                c_key, jnp.log(jnp.maximum(corr_rows[a], 1e-30))
            ).astype(jnp.int32)
        else:
            # t_tokens[i] = target's own choice after accepting i
            # proposals; accept while the draft matches it
            t_tokens = jnp.argmax(logits[0], axis=-1).astype(
                jnp.int32)                                  # (gamma+1,)
            agree = jnp.cumprod(
                (proposals == t_tokens[:gamma]).astype(jnp.int32))
            a = agree.sum()                 # accepted draft tokens
            corr = t_tokens[a]
        # emit proposals[:a], then the correction token (when a == gamma
        # that's the target's continuation past the whole accepted
        # block); slots past a+1 hold the correction too — they are
        # overwritten by the next round or trimmed at max_new_tokens
        padded = jnp.concatenate([proposals, jnp.zeros((1,), jnp.int32)])
        upd = jnp.where(jnp.arange(gamma + 1) < a, padded, corr)
        buf = jax.lax.dynamic_update_slice(buf, upd, (n,))
        n = n + a + 1
        # --- cache bookkeeping ----------------------------------------
        # both caches wrote gamma+1 rows (last + proposals); only
        # last + the a accepted stay valid. Rows past the index are
        # unreachable (the position mask attends k_pos <= q_pos), so ONE
        # scalar write is the whole rewind.
        base = prompt_len + n - 1
        t_cache = {"cache": _set_cache_index(
            t_cache_adv["cache"], base)}
        d_cache = {"cache": _set_cache_index(d_cache["cache"], base)}
        return (buf, n, t_cache, d_cache, rounds + 1, accepted_total + a,
                rng)

    from kubeflow_tpu.models.gpt import eos_id_array

    stops = eos_id_array(eos_token_id)

    def cond(state):
        buf, n, *_rest = state
        more = n < max_new_tokens
        if stops is not None:
            emitted = jnp.arange(buf.shape[0]) < n
            more = more & ~jnp.any(emitted & jnp.isin(buf, stops))
        return more

    state0 = (buf0, jnp.asarray(1, jnp.int32),
              {"cache": _set_cache_index(t_cache["cache"],
                                         prompt_len)},
              {"cache": _set_cache_index(d_cache["cache"], prompt_len)},
              jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32), rng)
    buf, n, _, _, rounds, accepted, _ = jax.lax.while_loop(
        cond, round_body, state0)
    out = buf[:max_new_tokens]
    if stops is not None:
        # clamp past the first stop id (rounds overshoot by up to gamma)
        pos = jnp.arange(max_new_tokens)
        hit = jnp.isin(out, stops)
        first = jnp.argmax(hit)  # 0 when no hit; guarded by jnp.any below
        out = jnp.where(jnp.any(hit) & (pos > first), stops[0], out)
    return out[None, :], {
        "rounds": rounds, "drafted_accepted": accepted,
        "tokens": jnp.minimum(n, max_new_tokens),
    }
