"""Speculative decoding — draft-accelerated generation, target-exact.

Decode is HBM-bandwidth-bound: every generated token streams the whole
model once (models/gpt.py#generate). Speculative decoding (Leviathan et
al. 2023 / Chen et al. 2023 pattern) amortizes that: a small DRAFT model
proposes `gamma` tokens autoregressively, then the TARGET model scores
all of them in ONE forward pass (a gamma+1-token prefill over the KV
cache — MXU-shaped work instead of gamma bandwidth-bound steps) and
accepts the longest prefix it agrees with, emitting its own correction
token at the first disagreement. Greedy mode here: acceptance is
argmax-match, so the output is EXACTLY the target model's greedy decode
for ANY draft — a random draft only costs speed, never correctness
(pinned by test).

TPU-first shape: `gamma` is static, every round is the same two
executables (draft scan + target prefill), and the variable accepted
length only moves the CACHE INDEX — stale cache rows past the index are
invisible by construction (the position mask attends only to
k_pos <= q_pos), so "rewinding" after a rejection is one scalar write,
no buffer surgery. The outer loop is a lax.while_loop on tokens
generated; everything jits once.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from kubeflow_tpu.models.gpt import GPTLM, set_cache_indices

# Rewind/advance every layer's cache_index (and the LM's pos_index) —
# the whole cost of rejecting speculated tokens. One shared owner of the
# index-rewrite contract (models/gpt.py); batch-1 here, so one scalar
# fills every row.
_set_cache_index = set_cache_indices


def speculative_generate(
    target: GPTLM,
    target_variables: dict,
    draft: GPTLM,
    draft_variables: dict,
    prompt_ids: jax.Array,
    max_new_tokens: int,
    gamma: int = 4,
    eos_token_id: int | None = None,
):
    """Greedy speculative decoding. Returns (tokens (1, max_new_tokens),
    stats dict with 'rounds' and 'drafted_accepted').

    Batch size 1 (rows diverge in accepted length; a batched variant
    needs per-row cache indices). The draft must share the target's
    vocabulary; nothing else — architectures, sizes, and even weights may
    differ arbitrarily.

    eos_token_id mirrors generate()'s contract: once EOS lands in the
    emitted prefix the loop stops (no more speculation rounds for a
    sequence the target has finished) and every position after the first
    EOS is clamped to EOS — callers trim at the first occurrence, and
    the output past EOS matches generate(..., eos_token_id=...) exactly.
    """
    b, prompt_len = prompt_ids.shape
    if b != 1:
        raise ValueError(
            f"speculative_generate is batch-1 (got batch {b}): accepted "
            "length diverges per row; run rows as separate calls")
    if max_new_tokens < 1:
        raise ValueError(
            f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    for m, name in ((target, "target"), (draft, "draft")):
        if prompt_len + max_new_tokens + gamma + 1 > m.cfg.max_len:
            raise ValueError(
                f"{name}.cfg.max_len {m.cfg.max_len} < prompt {prompt_len} "
                f"+ max_new_tokens {max_new_tokens} + gamma+1 {gamma + 1}")
        if getattr(m.cfg, "kv_cache_capacity", 0):
            raise ValueError(
                f"{name} uses a rolling KV cache (kv_cache_capacity) — "
                "speculative rewind makes ring-slot identity ambiguous "
                "(a rewound index cannot tell stale newer writes from "
                "valid older ones); serve rolling models without a draft")

    # prefill both caches over the prompt; first token comes from the
    # target alone (same as plain greedy)
    t_logits, t_cache = target.apply(
        target_variables, prompt_ids, decode=True, mutable=["cache"])
    _, d_cache = draft.apply(
        draft_variables, prompt_ids, decode=True, mutable=["cache"])
    first = jnp.argmax(t_logits[:, -1], axis=-1).astype(jnp.int32)  # (1,)

    buf0 = jnp.zeros((max_new_tokens + gamma + 1,), jnp.int32)
    buf0 = buf0.at[0].set(first[0])

    def draft_step(carry, _):
        cache, tok = carry
        logits, cache = draft.apply(
            {**draft_variables, **cache}, tok[:, None], decode=True,
            mutable=["cache"])
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        return (cache, nxt), nxt

    def round_body(state):
        buf, n, t_cache, d_cache, rounds, accepted_total = state
        last = buf[n - 1][None]                                # (1,)
        # --- draft proposes gamma tokens ------------------------------
        (d_cache, p_last), proposals = jax.lax.scan(
            draft_step, (d_cache, last), None, length=gamma)
        proposals = proposals[:, 0]                            # (gamma,)
        # one extra draft step writes p_gamma into the draft cache (its
        # proposal is discarded) so an all-accepted round leaves no
        # unwritten row below the advanced cache index
        (d_cache, _), _ = draft_step((d_cache, p_last), None)
        # --- target scores last + ALL proposals in ONE pass -----------
        inp = jnp.concatenate([last, proposals])[None, :]   # (1, gamma+1)
        logits, t_cache_adv = target.apply(
            {**target_variables, **t_cache}, inp, decode=True,
            mutable=["cache"])
        # t_tokens[i] = target's own choice after accepting i proposals
        t_tokens = jnp.argmax(logits[0], axis=-1).astype(
            jnp.int32)                                      # (gamma+1,)
        # accept while the draft matches the target's own choice
        agree = jnp.cumprod(
            (proposals == t_tokens[:gamma]).astype(jnp.int32))
        a = agree.sum()                     # accepted draft tokens, 0..gamma
        # emit proposals[:a], then the target's correction t_tokens[a]
        # (when a == gamma that's the target's continuation past the whole
        # accepted block); slots past a+1 hold the correction too — they
        # are overwritten by the next round or trimmed at max_new_tokens
        padded = jnp.concatenate([proposals, jnp.zeros((1,), jnp.int32)])
        upd = jnp.where(jnp.arange(gamma + 1) < a, padded, t_tokens[a])
        buf = jax.lax.dynamic_update_slice(buf, upd, (n,))
        n = n + a + 1
        # --- cache bookkeeping ----------------------------------------
        # both caches wrote gamma+1 rows (last + proposals); only
        # last + the a accepted stay valid. Rows past the index are
        # unreachable (the position mask attends k_pos <= q_pos), so ONE
        # scalar write is the whole rewind.
        base = prompt_len + n - 1
        t_cache = {"cache": _set_cache_index(
            t_cache_adv["cache"], base)}
        d_cache = {"cache": _set_cache_index(d_cache["cache"], base)}
        return (buf, n, t_cache, d_cache, rounds + 1, accepted_total + a)

    from kubeflow_tpu.models.gpt import eos_id_array

    stops = eos_id_array(eos_token_id)

    def cond(state):
        buf, n, *_rest = state
        more = n < max_new_tokens
        if stops is not None:
            emitted = jnp.arange(buf.shape[0]) < n
            more = more & ~jnp.any(emitted & jnp.isin(buf, stops))
        return more

    state0 = (buf0, jnp.asarray(1, jnp.int32),
              {"cache": _set_cache_index(t_cache["cache"],
                                         prompt_len)},
              {"cache": _set_cache_index(d_cache["cache"], prompt_len)},
              jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32))
    buf, n, _, _, rounds, accepted = jax.lax.while_loop(
        cond, round_body, state0)
    out = buf[:max_new_tokens]
    if stops is not None:
        # clamp past the first stop id (rounds overshoot by up to gamma)
        pos = jnp.arange(max_new_tokens)
        hit = jnp.isin(out, stops)
        first = jnp.argmax(hit)  # 0 when no hit; guarded by jnp.any below
        out = jnp.where(jnp.any(hit) & (pos > first), stops[0], out)
    return out[None, :], {
        "rounds": rounds, "drafted_accepted": accepted,
        "tokens": jnp.minimum(n, max_new_tokens),
    }
