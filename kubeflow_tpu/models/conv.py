"""Convolution as im2col + matmul — a conv path that never emits a conv HLO.

Why this exists (docs/perf.md, axon characterization): on the axon v5e
backend `lax.conv_general_dilated` lowers ~200× below matmul throughput
(0.3–0.6 TFLOP/s vs 117 TFLOP/s measured), so a ResNet built on conv HLOs is
bounded at ~1% MFU by the backend, not by the model. Expressing the conv as
statically-unrolled shifted slices + ONE matmul keeps all FLOPs on the MXU's
well-trodden dot path:

  patches[b, oy, ox, (i*kw + j)*cin + ci] = x_pad[b, oy*sh + i, ox*sw + j, ci]
  y = patches @ kernel.reshape(kh*kw*cin, cout)

which is exactly the reference's im2col/GEMM formulation of conv (the CUDA
lineage: cuDNN IMPLICIT_GEMM), done the XLA way — slices and concats fuse
into the matmul's operand, and autodiff yields pad/slice-add + matmuls for
the backward (no conv-transpose HLO either).

The module is param-compatible with `flax.linen.Conv` (same "kernel"/"bias"
names and HWIO shape), so checkpoints interchange and `ResNet(conv_impl=...)`
can flip per backend with no other change. SAME padding, positive strides,
NHWC only — the shapes ResNet uses.

Reference parity note: the reference platform never owns convs (they live in
user torch/TF images — SURVEY.md §2.2 DP row); this in-tree path exists so
the north-star ResNet bench reflects the framework, not a backend gap.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


def _same_pads(size: int, k: int, s: int) -> tuple[int, int, int]:
    """(pad_lo, pad_hi, out_size) for SAME padding along one spatial dim."""
    out = -(-size // s)  # ceil div
    total = max(0, (out - 1) * s + k - size)
    lo = total // 2
    return lo, total - lo, out


def im2col_conv(
    x: jax.Array,
    kernel: jax.Array,
    strides: Sequence[int] = (1, 1),
) -> jax.Array:
    """SAME-padded NHWC conv computed as shifted slices + one matmul.

    x: (B, H, W, Cin); kernel: (kh, kw, Cin, Cout) [HWIO, as flax]. Matches
    `lax.conv_general_dilated(..., padding="SAME")` numerics in the same
    dtype up to dot-order rounding.
    """
    kh, kw, cin, cout = kernel.shape
    b, h, w, _ = x.shape
    sh, sw = strides
    plo_h, phi_h, oh = _same_pads(h, kh, sh)
    plo_w, phi_w, ow = _same_pads(w, kw, sw)

    if kh == kw == 1:
        # 1x1: pure (strided) matmul, no patches needed
        y = x[:, ::sh, ::sw, :] if (sh, sw) != (1, 1) else x
        return (y.reshape(-1, cin) @ kernel.reshape(cin, cout)).reshape(
            b, oh, ow, cout
        )

    xp = jnp.pad(x, ((0, 0), (plo_h, phi_h), (plo_w, phi_w), (0, 0)))
    # statically-unrolled kh*kw shifted strided views; concat order matches
    # the row-major flatten of the HWIO kernel's leading (kh, kw, cin) dims
    cols = [
        jax.lax.slice(
            xp,
            (0, i, j, 0),
            (b, i + (oh - 1) * sh + 1, j + (ow - 1) * sw + 1, cin),
            (1, sh, sw, 1),
        )
        for i in range(kh)
        for j in range(kw)
    ]
    patches = jnp.concatenate(cols, axis=-1)  # (B, OH, OW, kh*kw*cin)
    y = patches.reshape(-1, kh * kw * cin) @ kernel.reshape(kh * kw * cin, cout)
    return y.reshape(b, oh, ow, cout)


class Im2ColConv(nn.Module):
    """Drop-in for `nn.Conv(features, kernel_size, strides, use_bias, dtype)`
    restricted to NHWC + SAME padding, lowering via `im2col_conv`."""

    features: int
    kernel_size: Sequence[int]
    strides: Sequence[int] = (1, 1)
    use_bias: bool = True
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        kh, kw = self.kernel_size
        cin = x.shape[-1]
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (kh, kw, cin, self.features),
            self.param_dtype,
        )
        y = im2col_conv(
            x.astype(self.dtype), kernel.astype(self.dtype), tuple(self.strides)
        )
        if self.use_bias:
            bias = self.param(
                "bias", nn.initializers.zeros_init(), (self.features,),
                self.param_dtype,
            )
            y = y + bias.astype(self.dtype)
        return y


# Flax auto-names submodules by CLASS name ("Conv_0", "Im2ColConv_0", ...),
# so a drop-in replacement must also be NAMED "Conv" for param trees (and
# therefore checkpoints) to interchange with nn.Conv-built models. A real
# class statement (not type(...)) keeps it picklable: pickle resolves
# kubeflow_tpu.models.conv.Conv by attribute lookup.
class Conv(Im2ColConv):
    pass


ConvCompat = Conv
