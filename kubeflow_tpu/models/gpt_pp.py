"""Pipeline-parallel GPT — the decoder stack over the `pipeline` mesh axis.

The long-context flagship at scale: the same GPipe microbatch ring as
models/bert_pp.py (partial-manual shard_map over `pipeline`; TP/FSDP/
context shardings stay automatic inside stages), carrying the CAUSAL
decoder. Ring attention composes inside stages exactly as it does for the
BERT encoder (tests/test_composed_16dev.py precedent), so sequence
parallelism and pipeline parallelism stack on the decoder too.

Embeddings and the weight-tied LM head run outside the ring (their
activation shapes differ from the stack's); the tied table is therefore a
boundary param, replicated over `pipeline` like the BERT head.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from kubeflow_tpu.models.bert import ACT_SPEC, VocabEmbed, constrain
from kubeflow_tpu.models.gpt import GPTBlock, GPTConfig
from kubeflow_tpu.models.gpt import PARTITION_RULES as GPT_RULES
from kubeflow_tpu.parallel.pipeline import gpipe, lift_pipeline_rules

PP_PARTITION_RULES: list[tuple[str, P]] = lift_pipeline_rules(GPT_RULES)


class _Stage(nn.Module):
    """GPTConfig.remat is intentionally not re-applied per layer here: the
    gpipe ring already jax.checkpoint's the WHOLE stage body, which
    subsumes per-layer remat (see bert_pp._Stage)."""

    cfg: GPTConfig
    layers_per_stage: int

    @nn.compact
    def __call__(self, x, bias, train: bool = False):
        for i in range(self.layers_per_stage):
            x = GPTBlock(self.cfg, name=f"layer_{i}")(x, bias, train)
        return x


class GPTPipelineLM:
    """Drop-in for GPTLM with a pipelined decoder stack (training path;
    KV-cache generation stays on the unpipelined GPTLM — decode is
    latency-bound and single-stage)."""

    PARTITION_RULES = PP_PARTITION_RULES

    def __init__(self, cfg: GPTConfig, num_stages: int = 2,
                 n_micro: int | None = None, pad_token_id: int = 0):
        if cfg.num_layers % num_stages:
            raise ValueError(
                f"num_layers {cfg.num_layers} not divisible by "
                f"num_stages {num_stages}"
            )
        self.cfg = cfg
        self.pad_token_id = pad_token_id
        self.num_stages = num_stages
        self.n_micro = n_micro or 2 * num_stages
        self._embed_tok = VocabEmbed(cfg.vocab_size, cfg.hidden_size,
                                     dtype=cfg.dtype, name="token_embed")
        self._embed_pos = VocabEmbed(cfg.max_len, cfg.hidden_size,
                                     dtype=cfg.dtype, name="position_embed")
        self._stage = _Stage(cfg, cfg.num_layers // num_stages)

    # Trainer introspects __call__ for the `train` kwarg
    def __call__(self, input_ids, train: bool = False):  # pragma: no cover
        raise NotImplementedError("use .apply()")

    def init(self, rng, input_ids, train: bool = False) -> dict:
        c = self.cfg
        t_rng, p_rng, s_rng, d_rng = jax.random.split(rng, 4)
        tv = self._embed_tok.init(t_rng, input_ids)
        pos = jnp.arange(input_ids.shape[1])[None, :]
        pv = self._embed_pos.init(p_rng, pos)
        x = jnp.zeros(
            (input_ids.shape[0], input_ids.shape[1], c.hidden_size), c.dtype
        )
        bias = jnp.zeros((input_ids.shape[0], 1, 1, input_ids.shape[1]),
                         c.dtype)

        def one_stage(r):
            return self._stage.init(
                {"params": r, "dropout": d_rng}, x, bias, False
            )["params"]

        stage_params = jax.vmap(one_stage)(
            jax.random.split(s_rng, self.num_stages)
        )
        ln = nn.LayerNorm(dtype=c.dtype, name="ln_final")
        lv = ln.init(d_rng, x)
        return {"params": {
            "token_embed": tv["params"],
            "position_embed": pv["params"],
            "stages": stage_params,
            "ln_final": lv["params"],
        }}

    def apply(self, variables, input_ids, rngs=None, train: bool = False,
              mutable=None, **_ignored):
        out, aux = self._apply(variables, input_ids, rngs=rngs, train=train)
        if mutable is not None:
            # Trainer folds every 'losses' leaf into the objective
            upd = {"losses": {"moe_aux": aux}} if aux is not None else {}
            return out, upd
        return out

    def _apply(self, variables, input_ids, rngs=None, train: bool = False):
        p = variables["params"]
        c = self.cfg
        rngs = rngs or {}
        drop = rngs.get("dropout")
        tok = self._embed_tok.bind({"params": p["token_embed"]})
        x = tok(input_ids)
        pos = jnp.arange(input_ids.shape[1])[None, :]
        x = x + self._embed_pos.apply({"params": p["position_embed"]}, pos)
        mask = input_ids != self.pad_token_id
        bias = jnp.where(mask[:, None, None, :], 0.0, -1e9).astype(c.dtype)
        if train and drop is not None and c.dropout_rate > 0:
            # embedding dropout, matching dense GPTLM's training path
            # (nn.Dropout is parameterless — functional apply)
            x = nn.Dropout(c.dropout_rate, deterministic=False).apply(
                {}, x, rngs={"dropout": drop}
            )
        # f32 through the ring boundary (bert_pp precedent: a low-precision
        # all-reduce at the shard_map boundary trips AllReducePromotion)
        x = x.astype(jnp.float32)

        moe = bool(c.moe_experts)

        def stage_fn(sp, act, *, stage, rng):
            h, b = act[0], act[1]
            srngs = {"dropout": rng} if (train and rng is not None) else {}
            h, upd = self._stage.apply(
                {"params": sp}, h.astype(c.dtype), b.astype(c.dtype), train,
                rngs=srngs, mutable=["losses"],
            )
            h = constrain(h.astype(jnp.float32), ACT_SPEC)
            if not moe:
                return (h, b)
            # MoE aux rides the ring as a per-example accumulator leaf
            # (bert_pp precedent: same shape at every boundary; bubble
            # microbatches are discarded with the rest of outbuf)
            aux = sum(jax.tree.leaves(upd.get("losses", {})), 0.0)
            return (h, b, act[2] + jnp.asarray(aux, jnp.float32))

        act0 = (x, bias.astype(jnp.float32))
        if moe:
            act0 = (*act0, jnp.zeros((x.shape[0],), jnp.float32))
        out_tree = gpipe(
            stage_fn,
            p["stages"],
            act0,
            self.n_micro,
            rng=drop if train else None,
        )
        out = constrain(out_tree[0], ACT_SPEC)
        aux_total = out_tree[2].mean() if moe else None
        ln = nn.LayerNorm(dtype=c.dtype, name="ln_final")
        h = ln.apply({"params": p["ln_final"]}, out.astype(c.dtype))
        logits = tok.attend(h)  # weight-tied head, outside the ring
        return logits.astype(jnp.float32), aux_total
