"""MNIST-class models — north-star config #1 (BASELINE.md: >97% test acc).

Small enough that TPU considerations are trivial, but written the same way
as the big models: static shapes, channels-last, f32 params with optional
bf16 compute handled by the trainer.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class MnistMLP(nn.Module):
    """MLP for flat image vectors (sklearn digits 64-d or MNIST 784-d)."""

    hidden: Sequence[int] = (128, 64)
    num_classes: int = 10

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        for h in self.hidden:
            x = nn.relu(nn.Dense(h)(x))
        return nn.Dense(self.num_classes)(x)


class MnistCNN(nn.Module):
    """Conv net for (H, W, C) images."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x):
        if x.ndim == 2:  # flat input: assume square grayscale
            side = int(x.shape[-1] ** 0.5)
            x = x.reshape((x.shape[0], side, side, 1))
        x = nn.relu(nn.Conv(32, (3, 3))(x))
        x = nn.avg_pool(x, (2, 2), (2, 2))
        x = nn.relu(nn.Conv(64, (3, 3))(x))
        x = nn.avg_pool(x, (2, 2), (2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128)(x))
        return nn.Dense(self.num_classes)(x)
