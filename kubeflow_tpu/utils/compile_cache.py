"""Persistent XLA compile cache + serialized executables — ONE config path.

Two layers of compile reuse, shared by serving cold-start (serving/aot.py)
and training gang-restart (train/trainer.py warm_start):

  1. The **persistent XLA compilation cache**: `enable_persistent_cache`
     points jax's backend-compile cache at a directory (thresholds zeroed —
     a restarted process must hit for EVERY executable, however small).
     A re-traced program whose HLO matches a cached entry skips the XLA
     compiler entirely; the `/jax/compilation_cache/cache_misses`
     monitoring counter (install_compile_listener / compile_counts) is the
     proof both the serving AOT tests and the `train_restart_warm`
     cpu-proxy gate assert on.
  2. **Serialized executables**: `save_executable` / `load_executable`
     persist a jitted program's COMPILED form (jax.experimental.
     serialize_executable) keyed by `executable_key(...)` — reloading
     skips trace AND compile, the strongest restart-warm path. Keys must
     cover everything that changes the program: model-config hash, mesh
     shape, batch shapes/dtypes, compute dtype, jax version.

Why restart-warm matters (ROADMAP item 5, papers 1909.09756 / 2011.03641):
every gang restart previously paid a full re-trace+recompile of the train
step — orchestration overhead capping goodput while the chips idle. With
the cache dir injected into pod env (ENV_COMPILE_CACHE_DIR, jobcontroller)
and surviving restarts, a restarted incarnation performs zero backend
compilations of the train step.

Process-global metrics land in /metrics as the kftpu_train_compile_*
families (observability.py); `reset_compile_metrics` is the test hook.
"""

from __future__ import annotations

import hashlib
import os
import threading
from pathlib import Path

from kubeflow_tpu.utils.envvars import ENV_COMPILE_CACHE_DIR

#: suffix of serialized-executable artifacts inside <cache_dir>/executables
EXECUTABLE_SUFFIX = ".kfexec"

#: size bound for the executables dir — the shared cache deliberately
#: survives restarts and nothing else ever deletes from it, so without a
#: cap a long-lived platform accumulates one artifact per distinct
#: (model, shape, dtype, knobs, jax version) forever. Oldest-mtime
#: artifacts are evicted after each save; reloads touch mtime, so the
#: sweep is LRU in practice. (The XLA persistent-cache entries beside it
#: are jax's own; bound those with jax's cache-size flags where needed.)
EXECUTABLE_DIR_MAX_BYTES = 2 << 30

_MU = threading.Lock()
#: process-global counters (kftpu_train_compile_* in /metrics). Backend
#: miss/request counts come from the jax monitoring listener; the
#: executable reload/save counts from load_/save_executable.
_METRICS = {
    "requests_total": 0,          # backend compiles that consulted the cache
    "backend_misses_total": 0,    # backend compiles the XLA compiler ran
    "executable_reloads_total": 0,  # deserialized pre-compiled executables
    "executable_saves_total": 0,    # executables serialized for later runs
}
_LISTENER_INSTALLED = False


def enable_persistent_cache(cache_dir: str | Path) -> None:
    """Point jax's persistent backend-compile cache at `cache_dir` and
    zero the size/time thresholds (the default thresholds skip caching
    cheap compiles — a restarted incarnation must hit the cache for EVERY
    executable, however small). Also installs the miss-counting listener
    so compile_counts() deltas are meaningful from the first compile.

    jax LATCHES the cache state at the first compile: a process that
    compiled anything before this call (e.g. a trainer whose init ran
    first) has the cache pinned "disabled/not initialized", and a later
    config update alone leaves every subsequent write silently skipped —
    reads would miss and NO miss event would ever fire, making a
    zero-miss assertion vacuously true. reset_cache() drops the latch so
    the next compile re-initializes against the directory just set."""
    import jax
    from jax.experimental.compilation_cache import (
        compilation_cache as jax_cc,
    )

    jax.config.update("jax_compilation_cache_dir", str(cache_dir))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    jax_cc.reset_cache()
    install_compile_listener()


def cache_dir_from_env(explicit: str = "") -> str:
    """The effective cache dir: an explicit config value wins, else the
    pod env contract (ENV_COMPILE_CACHE_DIR, injected by the
    jobcontroller), else "" (caching off)."""
    return explicit or os.environ.get(ENV_COMPILE_CACHE_DIR, "")


def install_compile_listener() -> None:
    """Count backend compile requests/misses process-globally via the
    jax.monitoring events the compilation cache emits. Idempotent; safe
    to call before any cache is enabled (events simply don't fire)."""
    global _LISTENER_INSTALLED
    with _MU:
        if _LISTENER_INSTALLED:
            return
        _LISTENER_INSTALLED = True
    import jax.monitoring as mon

    def _listener(event: str, **kwargs) -> None:
        if event == "/jax/compilation_cache/cache_misses":
            with _MU:
                _METRICS["backend_misses_total"] += 1
        elif event == "/jax/compilation_cache/compile_requests_use_cache":
            with _MU:
                _METRICS["requests_total"] += 1

    mon.register_event_listener(_listener)


def compile_counts() -> dict[str, int]:
    """Snapshot of the process-global counters — subtract two snapshots
    to get the misses/requests a code region caused (the zero-backend-
    compilations assertion pattern)."""
    with _MU:
        return dict(_METRICS)


def compile_metrics_snapshot() -> dict[str, int]:
    """Alias used by observability.render_metrics (kftpu_train_compile_*)."""
    return compile_counts()


def reset_compile_metrics() -> None:
    """Test hook: zero the counters (the listener stays installed)."""
    with _MU:
        for k in _METRICS:
            _METRICS[k] = 0


def executable_key(**parts) -> str:
    """Deterministic content key for a serialized executable. Callers pass
    everything that changes the compiled program (model-config hash, mesh
    shape, batch shapes/dtypes, compute dtype, optimizer knobs, fused step
    count); jax version and backend are always folded in — a cache dir
    shared across upgrades must never replay a stale binary."""
    import jax

    parts = dict(parts)
    parts["jax_version"] = jax.__version__
    parts["backend"] = jax.default_backend()
    blob = "\x1f".join(f"{k}={parts[k]!r}" for k in sorted(parts))
    return hashlib.sha256(blob.encode()).hexdigest()[:32]


def executable_path(cache_dir: str | Path, key: str) -> Path:
    return Path(cache_dir) / "executables" / f"{key}{EXECUTABLE_SUFFIX}"


def save_executable(cache_dir: str | Path, key: str, compiled) -> Path | None:
    """Serialize a compiled executable (jax.experimental
    .serialize_executable) under its key. Returns the path, or None when
    this jax cannot serialize (the persistent backend cache still covers
    the restart — degraded, not broken). Writes are atomic (tmp+rename)
    so a killed pod never leaves a torn artifact for the next one."""
    try:
        import pickle

        from jax.experimental.serialize_executable import serialize
    except ImportError:
        return None
    path = executable_path(cache_dir, key)
    try:
        payload, in_tree, out_tree = serialize(compiled)
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(path.suffix + f".tmp.{os.getpid()}")
        with open(tmp, "wb") as fh:
            pickle.dump((payload, in_tree, out_tree), fh)
        os.replace(tmp, path)
    except Exception:  # noqa: BLE001 — serialization support varies by
        # backend/version; a failed save must never fail training, and the
        # persistent backend cache above still makes the restart warm
        return None
    with _MU:
        _METRICS["executable_saves_total"] += 1
    _evict_lru(path.parent, keep=path)
    return path


def _evict_lru(exec_dir: Path,
               keep: Path | None = None,
               max_bytes: int | None = None) -> None:
    """Drop oldest-mtime executables until the dir fits the size bound
    (the entry just saved is never the victim). Best-effort: a racing
    pod deleting the same file is fine."""
    limit = EXECUTABLE_DIR_MAX_BYTES if max_bytes is None else max_bytes
    try:
        entries = []
        for p in exec_dir.glob(f"*{EXECUTABLE_SUFFIX}"):
            st = p.stat()
            entries.append((st.st_mtime, st.st_size, p))
        total = sum(size for _, size, _ in entries)
        for _, size, p in sorted(entries):
            if total <= limit:
                break
            if keep is not None and p == keep:
                continue
            p.unlink()
            total -= size
    except OSError:
        return


def load_executable(cache_dir: str | Path, key: str):
    """Deserialize a previously saved executable — trace AND compile are
    both skipped. Returns the loaded callable, or None when absent /
    unreadable / built by an incompatible jax (key covers version, but a
    torn write or backend drift still degrades gracefully to None)."""
    path = executable_path(cache_dir, key)
    if not path.exists():
        return None
    try:
        import pickle

        from jax.experimental.serialize_executable import (
            deserialize_and_load,
        )

        with open(path, "rb") as fh:
            payload, in_tree, out_tree = pickle.load(fh)
        loaded = deserialize_and_load(payload, in_tree, out_tree)
    except Exception:  # noqa: BLE001 — a corrupt artifact must degrade to
        # a normal (cache-warm) compile, never crash the incarnation
        try:
            path.unlink()  # quarantine-by-removal: don't retry it forever
        except OSError:
            pass
        return None
    try:
        os.utime(path)  # a hit is a use: keep it young for the LRU sweep
    except OSError:
        pass  # kftpu: allow=KFTPU-EXCEPT (best-effort mtime touch)
    with _MU:
        _METRICS["executable_reloads_total"] += 1
    return loaded
