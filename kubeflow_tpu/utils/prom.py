"""Prometheus histogram helpers — the ONE implementation of bucket
observation and exposition-format rendering shared by every /metrics
surface (controller reconcile latencies, serving request latencies)."""

from __future__ import annotations


def observe(buckets: tuple[float, ...], counts: list[int],
            value: float) -> None:
    """Record one observation into per-bucket counts (+Inf in the last
    slot). Caller owns locking."""
    for i, le in enumerate(buckets):
        if value <= le:
            counts[i] += 1
            return
    counts[-1] += 1


def render_histogram(lines: list[str], name: str,
                     buckets: tuple[float, ...], counts: list[int],
                     total_sum: float, labels: str = "",
                     emit_type: bool = True) -> None:
    """Append exposition-format histogram lines: cumulative le buckets
    (+Inf == _count by construction), _sum, _count. `labels` is a
    pre-rendered 'key="value",' prefix for per-series histograms."""
    if emit_type:
        lines.append(f"# TYPE {name} histogram")
    cum = 0
    for le, n in zip(buckets, counts):
        cum += n
        lines.append(f'{name}_bucket{{{labels}le="{le}"}} {cum}')
    cum += counts[-1]
    lines.append(f'{name}_bucket{{{labels}le="+Inf"}} {cum}')
    lines.append(f"{name}_sum{{{labels[:-1]}}} {total_sum:.6f}"
                 if labels else f"{name}_sum {total_sum:.6f}")
    lines.append(f"{name}_count{{{labels[:-1]}}} {cum}"
                 if labels else f"{name}_count {cum}")
