"""Prometheus histogram helpers — the ONE implementation of bucket
observation and exposition-format rendering shared by every /metrics
surface (controller reconcile latencies, serving request latencies)."""

from __future__ import annotations


def observe(buckets: tuple[float, ...], counts: list[int],
            value: float) -> None:
    """Record one observation into per-bucket counts (+Inf in the last
    slot). Caller owns locking."""
    for i, le in enumerate(buckets):
        if value <= le:
            counts[i] += 1
            return
    counts[-1] += 1


class Exposition:
    """Exposition-format builder with ONE HELP/TYPE declaration path.

    Repeated `# TYPE` lines for the same family are invalid exposition
    format (real scrapers reject them); every per-sample emitter used to
    hand-roll its own declaration, which made that violation one labeled
    loop away. Here the first emission for a family declares it and every
    later sample just appends — so multi-sample families (per-kind
    gauges, per-controller quantiles) are correct by construction.
    """

    def __init__(self):
        self.lines: list[str] = []
        self._declared: set[str] = set()

    def declare(self, name: str, type_: str, help_: str = "") -> None:
        """Emit the HELP/TYPE header for a family exactly once — callable
        directly for families whose samples are conditional but whose
        presence in the exposition is pinned (golden stability)."""
        if name in self._declared:
            return
        self._declared.add(name)
        if help_:
            self.lines.append(f"# HELP {name} {help_}")
        self.lines.append(f"# TYPE {name} {type_}")

    def counter(self, name: str, value, help_: str = "",
                labels: str = "") -> None:
        self.declare(name, "counter", help_)
        self.lines.append(f"{name}{labels} {value}")

    def gauge(self, name: str, value, help_: str = "",
              labels: str = "") -> None:
        self.declare(name, "gauge", help_)
        self.lines.append(f"{name}{labels} {value}")

    def histogram(self, name: str, buckets: tuple[float, ...],
                  counts: list[int], total_sum: float,
                  labels: str = "", help_: str = "") -> None:
        self.declare(name, "histogram", help_)
        render_histogram(self.lines, name, buckets, counts, total_sum,
                         labels=labels, emit_type=False)

    def text(self) -> str:
        return "\n".join(self.lines) + ("\n" if self.lines else "")


def render_histogram(lines: list[str], name: str,
                     buckets: tuple[float, ...], counts: list[int],
                     total_sum: float, labels: str = "",
                     emit_type: bool = True) -> None:
    """Append exposition-format histogram lines: cumulative le buckets
    (+Inf == _count by construction), _sum, _count. `labels` is a
    pre-rendered 'key="value",' prefix for per-series histograms."""
    if emit_type:
        lines.append(f"# TYPE {name} histogram")
    cum = 0
    for le, n in zip(buckets, counts):
        cum += n
        lines.append(f'{name}_bucket{{{labels}le="{le}"}} {cum}')
    cum += counts[-1]
    lines.append(f'{name}_bucket{{{labels}le="+Inf"}} {cum}')
    lines.append(f"{name}_sum{{{labels[:-1]}}} {total_sum:.6f}"
                 if labels else f"{name}_sum {total_sum:.6f}")
    lines.append(f"{name}_count{{{labels[:-1]}}} {cum}"
                 if labels else f"{name}_count {cum}")
