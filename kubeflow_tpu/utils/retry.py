"""Shared retry/backoff policy — exponential backoff, full jitter, deadline.

Every retry loop in the platform (optimistic-concurrency writes, cold-start
polling, status waits, gang-restart requeues) consumes ONE policy shape
instead of hand-rolling `for _ in range(n): ... time.sleep(k)`. The jitter
formula is AWS "full jitter" (sleep = U(0, min(cap, base * mult^attempt)));
`jitter` scales it continuously down to 0 for deterministic schedules.

Three consumption modes:

  - ``policy.delay_for(attempt, rng)``   — pure: compute the Nth delay
  - ``retry_call(fn, ...)``              — retry `fn` on listed exceptions
  - ``poll_until(fn, ...)``              — poll `fn` until it returns non-None
  - ``with_conflict_retry(fn)``          — retry a read-modify-write attempt
                                           on ConflictError (k8s 409 analogue)
  - ``backoff_sleep(policy, attempt)``   — one jittered, deadline-clamped
                                           pause inside a hand-written loop
  - ``hinted_sleep(hint_s, ...)``        — honor a server's Retry-After hint
                                           within the caller's budget

Chaos drills (kubeflow_tpu/chaos.py) pass a seeded ``random.Random`` as
`rng` so injected-fault schedules stay reproducible; production callers
default to the module-level generator.
"""

from __future__ import annotations

import math
import random
import time
from dataclasses import dataclass
from typing import Any, Callable


@dataclass(frozen=True)
class BackoffPolicy:
    """Exponential backoff with bounded full jitter and optional budgets.

    base_s / max_s / multiplier: classic exponential ramp, capped.
    jitter: 0.0 = deterministic cap, 1.0 = full jitter U(0, cap).
    max_attempts: total call budget for retry_call (None = unbounded).
    deadline_s: wall-clock budget from the first attempt (None = unbounded).
    """

    base_s: float = 0.02
    max_s: float = 1.0
    multiplier: float = 2.0
    jitter: float = 1.0
    max_attempts: int | None = None
    deadline_s: float | None = None

    def cap_for(self, attempt: int) -> float:
        """Un-jittered delay ceiling for the Nth retry (attempt 0 = first).

        The ramp saturates at max_s; the exponent is clamped BEFORE
        evaluation because `multiplier ** attempt` overflows a float for
        attempt ~1024 — and long-lived poll loops (log follow, watch
        reconnect) legitimately reach unbounded attempt counts."""
        if self.base_s <= 0.0:
            return 0.0  # degenerate no-wait policy (and log() needs base > 0)
        if self.base_s >= self.max_s:
            return self.max_s
        if self.multiplier > 1.0:
            # smallest n with base * m**n >= max: beyond it, the answer
            # is max_s without ever computing the power
            saturated = math.log(self.max_s / self.base_s, self.multiplier)
            if attempt >= saturated:
                return self.max_s
        return min(self.max_s, self.base_s * self.multiplier ** attempt)

    def delay_for(self, attempt: int, rng: random.Random | None = None) -> float:
        cap = self.cap_for(attempt)
        if self.jitter <= 0.0:
            return cap
        r = rng if rng is not None else random
        return cap * (1.0 - self.jitter) + r.uniform(0.0, cap * self.jitter)


#: optimistic-concurrency writes: fast first retry, bounded total attempts
#: (a conflict storm must surface as an error, not an infinite spin)
CONFLICT_POLICY = BackoffPolicy(
    base_s=0.005, max_s=0.2, multiplier=2.0, jitter=1.0, max_attempts=12
)

#: status polling (job conditions, ISVC readiness, experiment completion):
#: starts responsive, backs off to a gentle steady-state poll. Half jitter
#: keeps a fleet of waiters from phase-locking on the store's write lock.
POLL_POLICY = BackoffPolicy(
    base_s=0.02, max_s=0.25, multiplier=2.0, jitter=0.5
)


class Deadline:
    """Monotonic-clock deadline; `None` timeout means 'never expires'."""

    def __init__(self, timeout_s: float | None):
        self.timeout_s = timeout_s
        self._t0 = time.monotonic()

    def expired(self) -> bool:
        rem = self.remaining()
        return rem is not None and rem <= 0.0

    def remaining(self, floor: float | None = None) -> float | None:
        """Seconds left (clamped at `floor` if given); None = unbounded."""
        if self.timeout_s is None:
            return None
        rem = self.timeout_s - (time.monotonic() - self._t0)
        return rem if floor is None else max(floor, rem)


def retry_call(
    fn: Callable[[], Any],
    *,
    policy: BackoffPolicy = CONFLICT_POLICY,
    retry_on: tuple[type[BaseException], ...] = (Exception,),
    rng: random.Random | None = None,
) -> Any:
    """Call `fn` until it returns, retrying `retry_on` exceptions under
    `policy`. Exhausting max_attempts — or a deadline_s the next sleep
    would overshoot — re-raises the LAST exception: the retry layer must
    never replace the real failure."""
    deadline = Deadline(policy.deadline_s)
    attempt = 0
    while True:
        try:
            return fn()
        except retry_on:
            if policy.max_attempts is not None and attempt + 1 >= policy.max_attempts:
                raise
            delay = policy.delay_for(attempt, rng)
            rem = deadline.remaining()
            if rem is not None and delay >= rem:
                raise
            time.sleep(delay)
            attempt += 1


def poll_until(
    fn: Callable[[], Any],
    *,
    timeout_s: float | None,
    policy: BackoffPolicy = POLL_POLICY,
    rng: random.Random | None = None,
    describe: str = "condition",
) -> Any:
    """Poll `fn` until it returns non-None; jittered-backoff sleeps between
    polls; TimeoutError after `timeout_s`. The final poll happens AT the
    deadline, so a condition that became true during the last sleep is
    still returned rather than timed out."""
    deadline = Deadline(timeout_s)
    attempt = 0
    while True:
        out = fn()
        if out is not None:
            return out
        rem = deadline.remaining()
        if rem is not None and rem <= 0.0:
            raise TimeoutError(f"{describe} not met within {timeout_s}s")
        delay = policy.delay_for(attempt, rng)
        if rem is not None:
            delay = min(delay, rem)
        time.sleep(max(delay, 0.0))
        attempt += 1


def backoff_sleep(
    policy: BackoffPolicy,
    attempt: int,
    *,
    deadline: Deadline | None = None,
    rng: random.Random | None = None,
) -> float:
    """The ONE sanctioned way to pause inside a hand-written poll loop
    (loops that can't be shaped as poll_until because each iteration does
    real work, e.g. streaming log bytes): sleeps the policy's jittered
    delay for `attempt`, clamped to the deadline's remaining budget.
    Returns the seconds actually slept (0.0 when the deadline is already
    spent). The KFTPU-SLEEP lint rule exists because every naked
    `time.sleep(k)` in a reconcile path eventually phase-locked a fleet
    or overshot a budget."""
    delay = policy.delay_for(attempt, rng)
    if deadline is not None:
        rem = deadline.remaining()
        if rem is not None:
            delay = min(delay, max(rem, 0.0))
    if delay > 0.0:
        time.sleep(delay)
    return delay


def hinted_sleep(
    hint_s: float,
    *,
    cap_s: float | None = None,
    deadline: Deadline | None = None,
) -> bool:
    """Honor a server-advertised wait (Retry-After) within the caller's
    budget: sleep min(hint, cap) unless that would overshoot the
    deadline. Returns True when the wait was taken (caller re-dials) and
    False when it would overshoot (caller surfaces the error now instead
    of parking past its own budget)."""
    delay = max(hint_s, 0.0)
    if cap_s is not None:
        delay = min(delay, cap_s)
    if deadline is not None:
        rem = deadline.remaining()
        if rem is not None and delay >= rem:
            return False
    time.sleep(delay)
    return True


def with_conflict_retry(
    fn: Callable[[], Any],
    *,
    policy: BackoffPolicy = CONFLICT_POLICY,
    rng: random.Random | None = None,
) -> Any:
    """Run one read-modify-write attempt (`fn` reads a fresh deep snapshot,
    mutates, writes back) and retry it on ConflictError. This is the ONE
    sanctioned conflict loop — see FakeCluster.read_modify_write, which
    delegates here. Budget exhaustion re-raises the last ConflictError."""
    from kubeflow_tpu.controller.fakecluster import ConflictError

    return retry_call(fn, policy=policy, retry_on=(ConflictError,), rng=rng)


# --------------------------------------------------- load-scaled budgets

_LOAD_FACTOR: float | None = None


def sched_load_factor(refresh: bool = False) -> float:
    """Observed scheduler-latency multiplier, cached per process: the
    median overshoot of a few short timed waits (an Event.wait(5ms) on
    an idle box returns in ~5ms; on a saturated core it returns whenever
    the scheduler gets around to it). Timing-sensitive TEST assertions
    multiply their wall-clock budgets by this (``load_scaled``) so a
    loaded CI box stretches the budget instead of flaking the drill —
    while a genuine hang still fails, because the factor is clamped to
    [1, 16] and measured, not guessed (the VERDICT weak-#6 deflake)."""
    global _LOAD_FACTOR
    if _LOAD_FACTOR is not None and not refresh:
        return _LOAD_FACTOR
    import threading

    ev = threading.Event()
    nominal = 0.005
    overshoot = []
    for _ in range(5):
        t0 = time.perf_counter()
        ev.wait(nominal)
        overshoot.append((time.perf_counter() - t0) / nominal)
    overshoot.sort()
    _LOAD_FACTOR = max(1.0, min(overshoot[len(overshoot) // 2], 16.0))
    return _LOAD_FACTOR


def load_scaled(budget_s: float) -> float:
    """A wall-clock assertion budget stretched by the observed scheduler
    load (``sched_load_factor``). Use for UPPER bounds in drill
    assertions ("the deadline bounded the hold") — never for lower
    bounds, which prove a wait actually happened and must stay exact."""
    return budget_s * sched_load_factor()
