"""Shared utilities: device selection, logging, timing."""

from kubeflow_tpu.utils.device import select_device

__all__ = ["select_device"]
