"""Shared utilities: device selection, retry/backoff policy, logging."""

from kubeflow_tpu.utils.device import select_device
from kubeflow_tpu.utils.retry import (
    BackoffPolicy,
    Deadline,
    backoff_sleep,
    hinted_sleep,
    poll_until,
    retry_call,
    with_conflict_retry,
)

__all__ = [
    "select_device",
    "BackoffPolicy",
    "Deadline",
    "backoff_sleep",
    "hinted_sleep",
    "poll_until",
    "retry_call",
    "with_conflict_retry",
]
