"""Device selection — the north star's "select device via a single flag".

The reference platform selects hardware by pod resource requests
(`nvidia.com/gpu`, `google.com/tpu`); here a single `--device=tpu|cpu` flag
picks the JAX platform. Must be called before any jax import touches a
backend, hence the env-var approach.
"""

from __future__ import annotations

import os


def select_device(device: str) -> str:
    """Pin the JAX platform. Call before the first jax array op.

    device: "tpu" | "cpu" | "auto". Returns the platform string chosen.
    """
    if device == "auto":
        return os.environ.get("JAX_PLATFORMS", "") or "auto"
    if device not in ("tpu", "cpu"):
        raise ValueError(f"unknown device {device!r}; expected tpu|cpu|auto")

    platform = device
    if device == "tpu":
        # TPU may be served by an out-of-tree PJRT plugin under another
        # platform name (e.g. "axon" in this environment); respect it.
        env = os.environ.get("JAX_PLATFORMS", "")
        for cand in env.split(","):
            if cand and cand != "cpu":
                platform = cand
                break

    import jax  # local import: reading jax.config is safe pre-backend

    if jax.config.jax_platforms != platform:
        try:
            jax.config.update("jax_platforms", platform)
        except RuntimeError:
            # backend already initialized; env var is the only lever left
            os.environ["JAX_PLATFORMS"] = platform
    return platform
