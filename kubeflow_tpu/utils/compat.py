"""Version-portable jax shims — ONE place where API drift is absorbed.

The repo targets a wide jax range (the CI image ships 0.4.37; TPU images
ship 0.5-0.7): ``jax.set_mesh`` only exists from ~0.6, its predecessor
``jax.sharding.use_mesh`` from ~0.5, and on 0.4.x the ambient mesh is the
``with mesh:`` context manager. Every Trainer.fit path previously called
``jax.set_mesh`` directly and failed WHOLESALE on 0.4.37 — resolve the
fallback chain here, once, at import of the call site.

Callers that cannot run under ANY resolution should skip with the
carried reason instead of raising:

    from kubeflow_tpu.utils.compat import MeshUnavailable, set_mesh
    try:
        with set_mesh(mesh):
            ...
    except MeshUnavailable as e:
        pytest.skip(str(e))  # or emit a structured-skip record
"""

from __future__ import annotations


class MeshUnavailable(RuntimeError):
    """No ambient-mesh mechanism exists on this jax — carry the reason so
    callers can skip-with-reason instead of crashing wholesale."""


def _resolve():
    import jax

    if hasattr(jax, "set_mesh"):
        return jax.set_mesh, "jax.set_mesh"
    use_mesh = getattr(jax.sharding, "use_mesh", None)
    if use_mesh is not None:
        return use_mesh, "jax.sharding.use_mesh"

    # 0.4.x: Mesh IS a context manager — entering it sets the legacy
    # ambient (physical) mesh, which is what with_sharding_constraint /
    # pjit-with-PartitionSpec consulted before the set_mesh API existed
    def _legacy(mesh):
        if hasattr(mesh, "__enter__"):
            return mesh
        raise MeshUnavailable(
            "this jax has no jax.set_mesh / jax.sharding.use_mesh and "
            f"{type(mesh).__name__} is not a context manager "
            "(AbstractMesh on 0.4.x?) — ambient mesh unavailable")

    return _legacy, "legacy `with mesh:`"


_SET_MESH, MESH_IMPL = _resolve()


def set_mesh(mesh):
    """Context manager making ``mesh`` ambient, on any supported jax.
    Raises MeshUnavailable (with the reason) when this jax has no
    equivalent for the given mesh object."""
    return _SET_MESH(mesh)


def _resolve_get_mesh():
    import jax

    getter = getattr(jax.sharding, "get_abstract_mesh", None)
    if getter is not None:
        return getter, "jax.sharding.get_abstract_mesh"

    # 0.4.x: the ambient mesh lives on the thread-resources env (what the
    # legacy `with mesh:` context sets). The physical Mesh object carries
    # the same `.empty` / `.shape` surface the callers consult, so it
    # stands in for the AbstractMesh directly.
    def _legacy():
        from jax._src import mesh as mesh_lib

        return mesh_lib.thread_resources.env.physical_mesh

    return _legacy, "legacy thread_resources physical mesh"


_GET_MESH, GET_MESH_IMPL = _resolve_get_mesh()


def get_abstract_mesh():
    """The ambient mesh (``.empty`` when none is set), on any supported
    jax. On 0.4.x this is the thread-local physical mesh the legacy
    ``with mesh:`` context manager sets — same ``.empty``/``.shape``
    surface, so sharding-aware call sites (models/bert.constrain,
    parallel/*) run unmodified on every jax this repo supports. Before
    this shim, every GPT/BERT forward pass — and with it the whole
    serving stack — failed wholesale on jax 0.4.37."""
    return _GET_MESH()


def promote_dtype(module, *args, dtype=None, inexact=True):
    """flax's dtype-promotion helper on any supported flax: newer flax
    exposes it as a Module METHOD (module.promote_dtype), this repo's
    floor (0.10.0) only as flax.linen.dtypes.promote_dtype. Before this
    shim every GPT/BERT forward under an ACTIVE mesh — i.e. every
    Trainer-driven step, which always runs inside compat.set_mesh —
    failed wholesale on VocabEmbed's TP lookup path."""
    fn = getattr(module, "promote_dtype", None)
    if fn is not None:
        return fn(*args, dtype=dtype, inexact=inexact)
    from flax.linen.dtypes import promote_dtype as _promote

    return _promote(*args, dtype=dtype, inexact=inexact)
