"""The KFTPU_* environment-variable registry — ONE place where every
platform env-var name is spelled out.

The pod env contract crosses a process boundary: the controller side
*injects* these variables (envcontract.synthesize_env, jobcontroller pod
creation, chaos.pod_env) and the worker side *reads* them (trainer,
tracing.init_worker_from_env, health.HeartbeatWriter.from_env). A typo'd
or renamed literal on either side doesn't fail loudly — the reader just
sees "unset" and silently degrades (no heartbeats, no trace flush, no
profile). Centralizing the names makes injector/reader drift impossible,
and the KFTPU-ENV lint rule (kubeflow_tpu/analysis) enforces that no
module outside this registry spells a ``KFTPU_*`` string literal.

Import the constant, never inline the string:

    from kubeflow_tpu.utils.envvars import ENV_TRACE_DIR
    os.environ.get(ENV_TRACE_DIR, "")

Stdlib-free on purpose: imported by the earliest-loading modules
(tracing, health) without dragging anything in.
"""

from __future__ import annotations

# --------------------------------------------------------------- pod contract

#: directory worker processes flush their trace spans into
ENV_TRACE_DIR = "KFTPU_TRACE_DIR"
#: parent SpanContext carried into a pod ("traceid-spanid")
ENV_TRACEPARENT = "KFTPU_TRACEPARENT"
#: per-incarnation heartbeat file one worker writes (liveness lease)
ENV_HEARTBEAT_FILE = "KFTPU_HEARTBEAT_FILE"
#: chaos carrier for seeded heartbeat-write drops ("rate:seed:count")
ENV_HEARTBEAT_DROP = "KFTPU_HB_DROP"
#: jax.profiler trace output dir (per-process; JAXJob profile toggle)
ENV_PROFILE_DIR = "KFTPU_PROFILE_DIR"
#: persistent XLA compile-cache directory (utils/compile_cache.py). The
#: jobcontroller injects a per-platform path that SURVIVES gang restarts,
#: so a restarted incarnation replays its train-step executables from the
#: cache instead of paying a full re-trace+recompile (docs/perf.md)
ENV_COMPILE_CACHE_DIR = "KFTPU_COMPILE_CACHE_DIR"
#: tfevents scalar output dir for TensorBoard
ENV_EVENT_DIR = "KFTPU_EVENT_DIR"
#: AF_UNIX socket path a serving pod worker binds (podworker/podclient)
ENV_POD_SOCKET = "KFTPU_POD_SOCKET"
#: the pod worker's replica name (trace service, heartbeat identity)
ENV_POD_NAME = "KFTPU_POD_NAME"
#: path to the JSON engine spec a pod worker builds its batcher from
ENV_POD_SPEC = "KFTPU_POD_SPEC"
#: wire transport a pod worker serves on: "unix" (default) or "tcp"
ENV_POD_TRANSPORT = "KFTPU_POD_TRANSPORT"
#: file a TCP pod worker atomically writes its bound 127.0.0.1 port to
#: (the controller polls it the way it polls the AF_UNIX socket path)
ENV_POD_PORT_FILE = "KFTPU_POD_NET_PORT_FILE"

# ------------------------------------------------------------- platform state

#: root for controller-side state (hostfiles, heartbeats, pod logs)
ENV_STATE_DIR = "KFTPU_STATE_DIR"
#: PVC mount root: pvc://volume/sub -> $KFTPU_PVC_ROOT/volume/sub
ENV_PVC_ROOT = "KFTPU_PVC_ROOT"
#: file-backed object-store emulator root (gs://, s3:// resolve under it)
ENV_OBJECT_STORE_EMULATOR = "KFTPU_OBJECT_STORE_EMULATOR"

# ----------------------------------------------------------- developer tools

#: "1" arms the runtime lock-order/race detector (analysis/lockcheck.py)
ENV_LOCKCHECK = "KFTPU_LOCKCHECK"
#: "1" regenerates the lint baseline instead of failing on findings
ENV_UPDATE_LINT_BASELINE = "KFTPU_UPDATE_LINT_BASELINE"
#: "1" regenerates golden files (metrics exposition) instead of diffing
ENV_UPDATE_GOLDEN = "KFTPU_UPDATE_GOLDEN"
#: "1" regenerates the CPU-proxy perf budgets (tests/golden/
#: prof_budgets.json) instead of gating against them (docs/profiling.md)
ENV_UPDATE_PROF_BUDGETS = "KFTPU_UPDATE_PROF_BUDGETS"
#: test-only chaos hook for the CPU-proxy perf gate: "phase:N[,phase:N]"
#: repeats a phase's deterministic work N times (profiling/cpu_proxy.py)
ENV_PROF_CHAOS = "KFTPU_PROF_CHAOS"
#: exhaustive-BFS depth bound for the protocol model checker
#: (analysis/protocheck — docs/analysis.md "Protocol model checking")
ENV_MODELCHECK_DEPTH = "KFTPU_MODELCHECK_DEPTH"
#: seed for the random-walk frontier the model checker runs past the
#: exhaustive bound (analysis/protocheck)
ENV_MODELCHECK_SEED = "KFTPU_MODELCHECK_SEED"
#: JSONL path the wire/KV/ledger protocol event-log hooks append to when
#: armed (off when unset; analysis/protocheck conformance checking)
ENV_PROTOLOG = "KFTPU_PROTOLOG"

# ------------------------------------------------------------ chip scheduler

#: chips per slice in the shared chip ledger's inventory — the slice-
#: aware bin-packing granularity (scheduler/chipsched.py; Platform
#: construction reads it, docs/scheduler.md)
ENV_SCHED_CHIPS_PER_SLICE = "KFTPU_SCHED_CHIPS_PER_SLICE"
#: Retry-After hint (seconds) a chip-claim deny carries back to the
#: caller (the activator's Retry-After idiom, scheduler edition)
ENV_SCHED_RETRY_AFTER_S = "KFTPU_SCHED_RETRY_AFTER_S"

# ------------------------------------------------------------ SLO monitoring

#: sampling-tick interval in seconds for the SLO monitor's background
#: scrape of the kftpu_* families (Platform.start_slo; docs/slo.md)
ENV_SLO_TICK_S = "KFTPU_SLO_TICK_S"
#: per-series ring capacity of the SLO monitor's time-series store
#: (monitoring/tsdb.py — samples past it evict oldest, counted)
ENV_SLO_CAPACITY = "KFTPU_SLO_CAPACITY"

#: every name defined above, for tooling that wants the full contract
ALL_ENV_VARS = tuple(
    v for k, v in sorted(globals().items())
    if k.startswith("ENV_") and isinstance(v, str)
)
