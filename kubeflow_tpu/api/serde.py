"""Dict/YAML round-trip for spec dataclasses.

Serialized form uses camelCase keys + kind/apiVersion envelope so manifests
look like the reference's CR YAML (samples/ fixtures double as docs + tests).
"""

from __future__ import annotations

import dataclasses
import enum
import types
import typing
from typing import Any, get_args, get_origin, get_type_hints

import yaml

from kubeflow_tpu.api.jobs import JobKind, TrainJob, job_class_for_kind


def _camel(s: str) -> str:
    head, *rest = s.split("_")
    return head + "".join(w.capitalize() for w in rest)


def _snake(s: str) -> str:
    out = []
    for ch in s:
        if ch.isupper():
            out.append("_")
            out.append(ch.lower())
        else:
            out.append(ch)
    return "".join(out)


def to_dict(obj: Any) -> Any:
    """Dataclass -> plain dict with camelCase keys; drops empty/None fields."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for f in dataclasses.fields(obj):
            v = to_dict(getattr(obj, f.name))
            if v is None or v == {} or v == [] or v == "":
                continue
            out[_camel(f.name)] = v
        return out
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, dict):
        return {k: to_dict(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_dict(v) for v in obj]
    return obj


def _from_dict(cls: type, data: Any) -> Any:
    if data is None:
        return None
    origin = get_origin(cls)
    if origin is typing.Union or origin is types.UnionType:
        args = [a for a in get_args(cls) if a is not type(None)]
        if not args:
            return data
        return _from_dict(args[0], data)
    if dataclasses.is_dataclass(cls):
        hints = get_type_hints(cls)
        kwargs = {}
        by_camel = {_camel(f.name): f.name for f in dataclasses.fields(cls)}
        for key, val in (data or {}).items():
            fname = by_camel.get(key, _snake(key))
            if fname not in hints:
                continue  # forward-compat: ignore unknown fields like the apiserver
            kwargs[fname] = _from_dict(hints[fname], val)
        return cls(**kwargs)
    if origin is dict:
        _, vt = get_args(cls)
        return {k: _from_dict(vt, v) for k, v in (data or {}).items()}
    if origin in (list, tuple):
        (vt,) = get_args(cls) or (Any,)
        return [_from_dict(vt, v) for v in (data or [])]
    if isinstance(cls, type) and issubclass(cls, enum.Enum):
        return cls(data)
    return data


# Manifest `kind:` string -> API/store bucket. Shared routing table for the
# apiserver (POST dispatch) and remote clients (apply), so they cannot drift.
MANIFEST_KINDS = {
    "JAXJob": "jobs", "TFJob": "jobs", "PyTorchJob": "jobs", "MPIJob": "jobs",
    "XGBoostJob": "jobs", "PaddleJob": "jobs", "MXJob": "jobs",
    "Experiment": "experiments",
    "InferenceService": "inferenceservices",
    "PodDefault": "poddefaults",
    "Profile": "profiles",
    "Tensorboard": "tensorboards",
    "PipelineRun": "pipelineruns",
    "Notebook": "notebooks",
    "PVCViewer": "pvcviewers",
    "AccessBinding": "bindings",
}


def job_to_dict(job: TrainJob) -> dict:
    d = to_dict(job)
    d.pop("kind", None)
    d.pop("apiVersion", None)
    # A never-reconciled status serializes to noise ({restartCount: 0}); drop it
    # so spec manifests are deterministic golden files.
    if not job.status.conditions and job.status.start_time is None:
        d.pop("status", None)
    # JAX-only spec fields must not leak into other kinds' manifests
    # (migration parity: a TFJob CR has no coordinatorPort/numSlices).
    if job.kind != JobKind.JAX and "spec" in d:
        d["spec"].pop("coordinatorPort", None)
        d["spec"].pop("numSlices", None)
    return {"apiVersion": job.api_version, "kind": job.kind.value, **d}


def job_to_yaml(job: TrainJob) -> str:
    return yaml.safe_dump(job_to_dict(job), sort_keys=False)


def job_from_dict(data: dict) -> TrainJob:
    kind = JobKind(data["kind"])
    cls = job_class_for_kind(kind)
    body = {k: v for k, v in data.items() if k not in ("kind", "apiVersion")}
    job = _from_dict(cls, body)
    job.kind = kind
    return job


def job_from_yaml(text: str) -> TrainJob:
    return job_from_dict(yaml.safe_load(text))
