"""Typed spec layer — the CRD-equivalent API surface.

Reference parity: training-operator pkg/apis/kubeflow.org/v1 (common_types.go,
tfjob_types.go, pytorchjob_types.go, mpijob_types.go, ...) — unverified cites,
see SURVEY.md §0. Specs are plain dataclasses with dict/YAML round-trip and a
validation pass equivalent to the reference's admission webhooks.
"""

from kubeflow_tpu.api.common import (
    CleanPodPolicy,
    ElasticPolicy,
    JobCondition,
    JobConditionType,
    JobStatus,
    ObjectMeta,
    ReplicaSpec,
    ReplicaStatus,
    RestartPolicy,
    RunPolicy,
    SchedulingPolicy,
    ContainerSpec,
    PodTemplateSpec,
)
from kubeflow_tpu.api.jobs import (
    JAXJob,
    JAXJobSpec,
    JobKind,
    MPIJob,
    PyTorchJob,
    TFJob,
    TrainJob,
    REPLICA_WORKER,
    REPLICA_CHIEF,
    REPLICA_PS,
    REPLICA_MASTER,
    REPLICA_LAUNCHER,
    REPLICA_SCHEDULER,
    REPLICA_SERVER,
    MXJob,
)
from kubeflow_tpu.api.validation import ValidationError, validate_job

__all__ = [
    "CleanPodPolicy",
    "ContainerSpec",
    "ElasticPolicy",
    "JAXJob",
    "JAXJobSpec",
    "JobCondition",
    "JobConditionType",
    "JobKind",
    "JobStatus",
    "MPIJob",
    "ObjectMeta",
    "PodTemplateSpec",
    "PyTorchJob",
    "ReplicaSpec",
    "ReplicaStatus",
    "RestartPolicy",
    "RunPolicy",
    "SchedulingPolicy",
    "TFJob",
    "TrainJob",
    "ValidationError",
    "validate_job",
    "REPLICA_WORKER",
    "REPLICA_CHIEF",
    "REPLICA_PS",
    "REPLICA_MASTER",
    "REPLICA_LAUNCHER",
    "REPLICA_SCHEDULER",
    "REPLICA_SERVER",
    "MXJob",
]
