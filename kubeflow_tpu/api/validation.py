"""Admission validation — the webhook analogue.

Reference parity: training-operator pkg/webhooks/ validating webhooks
(replica sanity, port presence, elastic bounds — unverified, SURVEY.md §2.1).
Pure functions: given a job, raise ValidationError or return normalized job.
"""

from __future__ import annotations

import re

from kubeflow_tpu.api.common import RestartPolicy
from kubeflow_tpu.api.jobs import (
    JobKind,
    REPLICA_CHIEF,
    REPLICA_LAUNCHER,
    REPLICA_MASTER,
    REPLICA_PS,
    REPLICA_SCHEDULER,
    REPLICA_SERVER,
    REPLICA_WORKER,
    REPLICA_EVALUATOR,
    TrainJob,
)

# RFC-1123 subdomain, as kube-apiserver enforces on object names.
_NAME_RE = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")

VALID_REPLICA_TYPES = {
    JobKind.JAX: {REPLICA_WORKER},
    JobKind.TF: {REPLICA_CHIEF, REPLICA_WORKER, REPLICA_PS, REPLICA_MASTER, REPLICA_EVALUATOR},
    JobKind.PYTORCH: {REPLICA_MASTER, REPLICA_WORKER},
    JobKind.MPI: {REPLICA_LAUNCHER, REPLICA_WORKER},
    JobKind.XGBOOST: {REPLICA_MASTER, REPLICA_WORKER},
    JobKind.PADDLE: {REPLICA_MASTER, REPLICA_WORKER},
    JobKind.MXNET: {REPLICA_SCHEDULER, REPLICA_SERVER, REPLICA_WORKER},
}

# TPU slice topologies valid for v5e (chips = product; SURVEY.md §2.2: the
# slice is the atomic gang unit).
_TOPOLOGY_RE = re.compile(r"^\d+x\d+(x\d+)?$")


class ValidationError(ValueError):
    def __init__(self, field: str, message: str):
        self.field = field
        super().__init__(f"{field}: {message}")


def validate_job(job: TrainJob) -> TrainJob:
    """Validate + default a job spec in place. Raises ValidationError."""
    if not job.metadata.name:
        raise ValidationError("metadata.name", "name is required")
    if job.spec.success_policy not in ("", "AllWorkers"):
        raise ValidationError(
            "spec.successPolicy",
            f"{job.spec.success_policy!r} must be \"\" or \"AllWorkers\"",
        )
    if job.spec.success_policy == "AllWorkers":
        if job.kind == JobKind.MPI:
            raise ValidationError(
                "spec.successPolicy",
                "AllWorkers cannot apply to MPIJob: its workers idle "
                "(sshd analogue) and never exit, so the job could never "
                "succeed",
            )
        workers = job.spec.replica_specs.get("worker")
        if workers is None or workers.replicas == 0:
            raise ValidationError(
                "spec.successPolicy",
                "AllWorkers requires at least one worker replica (the "
                "controller would wait on workers that never exist)",
            )
    if not _NAME_RE.match(job.metadata.name) or len(job.metadata.name) > 63:
        raise ValidationError(
            "metadata.name",
            f"{job.metadata.name!r} must be a lowercase RFC-1123 label (<=63 chars)",
        )

    if not job.spec.replica_specs:
        raise ValidationError("spec.replicaSpecs", "at least one replica type required")

    allowed = VALID_REPLICA_TYPES[job.kind]
    for rtype, rs in job.spec.replica_specs.items():
        if rtype not in allowed:
            raise ValidationError(
                f"spec.replicaSpecs[{rtype}]",
                f"invalid replica type for {job.kind.value}; allowed: {sorted(allowed)}",
            )
        if rs.replicas < 0:
            raise ValidationError(
                f"spec.replicaSpecs[{rtype}].replicas", "must be >= 0"
            )
        if rs.restart_policy not in RestartPolicy:
            raise ValidationError(
                f"spec.replicaSpecs[{rtype}].restartPolicy", "invalid policy"
            )

    # Kind-specific topology rules (webhook parity).
    if job.kind == JobKind.TF:
        chief_like = sum(
            job.spec.replica_specs.get(t, None) is not None
            and job.spec.replica_specs[t].replicas
            for t in (REPLICA_CHIEF, REPLICA_MASTER)
        )
        if chief_like > 1:
            raise ValidationError(
                "spec.replicaSpecs", "TFJob may have at most one chief/master replica"
            )
    if job.kind in (JobKind.PYTORCH, JobKind.XGBOOST, JobKind.PADDLE):
        master = job.spec.replica_specs.get(REPLICA_MASTER)
        if master is not None and master.replicas > 1:
            raise ValidationError(
                f"spec.replicaSpecs[{REPLICA_MASTER}].replicas", "must be <= 1"
            )
    if job.kind == JobKind.MPI:
        launcher = job.spec.replica_specs.get(REPLICA_LAUNCHER)
        if launcher is None or launcher.replicas != 1:
            raise ValidationError(
                f"spec.replicaSpecs[{REPLICA_LAUNCHER}]", "MPIJob requires exactly one launcher"
            )
    if job.kind == JobKind.MXNET:
        sched = job.spec.replica_specs.get(REPLICA_SCHEDULER)
        if sched is None or sched.replicas != 1:
            raise ValidationError(
                f"spec.replicaSpecs[{REPLICA_SCHEDULER}]",
                "MXJob requires exactly one scheduler",
            )
    if job.kind == JobKind.JAX:
        workers = job.spec.replica_specs.get(REPLICA_WORKER)
        if workers is None or workers.replicas < 1:
            raise ValidationError(
                f"spec.replicaSpecs[{REPLICA_WORKER}]", "JAXJob requires >= 1 worker"
            )
        if not (0 < job.spec.coordinator_port < 65536):
            raise ValidationError("spec.coordinatorPort", "must be a valid port")
        if job.spec.num_slices < 1:
            raise ValidationError("spec.numSlices", "must be >= 1")
        if workers.replicas % job.spec.num_slices != 0:
            raise ValidationError(
                "spec.numSlices",
                f"worker count {workers.replicas} must be divisible by "
                f"numSlices {job.spec.num_slices} (slices are equal-sized)",
            )

    rp = job.spec.run_policy
    if rp.backoff_limit < 0:
        raise ValidationError("spec.runPolicy.backoffLimit", "must be >= 0")
    if rp.ttl_seconds_after_finished is not None and rp.ttl_seconds_after_finished < 0:
        raise ValidationError("spec.runPolicy.ttlSecondsAfterFinished", "must be >= 0")
    if rp.active_deadline_seconds is not None and rp.active_deadline_seconds <= 0:
        raise ValidationError("spec.runPolicy.activeDeadlineSeconds", "must be > 0")

    ep = rp.elastic_policy
    if ep is not None:
        if ep.min_replicas < 1 or ep.max_replicas < ep.min_replicas:
            raise ValidationError(
                "spec.runPolicy.elasticPolicy", "need 1 <= minReplicas <= maxReplicas"
            )
        if ep.max_restarts < 0:
            raise ValidationError("spec.runPolicy.elasticPolicy.maxRestarts", "must be >= 0")

    sp = rp.scheduling_policy
    if sp is not None:
        total = job.total_replicas()
        if sp.min_available is None:
            sp.min_available = total  # default: full gang (PodGroup minMember = Σreplicas)
        if sp.min_available > total:
            raise ValidationError(
                "spec.runPolicy.schedulingPolicy.minAvailable",
                f"{sp.min_available} exceeds total replicas {total}",
            )
        if sp.slice_topology and not _TOPOLOGY_RE.match(sp.slice_topology):
            raise ValidationError(
                "spec.runPolicy.schedulingPolicy.sliceTopology",
                f"{sp.slice_topology!r} is not like '2x4'",
            )
    return job
