"""Job kinds — the TrainJob family.

Reference parity: training-operator pkg/apis/kubeflow.org/v1/{tfjob_types.go,
pytorchjob_types.go, mpijob_types.go} (unverified, SURVEY.md §2.1).

The flagship kind is JAXJob: a gang of identical SPMD worker processes that
rendezvous through `jax.distributed.initialize`. TFJob/PyTorchJob/MPIJob specs
are kept for migration parity — their env contracts are synthesized exactly
(controller/envcontract.py), so a user moving off the reference finds the same
knobs, but the recommended path is JAXJob.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from kubeflow_tpu.api.common import (
    JobStatus,
    ObjectMeta,
    ReplicaSpec,
    RunPolicy,
)

# Canonical replica type names (label values under
# training.kubeflow.org/replica-type in the reference).
REPLICA_WORKER = "worker"
REPLICA_CHIEF = "chief"
REPLICA_PS = "ps"
REPLICA_MASTER = "master"
REPLICA_LAUNCHER = "launcher"
REPLICA_EVALUATOR = "evaluator"
REPLICA_SCHEDULER = "scheduler"  # MXNet
REPLICA_SERVER = "server"        # MXNet


class JobKind(str, enum.Enum):
    JAX = "JAXJob"
    TF = "TFJob"
    PYTORCH = "PyTorchJob"
    MPI = "MPIJob"
    XGBOOST = "XGBoostJob"
    PADDLE = "PaddleJob"
    MXNET = "MXJob"


# Default rendezvous ports, matching the reference's per-framework defaults.
DEFAULT_PORTS = {
    JobKind.JAX: 1234,       # jax.distributed coordinator
    JobKind.TF: 2222,        # tfjob default port
    JobKind.PYTORCH: 23456,  # MASTER_PORT default in pytorch envvar.go
    JobKind.MPI: 22,
    JobKind.XGBOOST: 9991,
    JobKind.PADDLE: 36543,
    JobKind.MXNET: 9091,     # mxnet scheduler (DMLC_PS_ROOT_PORT)
}

# Which replica type's completion decides job success, per kind
# (tfjob: chief, else worker-0 / master / launcher).
SUCCESS_REPLICA = {
    JobKind.JAX: REPLICA_WORKER,
    JobKind.TF: REPLICA_CHIEF,      # falls back to worker if no chief
    JobKind.PYTORCH: REPLICA_MASTER,
    JobKind.MPI: REPLICA_LAUNCHER,
    JobKind.XGBOOST: REPLICA_MASTER,
    JobKind.PADDLE: REPLICA_MASTER,
    JobKind.MXNET: REPLICA_WORKER,  # scheduler/server idle; workers decide
}


@dataclass
class JAXJobSpec:
    replica_specs: dict[str, ReplicaSpec] = field(default_factory=dict)
    run_policy: RunPolicy = field(default_factory=RunPolicy)
    # Port the worker-0 coordination service listens on.
    coordinator_port: int = DEFAULT_PORTS[JobKind.JAX]
    # Number of slices for multislice (DCN/megascale) jobs; 1 = single slice.
    num_slices: int = 1
    # First-class profiling toggle (SURVEY.md §5.1): when set, workers get
    # KFTPU_PROFILE_DIR and the in-tree trainer writes a jax.profiler
    # (perfetto-compatible) trace per process under it.
    profile_dir: str = ""
    # TFJob successPolicy parity: "" = the kind's success replica decides
    # (chief/master/launcher/worker-0; JAX jobs always need all workers);
    # "AllWorkers" = every worker AND the success replica must complete
    # (passive replicas — PS/scheduler/server — stay excluded: they never
    # exit and are reaped on success)
    success_policy: str = ""


@dataclass
class TrainJob:
    """Base class for every training job kind."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: JAXJobSpec = field(default_factory=JAXJobSpec)
    status: JobStatus = field(default_factory=JobStatus)

    kind: JobKind = JobKind.JAX
    api_version: str = "kubeflow-tpu.org/v1"

    # -- naming conventions (pkg/core/pod.go GenGeneralName analogues) --

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def replica_name(self, rtype: str, index: int) -> str:
        return f"{self.metadata.name}-{rtype}-{index}"

    def replica_hostname(self, rtype: str, index: int) -> str:
        """Stable DNS-style name for a replica — the headless-Service contract.
        In the fake cluster this resolves via the rendezvous registry."""
        return f"{self.replica_name(rtype, index)}.{self.metadata.name}.{self.metadata.namespace}"

    def total_replicas(self) -> int:
        return sum(rs.replicas for rs in self.spec.replica_specs.values())

    def labels(self, rtype: str | None = None, index: int | None = None) -> dict[str, str]:
        """Label conventions, mirroring training.kubeflow.org/* labels."""
        out = {
            "kubeflow-tpu.org/job-name": self.metadata.name,
            "kubeflow-tpu.org/job-kind": self.kind.value,
        }
        if rtype is not None:
            out["kubeflow-tpu.org/replica-type"] = rtype
        if index is not None:
            out["kubeflow-tpu.org/replica-index"] = str(index)
        return out


@dataclass
class JAXJob(TrainJob):
    kind: JobKind = JobKind.JAX


@dataclass
class TFJob(TrainJob):
    kind: JobKind = JobKind.TF


@dataclass
class PyTorchJob(TrainJob):
    kind: JobKind = JobKind.PYTORCH


@dataclass
class MPIJob(TrainJob):
    kind: JobKind = JobKind.MPI


@dataclass
class XGBoostJob(TrainJob):
    kind: JobKind = JobKind.XGBOOST


@dataclass
class PaddleJob(TrainJob):
    kind: JobKind = JobKind.PADDLE


@dataclass
class MXJob(TrainJob):
    kind: JobKind = JobKind.MXNET


# stamped by apply_elastic_scale on every scale; read by the capacity
# autoscaler as its stabilization-window anchor
LAST_SCALE_ANNOTATION = "kubeflow-tpu.org/autoscale-last-scale"


def apply_elastic_scale(job: TrainJob, replicas: int) -> None:
    """Mutate `job` in place to `replicas` workers (elastic scale).

    TPU elasticity is slice-granular (SURVEY.md §2.2): the new size must keep
    whole slices, and the change lands as a whole-gang re-mesh (coordinator
    restart + resume from checkpoint), never a live resize. Requires an
    ElasticPolicy and min_replicas <= replicas <= max_replicas. Shared by
    TrainingClient.scale_job and the capacity autoscaler (the reference's
    pytorch HPA analogue) so both enforce identical invariants.
    """
    if job.status.is_finished:
        raise ValueError(f"job {job.name} already finished; cannot scale")
    ep = job.spec.run_policy.elastic_policy
    if ep is None:
        raise ValueError(f"job {job.name} has no elasticPolicy; cannot scale")
    if not (ep.min_replicas <= replicas <= ep.max_replicas):
        raise ValueError(
            f"replicas {replicas} outside elastic range "
            f"[{ep.min_replicas}, {ep.max_replicas}]"
        )
    workers = job.spec.replica_specs.get(REPLICA_WORKER)
    if workers is None:
        raise ValueError(f"job {job.name} has no worker replicas; cannot scale")
    old_total = job.total_replicas()
    if job.spec.num_slices > 1:
        per_slice = workers.replicas // job.spec.num_slices
        if replicas % per_slice:
            raise ValueError(
                f"replicas {replicas} not a multiple of per-slice worker "
                f"count {per_slice} (scale by whole slices)"
            )
        job.spec.num_slices = replicas // per_slice
    workers.replicas = replicas
    # every scale (user or autoscaler) opens a stabilization window: the
    # capacity autoscaler (controller/autoscaler.py) must not revert a manual
    # scale inside its cooldown, so the stamp lives in this shared path
    import time as _time

    job.metadata.annotations[LAST_SCALE_ANNOTATION] = str(_time.time())
    sp = job.spec.run_policy.scheduling_policy
    if sp is not None and sp.min_available is not None:
        # full-gang intent follows the new size; an explicit partial
        # min stays, clamped to remain satisfiable
        if sp.min_available >= old_total:
            sp.min_available = job.total_replicas()
        else:
            sp.min_available = min(sp.min_available, job.total_replicas())


TRAIN_FAMILIES = ("mnist", "resnet", "bert", "bert_pretrain", "gpt")


def build_example_train_job(
    name: str,
    *,
    family: str,
    num_workers: int = 1,
    namespace: str = "default",
    device: str = "auto",
    args: list | None = None,
    interpreter: str = "python",
    working_dir: str = "",
    elastic: tuple | None = None,
) -> "JAXJob":
    """The ONE builder behind TrainingClient.train() and RemoteClient.train():
    a JAXJob running `<interpreter> -m examples.<family>`. In-process clients
    pass sys.executable + the repo root; remote clients pass the symbolic
    "python" and no working_dir — the SERVER's pod runtime resolves both."""
    from kubeflow_tpu.api.common import (
        ContainerSpec,
        ElasticPolicy,
        ObjectMeta,
        PodTemplateSpec,
        ReplicaSpec,
        RunPolicy,
    )

    if family not in TRAIN_FAMILIES:
        raise ValueError(f"unknown family {family!r} (one of {TRAIN_FAMILIES})")
    rp = RunPolicy()
    if elastic is not None:
        lo, hi = elastic
        if not (lo <= num_workers <= hi):
            raise ValueError(
                f"num_workers {num_workers} outside elastic range [{lo}, {hi}]"
            )
        rp.elastic_policy = ElasticPolicy(min_replicas=lo, max_replicas=hi)
    return JAXJob(
        metadata=ObjectMeta(name=name, namespace=namespace),
        spec=JAXJobSpec(
            replica_specs={REPLICA_WORKER: ReplicaSpec(
                replicas=num_workers,
                template=PodTemplateSpec(container=ContainerSpec(
                    command=[interpreter, "-m", f"examples.{family}",
                             f"--device={device}", *(args or [])],
                    working_dir=working_dir,
                )),
            )},
            run_policy=rp,
        ),
    )


_KIND_TO_CLS = {
    JobKind.JAX: JAXJob,
    JobKind.TF: TFJob,
    JobKind.PYTORCH: PyTorchJob,
    JobKind.MPI: MPIJob,
    JobKind.XGBOOST: XGBoostJob,
    JobKind.PADDLE: PaddleJob,
    JobKind.MXNET: MXJob,
}


def job_class_for_kind(kind: JobKind | str) -> type[TrainJob]:
    return _KIND_TO_CLS[JobKind(kind)]
