"""Common job-spec types shared by every job kind.

Reference parity: training-operator pkg/apis/kubeflow.org/v1/common_types.go
(ReplicaSpec, RunPolicy, JobCondition, JobStatus, ReplicaStatus — unverified,
SURVEY.md §2.1). Field names follow the CRD's camelCase in serialized form and
snake_case in Python.
"""

from __future__ import annotations

import dataclasses
import datetime
import enum
from dataclasses import dataclass, field
from typing import Any


def utcnow() -> str:
    """Canonical timestamp format for every object/status in the platform
    (jobcontroller._parse_ts assumes exactly this shape)."""
    return datetime.datetime.now(datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")


_utcnow = utcnow


class RestartPolicy(str, enum.Enum):
    """Per-replica restart policy (common_types.go RestartPolicy).

    EXIT_CODE: retry only on retryable exit codes (1-127 => permanent failure,
    128+ => retryable), mirroring the reference's ExitCode semantics.
    """

    NEVER = "Never"
    ON_FAILURE = "OnFailure"
    ALWAYS = "Always"
    EXIT_CODE = "ExitCode"


class CleanPodPolicy(str, enum.Enum):
    """What to do with replica processes when the job finishes."""

    ALL = "All"
    RUNNING = "Running"
    NONE = "None"


class JobConditionType(str, enum.Enum):
    CREATED = "Created"
    RUNNING = "Running"
    RESTARTING = "Restarting"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    SUSPENDED = "Suspended"


# Exit codes 128+ (signals, OOM-kill analogues) are retryable under the
# ExitCode restart policy; 1-127 are permanent. Matches the reference's
# convention for RestartPolicyExitCode.
RETRYABLE_EXIT_CODE_MIN = 128

# The preempted exit class (128 + SIGTERM): a chip-scheduler eviction is
# retryable BY CONSTRUCTION — the gang restarts from checkpoint once
# capacity frees, riding the same backoff as a crash (docs/scheduler.md).
PREEMPTED_EXIT_CODE = 143


def is_retryable_exit_code(code: int) -> bool:
    return code >= RETRYABLE_EXIT_CODE_MIN


def _scalar_str(v) -> str:
    """String form of a YAML scalar, rendering booleans the way the
    manifest author wrote them ('true'/'false')."""
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


@dataclass
class ObjectMeta:
    """Minimal object metadata (k8s ObjectMeta analogue)."""

    name: str = ""
    namespace: str = "default"
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    uid: str = ""

    def __post_init__(self):
        # k8s labels/annotations are string-typed; unquoted YAML scalars
        # (numbers/bools) and an explicit `labels:` null must normalize at
        # parse time or selectors silently never match (same coercion
        # ContainerSpec applies to env/command/args). A null VALUE is
        # rejected like k8s admission does — coercing it to the string
        # "None" would make `team=None` unexpectedly match.
        for which, d in (("label", self.labels),
                         ("annotation", self.annotations)):
            for k, v in (d or {}).items():
                if v is None:
                    raise ValueError(
                        f"{which} {k!r} has a null value (write an empty "
                        f"string, or drop the key)")
        self.labels = {
            str(k): _scalar_str(v) for k, v in (self.labels or {}).items()
        }
        self.annotations = {
            str(k): _scalar_str(v)
            for k, v in (self.annotations or {}).items()
        }
    # Set by the object store on admission (k8s semantics); empty until then so
    # spec serialization stays deterministic for golden-file tests.
    creation_timestamp: str = ""
    # Optimistic-concurrency token (k8s resourceVersion): bumped by the store
    # on every write; a stale-version update is rejected with ConflictError.
    resource_version: int = 0


@dataclass
class ContainerSpec:
    """The command a replica runs. A pod-container analogue: in this runtime a
    'container' is an OS process (the fake-cluster maps image -> interpreter).
    """

    image: str = "python"
    command: list[str] = field(default_factory=list)
    args: list[str] = field(default_factory=list)
    env: dict[str, str] = field(default_factory=dict)
    working_dir: str = ""
    # Resource requests; the TPU resource key mirrors GKE's `google.com/tpu`.
    resources: dict[str, Any] = field(default_factory=dict)
    ports: dict[str, int] = field(default_factory=dict)

    def __post_init__(self) -> None:
        # YAML turns unquoted numeric-looking values into numbers — common
        # when sweep trial-template substitution writes `LR: ${...}` without
        # quotes. Env values and argv elements are string-typed all the way
        # down (os env / execve), so coerce scalars here instead of letting a
        # float reach the reconciler and hang the job with an opaque
        # ReconcileError (observed: "expected string or bytes-like object").
        def coerce(v):
            # YAML booleans render as 'true'/'false' (the string the manifest
            # author wrote), not Python's 'True'/'False'
            if isinstance(v, bool):
                return "true" if v else "false"
            if isinstance(v, (int, float)):
                return str(v)
            return v

        self.env = {k: coerce(v) for k, v in self.env.items()}
        self.command = [coerce(v) for v in self.command]
        self.args = [coerce(v) for v in self.args]


@dataclass
class PodTemplateSpec:
    """Template for the worker process ('pod') of one replica."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    container: ContainerSpec = field(default_factory=ContainerSpec)
    # Scheduler hint, e.g. "gang" (volcano analogue) or "default".
    scheduler_name: str = "gang"
    node_selector: dict[str, str] = field(default_factory=dict)


@dataclass
class ReplicaSpec:
    """One replica group (worker/ps/chief/master/launcher)."""

    replicas: int = 1
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    restart_policy: RestartPolicy = RestartPolicy.ON_FAILURE


@dataclass
class SchedulingPolicy:
    """Gang-scheduling knobs (RunPolicy.SchedulingPolicy in the reference)."""

    min_available: int | None = None
    queue: str = "default"
    priority_class: str = ""
    # TPU slice topology the gang must land on, e.g. "2x4" (v5e-8).
    # The slice is the atomic scheduling unit on TPU (SURVEY.md §2.2).
    slice_topology: str = ""


@dataclass
class ElasticPolicy:
    """Elastic scaling policy (pytorchjob ElasticPolicy analogue).

    On TPU, elasticity is slice-granular: scale by whole slices, and every
    scale event is a re-mesh (coordinator restart + jax.distributed re-init).
    """

    min_replicas: int = 1
    max_replicas: int = 1
    max_restarts: int = 3
    # Rendezvous backend: "jax" (jax.distributed coordination service) for
    # JAXJob; PyTorchJob honors this verbatim in PET_RDZV_BACKEND (c10d/etcd).
    rdzv_backend: str = "jax"
    nproc_per_node: int = 1


@dataclass
class RunPolicy:
    """Job-level execution policy (common_types.go RunPolicy)."""

    clean_pod_policy: CleanPodPolicy = CleanPodPolicy.RUNNING
    ttl_seconds_after_finished: int | None = None
    active_deadline_seconds: int | None = None
    backoff_limit: int = 3
    scheduling_policy: SchedulingPolicy | None = None
    suspend: bool = False
    elastic_policy: ElasticPolicy | None = None


@dataclass
class JobCondition:
    type: JobConditionType = JobConditionType.CREATED
    status: bool = True
    reason: str = ""
    message: str = ""
    last_transition_time: str = field(default_factory=_utcnow)


@dataclass
class ReplicaStatus:
    active: int = 0
    succeeded: int = 0
    failed: int = 0
    # label selector string for this replica group's pods, as the reference
    # surfaces in ReplicaStatus.Selector
    selector: str = ""


@dataclass
class JobStatus:
    conditions: list[JobCondition] = field(default_factory=list)
    replica_statuses: dict[str, ReplicaStatus] = field(default_factory=dict)
    start_time: str | None = None
    completion_time: str | None = None
    last_reconcile_time: str | None = None
    restart_count: int = 0

    # -- condition helpers (pkg/util/status.go analogues) --

    def condition(self, ctype: JobConditionType) -> JobCondition | None:
        for c in self.conditions:
            if c.type == ctype:
                return c
        return None

    def has_condition(self, ctype: JobConditionType) -> bool:
        c = self.condition(ctype)
        return c is not None and c.status

    def set_condition(
        self, ctype: JobConditionType, reason: str = "", message: str = ""
    ) -> None:
        """Append/refresh a condition. Running/Restarting/terminal conditions are
        mutually exclusive, mirroring the reference's updateJobConditions."""
        new = JobCondition(type=ctype, status=True, reason=reason, message=message)
        exclusive = {
            JobConditionType.RUNNING,
            JobConditionType.RESTARTING,
            JobConditionType.SUCCEEDED,
            JobConditionType.FAILED,
            JobConditionType.SUSPENDED,
        }
        out: list[JobCondition] = []
        for c in self.conditions:
            if c.type == ctype:
                continue
            if ctype in exclusive and c.type in exclusive:
                c = dataclasses.replace(c, status=False)
            out.append(c)
        out.append(new)
        self.conditions = out

    @property
    def is_finished(self) -> bool:
        return self.has_condition(JobConditionType.SUCCEEDED) or self.has_condition(
            JobConditionType.FAILED
        )

    @property
    def is_succeeded(self) -> bool:
        return self.has_condition(JobConditionType.SUCCEEDED)

    @property
    def is_failed(self) -> bool:
        return self.has_condition(JobConditionType.FAILED)
