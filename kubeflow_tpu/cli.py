"""CLI — `python -m kubeflow_tpu <command>`.

Reference parity: the reference platform is driven by kubectl + per-project
CLIs (kfctl-era; SURVEY.md §2.7) against CR manifests. This CLI takes the
same CR-shaped YAML (samples/) and drives the in-process platform one-shot:

  run          -f job.yaml        submit a TrainJob, wait, print verdict+logs
  mpirun       -np N -- cmd ...   mpirun-shaped MPIJob launch (UX parity)
  validate     -f job.yaml        admission-check a manifest
  render-env   -f job.yaml        print the synthesized rendezvous env
  sweep        -f experiment.yaml run an Experiment, print the optimal trial
  serve        -f isvc.yaml       serve an InferenceService until Ctrl-C
  pipeline-compile module:fn      compile a @pipeline function to IR YAML
  pipeline-run -f ir.yaml         execute compiled IR locally
"""

from __future__ import annotations

import argparse
import importlib
import json
import sys
from pathlib import Path


def _read(path: str) -> str:
    return sys.stdin.read() if path == "-" else Path(path).read_text()


# ------------------------------------------------------------------ commands

def cmd_validate(args) -> int:
    from kubeflow_tpu.api.serde import job_from_yaml, job_to_yaml
    from kubeflow_tpu.api.validation import validate_job

    job = validate_job(job_from_yaml(_read(args.filename)))
    print(job_to_yaml(job), end="")
    print(f"# {job.kind.value} {job.namespace}/{job.name}: OK", file=sys.stderr)
    return 0


def cmd_render_env(args) -> int:
    from kubeflow_tpu.api.serde import job_from_yaml
    from kubeflow_tpu.api.validation import validate_job
    from kubeflow_tpu.controller.envcontract import synthesize_env

    job = validate_job(job_from_yaml(_read(args.filename)))
    env = synthesize_env(job, args.rtype, args.index)
    for k in sorted(env):
        print(f"{k}={env[k]}")
    return 0


def cmd_run(args) -> int:
    from kubeflow_tpu.api.serde import job_from_yaml
    from kubeflow_tpu.client import Platform, TrainingClient

    job = job_from_yaml(_read(args.filename))
    with Platform(capacity_chips=args.capacity_chips, log_dir=args.log_dir) as platform:
        client = TrainingClient(platform)
        client.create_job(job)
        print(f"{job.kind.value} {job.namespace}/{job.name} created", file=sys.stderr)
        done = client.wait_for_job_conditions(
            job.name, job.namespace, timeout_s=args.timeout
        )
        for cond in done.status.conditions:
            if cond.status:
                print(f"condition: {cond.type.value} ({cond.reason})", file=sys.stderr)
        if args.logs:
            for rtype, rs in job.spec.replica_specs.items():
                for i in range(rs.replicas):
                    print(f"--- {rtype}-{i} ---")
                    print(client.get_job_logs(job.name, job.namespace, rtype, i), end="")
        return 0 if done.status.is_succeeded else 1


def cmd_mpirun(args) -> int:
    """mpirun-shaped launch UX (SURVEY.md §2.3 OpenMPI row): build an MPIJob
    whose launcher runs the given command against a materialized hostfile,
    with N idle workers forming the gang."""
    from kubeflow_tpu.api import (
        ContainerSpec,
        ObjectMeta,
        PodTemplateSpec,
        ReplicaSpec,
        RunPolicy,
        CleanPodPolicy,
        REPLICA_LAUNCHER,
        REPLICA_WORKER,
    )
    from kubeflow_tpu.api.jobs import MPIJob, JAXJobSpec
    from kubeflow_tpu.client import Platform, TrainingClient

    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        print("mpirun: no command given (use: mpirun -np N -- cmd ...)",
              file=sys.stderr)
        return 2
    args.cmd = cmd
    job = MPIJob(
        metadata=ObjectMeta(name=args.name),
        spec=JAXJobSpec(
            replica_specs={
                REPLICA_LAUNCHER: ReplicaSpec(
                    replicas=1,
                    template=PodTemplateSpec(
                        container=ContainerSpec(command=list(args.cmd))
                    ),
                ),
                REPLICA_WORKER: ReplicaSpec(
                    replicas=args.np,
                    template=PodTemplateSpec(
                        container=ContainerSpec(
                            command=[sys.executable, "-c",
                                     "import time; time.sleep(10**8)"]
                        )
                    ),
                ),
            },
            run_policy=RunPolicy(clean_pod_policy=CleanPodPolicy.RUNNING),
        ),
    )
    with Platform(capacity_chips=args.capacity_chips, log_dir=args.log_dir) as platform:
        client = TrainingClient(platform)
        client.create_job(job)
        done = client.wait_for_job_conditions(
            args.name, timeout_s=args.timeout
        )
        print(client.get_job_logs(args.name, rtype="launcher"), end="")
        return 0 if done.status.is_succeeded else 1


def cmd_sweep(args) -> int:
    from kubeflow_tpu.client import Platform
    from kubeflow_tpu.sweep import SweepClient
    from kubeflow_tpu.sweep.serde import experiment_from_yaml

    exp = experiment_from_yaml(_read(args.filename))
    with Platform(capacity_chips=args.capacity_chips, log_dir=args.log_dir) as platform:
        sweep = SweepClient(platform)
        sweep.create_experiment(exp)
        print(f"experiment {exp.metadata.name} created "
              f"(max {exp.spec.max_trial_count} trials)", file=sys.stderr)
        done = sweep.wait_for_experiment(
            exp.metadata.name, exp.metadata.namespace, timeout_s=args.timeout
        )
        if args.resume_to > 0:
            # continue the finished sweep with a larger budget in the same
            # platform session (resumePolicy=LongRunning); an unresumable
            # outcome (FAILED, GoalReached, budget too small) reports and
            # falls through to the normal JSON summary instead of crashing
            try:
                sweep.resume_experiment(
                    exp.metadata.name, args.resume_to, exp.metadata.namespace
                )
            except ValueError as exc:
                print(f"not resumed: {exc}", file=sys.stderr)
            else:
                print(f"resumed to maxTrialCount={args.resume_to}",
                      file=sys.stderr)
                done = sweep.wait_for_experiment(
                    exp.metadata.name, exp.metadata.namespace,
                    timeout_s=args.timeout,
                )
        best = done.status.current_optimal_trial
        print(json.dumps({
            "condition": done.status.condition.value,
            "message": done.status.message,
            "trials": done.status.trials,
            "succeeded": done.status.trials_succeeded,
            "earlyStopped": done.status.trials_early_stopped,
            "optimal": {
                "trial": best.trial_name if best else None,
                "parameters": (
                    {a.name: a.value for a in best.parameter_assignments}
                    if best else {}
                ),
                "metrics": (
                    {m.name: m.latest for m in best.observation.metrics}
                    if best else {}
                ),
            },
        }, indent=2))
        return 0 if done.status.condition.value == "Succeeded" else 1


def cmd_serve(args) -> int:
    from kubeflow_tpu.client import Platform
    from kubeflow_tpu.serving import ServingClient
    from kubeflow_tpu.serving.serde import isvc_from_yaml

    isvc = isvc_from_yaml(_read(args.filename))
    with Platform(log_dir=args.log_dir) as platform:
        serving = ServingClient(platform)
        serving.create(isvc)
        ready = serving.wait_ready(
            isvc.metadata.name, isvc.metadata.namespace, timeout_s=args.timeout
        )
        print(f"ready: {ready.status.url}")
        print(f"  v1: POST {ready.status.url}/v1/models/{isvc.metadata.name}:predict")
        print(f"  v2: POST {ready.status.url}/v2/models/{isvc.metadata.name}/infer")
        try:
            import threading

            threading.Event().wait()  # hold until Ctrl-C
        except KeyboardInterrupt:
            pass
    return 0


def _load_pipeline(spec: str):
    mod_name, _, fn_name = spec.partition(":")
    if not fn_name:
        raise SystemExit(f"pipeline ref {spec!r} must look like 'module:function'")
    return getattr(importlib.import_module(mod_name), fn_name)


def cmd_pipeline_compile(args) -> int:
    from kubeflow_tpu.pipelines import compile_to_yaml

    text = compile_to_yaml(_load_pipeline(args.pipeline)())
    if args.output:
        Path(args.output).write_text(text)
        print(f"wrote {args.output}", file=sys.stderr)
    else:
        print(text, end="")
    return 0


def cmd_pipeline_run(args) -> int:
    import contextlib

    import yaml

    from kubeflow_tpu.pipelines import LocalPipelineRunner

    ir = yaml.safe_load(_read(args.filename))
    arguments = {}
    for kv in args.arg or []:
        k, _, v = kv.partition("=")
        try:
            arguments[k] = json.loads(v)
        except json.JSONDecodeError:
            arguments[k] = v
    # trainJob/sweep steps need a live control plane; spin one up only then
    needs_platform = any(
        "trainJob" in ex or "sweep" in ex
        for ex in ir.get("deploymentSpec", {}).get("executors", {}).values()
    )
    with contextlib.ExitStack() as stack:
        platform = None
        if needs_platform:
            from kubeflow_tpu.client import Platform

            platform = stack.enter_context(Platform(log_dir=args.log_dir))
        runner = LocalPipelineRunner(
            work_dir=args.work_dir, cache=not args.no_cache, platform=platform
        )
        run = runner.run(ir, arguments)
    print(json.dumps({
        "runId": run.run_id,
        "state": run.state.value,
        "tasks": {t: r.state.value for t, r in run.tasks.items()},
        "output": run.output,
    }, indent=2))
    return 0 if run.succeeded else 1


def cmd_pipeline_submit(args) -> int:
    """Submit compiled IR to a REMOTE platform as a PipelineRun and poll."""
    import yaml

    ir = yaml.safe_load(_read(args.filename))
    arguments = {}
    for kv in args.arg or []:
        k, _, v = kv.partition("=")
        try:
            arguments[k] = json.loads(v)
        except json.JSONDecodeError:
            arguments[k] = v
    client = _remote(args)
    client.submit_pipeline_run(args.name, ir, arguments,
                               namespace=args.namespace)
    print(f"pipelinerun {args.namespace}/{args.name} submitted", file=sys.stderr)
    run = client.wait_for_pipeline_run(
        args.name, args.namespace, timeout_s=args.timeout
    )
    st = run.get("status", {})
    print(json.dumps({
        "state": st.get("state"),
        "tasks": st.get("tasks", {}),
        "output": st.get("output"),
        "error": st.get("error", ""),
    }, indent=2))
    return 0 if st.get("state") == "Succeeded" else 1


def cmd_platform(args) -> int:
    """Run the control plane as a daemon serving the REST API — from a
    KfDef manifest (-f, kfctl-apply analogue) or bare flags."""
    import threading

    if getattr(args, "kfdef", ""):
        from pathlib import Path

        from kubeflow_tpu.kfdef import apply_kfdef, load_kfdef

        try:
            kfdef = load_kfdef(args.kfdef)
            platform, server = apply_kfdef(
                kfdef, base_dir=Path(args.kfdef).resolve().parent)
        except (OSError, ValueError) as exc:
            print(f"kfdef error: {exc}", file=sys.stderr)
            return 1
        apps = kfdef.spec.applications or ["(all)"]
        extra = (f" activator={platform.activator.url}"
                 if platform.activator is not None else "")
        print(f"platform {kfdef.metadata.name!r} serving at {server.url} "
              f"applications={','.join(apps)}{extra}", flush=True)
        try:
            threading.Event().wait()
        except KeyboardInterrupt:
            pass
        finally:
            server.stop()
            platform.stop()
        return 0

    from kubeflow_tpu.apiserver import PlatformServer
    from kubeflow_tpu.client import Platform

    with Platform(capacity_chips=args.capacity_chips, log_dir=args.log_dir) as platform:
        server = PlatformServer(platform, port=args.port, host=args.host).start()
        print(f"platform API serving at {server.url}", flush=True)
        try:
            threading.Event().wait()
        except KeyboardInterrupt:
            server.stop()
    return 0


def cmd_platform_init(args) -> int:
    """Scaffold a kfdef.yaml (kfctl init analogue)."""
    from kubeflow_tpu.kfdef import init_scaffold

    try:
        path = init_scaffold(args.directory)
    except (OSError, FileExistsError) as exc:
        print(f"init error: {exc}", file=sys.stderr)
        return 1
    print(f"wrote {path} — edit it, then: "
          f"python -m kubeflow_tpu platform -f {path}")
    return 0


def _remote(args):
    from kubeflow_tpu.remote import RemoteClient

    return RemoteClient(args.server)


def cmd_apply(args) -> int:
    out = _remote(args).apply(_read(args.filename))
    meta = out.get("metadata", {})
    print(f"{out.get('kind')} {meta.get('namespace')}/{meta.get('name')} created")
    return 0


def cmd_get(args) -> int:
    client = _remote(args)
    if args.name:
        if args.selector:
            print("error: a name and a selector cannot both be given "
                  "(kubectl semantics)", file=sys.stderr)
            return 2
        print(json.dumps(client.get(args.kind, args.name, args.namespace), indent=2))
        return 0
    objs = client.list(
        args.kind,
        namespace="" if args.all_namespaces else args.namespace,
        label_selector=args.selector,
    )
    for o in objs:
        meta = o.get("metadata", {})
        status = o.get("status", {})
        conds = [c["type"] for c in status.get("conditions", []) if c.get("status", True)]
        state = conds[-1] if conds else status.get("condition", "")
        print(f"{meta.get('namespace', '?')}/{meta.get('name', '?')}\t{state}")
    if not objs:
        print(f"no {args.kind} found", file=sys.stderr)
    return 0


def cmd_logs(args) -> int:
    client = _remote(args)
    if args.follow:
        for chunk in client.follow_job_logs(
                args.name, args.namespace, args.rtype, args.index):
            print(chunk, end="", flush=True)
        return 0
    print(client.job_logs(args.name, args.namespace, args.rtype, args.index),
          end="")
    return 0


def cmd_delete(args) -> int:
    out = _remote(args).delete(args.kind, args.name, args.namespace)
    print(f"deleted {out.get('deleted')}")
    return 0


def cmd_scale(args) -> int:
    out = _remote(args).scale_job(args.name, args.replicas, args.namespace)
    workers = out.get("spec", {}).get("replicaSpecs", {}).get("worker", {})
    print(f"scaled {args.namespace}/{args.name} to {workers.get('replicas')} workers")
    return 0


def cmd_generate(args) -> int:
    """KV-cache text generation against a saved gpt-lm predictor dir
    (the serving model-dir contract; tokenizer.json beside it when the
    prompt is text rather than ids). --draft-model-dir switches to
    speculative decoding: the draft proposes, the target verifies —
    output is exactly the target's greedy decode, faster."""
    import numpy as np

    from kubeflow_tpu.utils import select_device

    select_device(args.device)

    tok = None
    tok_path = Path(args.model_dir) / "tokenizer.json"
    if tok_path.exists():
        # dispatches: in-tree trainable BPE or an imported GPT-2
        # byte-level one (import-gpt2 --vocab-json/--merges-txt)
        from kubeflow_tpu.train.bpe_gpt2 import load_any_tokenizer

        tok = load_any_tokenizer(tok_path)
    if tok is not None:
        try:
            encoded = tok.encode(args.prompt, eos=False)
        except ValueError as exc:
            print(f"error: {exc}", file=sys.stderr)
            return 2
        if not encoded:
            print("error: prompt encodes to zero tokens", file=sys.stderr)
            return 2
        ids = np.asarray([encoded], np.int32)
    else:
        try:
            ids = np.asarray([[int(t) for t in args.prompt.split()]],
                             np.int32)
        except ValueError:
            print("error: no tokenizer.json in the model dir — pass the "
                  "prompt as space-separated token ids", file=sys.stderr)
            return 2

    # gen-config checks come from config.json alone — no weight loading
    # before cheap validation
    try:
        tcfg = json.loads(
            (Path(args.model_dir) / "config.json").read_text())
    except OSError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    gen = tcfg.get("generate")
    if gen is None:
        print("error: model dir has no generate config (not a gpt-lm "
              "generative predictor)", file=sys.stderr)
        return 2

    def trim_at_stop(out, eos):
        """Trim at the FIRST occurrence of ANY stop id (int or list)."""
        if eos is None:
            return out
        stops = [int(x) for x in eos] if isinstance(eos, list) else [int(eos)]
        toks = out.tolist()
        hits = [toks.index(s) for s in stops if s in toks]
        return out[: min(hits)] if hits else out

    if args.draft_model_dir:
        import jax

        from kubeflow_tpu.models.speculative import speculative_generate
        from kubeflow_tpu.serving.model import load_generative_model

        if int(gen.get("num_beams", 1)) > 1:
            print("error: speculative decoding is incompatible with beam "
                  "search (num_beams > 1 in the target config)",
                  file=sys.stderr)
            return 2
        temp = float(gen.get("temperature", 0.0))
        if temp > 0 and int(gen.get("top_k", 0)) > 0:
            # mirror the continuous engine's refusal (serving/continuous.py
            # submit): a SAMPLED row's rejection scheme must accept against
            # the draft's ACTUAL proposal distribution — a top_k-truncated
            # p_d/p_t pair needs both sides renormalized consistently,
            # which speculative_generate does not implement. Silently
            # ignoring top_k here would serve a DIFFERENT distribution
            # than the same predictor without --draft-model-dir.
            print("error: speculative decoding with temperature > 0 does "
                  "not compose with top_k > 0 in the target config",
                  file=sys.stderr)
            return 2
        tmod, tvars, _ = load_generative_model(Path(args.model_dir))
        dmod, dvars, _ = load_generative_model(Path(args.draft_model_dir))
        if tmod.cfg.vocab_size != dmod.cfg.vocab_size:
            print(f"error: draft vocab {dmod.cfg.vocab_size} != target "
                  f"vocab {tmod.cfg.vocab_size}", file=sys.stderr)
            return 2
        eos = gen.get("eos_token_id")
        try:
            out_ids, stats = speculative_generate(
                tmod, tvars, dmod, dvars, ids,
                max_new_tokens=int(gen.get("max_new_tokens", 32)),
                gamma=args.gamma,
                eos_token_id=eos,
                # temperature > 0 runs the rejection-sampling scheme —
                # target-distribution-exact; per-invocation key from the
                # CLI seed
                temperature=temp,
                rng=(jax.random.PRNGKey(args.seed) if temp > 0 else None),
            )
        except ValueError as exc:
            print(f"error: {exc}", file=sys.stderr)
            return 2
        out = trim_at_stop(np.asarray(out_ids)[0], eos)
        rounds = int(stats["rounds"])
        accepted = int(stats["drafted_accepted"])
        print(f"[speculative] rounds={rounds} drafted_accepted={accepted} "
              f"tokens={len(out)}", file=sys.stderr)
        print(tok.decode(out) if tok is not None else
              " ".join(map(str, out)))
        return 0

    from kubeflow_tpu.serving.model import JaxModel

    jm = JaxModel("cli", args.model_dir)
    jm.load()
    out = trim_at_stop(np.asarray(jm(ids)["predictions"])[0],
                       gen.get("eos_token_id"))
    print(tok.decode(out) if tok is not None else " ".join(map(str, out)))
    return 0


def cmd_import_gpt2(args) -> int:
    """HF/torch GPT-2 checkpoint -> serving-ready gpt-lm predictor dir
    (the migration on-ramp: bring reference-stack weights, serve on TPU)."""
    from kubeflow_tpu.train.convert import import_gpt2
    from kubeflow_tpu.utils import select_device

    select_device(args.device)
    try:
        out = import_gpt2(
            args.checkpoint, args.out,
            num_heads=args.num_heads or None,
            max_new_tokens=args.max_new_tokens, max_len=args.max_len,
            prompt_len=args.prompt_len,
            vocab_json=args.vocab_json, merges_txt=args.merges_txt,
            continuous_rows=args.continuous_rows,
        )
    except (OSError, KeyError, ValueError) as exc:
        print(f"import error: {exc}", file=sys.stderr)
        return 2
    print(f"serving-ready predictor dir: {out}\n"
          f"  serve:    python -m kubeflow_tpu.serving.server "
          f"--model-name gpt2 --model-dir {out}\n"
          f"  generate: python -m kubeflow_tpu generate --model-dir {out} "
          f"--prompt '<ids or text>'")
    return 0


def cmd_import_llama(args) -> int:
    """HF/torch Llama/Mistral checkpoint -> serving-ready gpt-lm predictor
    dir (GPTConfig.llama family: rope + GQA + RMSNorm + SwiGLU)."""
    from kubeflow_tpu.train.convert import import_llama
    from kubeflow_tpu.utils import select_device

    select_device(args.device)
    try:
        out = import_llama(
            args.checkpoint, args.out,
            num_heads=args.num_heads or None,
            max_new_tokens=args.max_new_tokens, max_len=args.max_len,
            prompt_len=args.prompt_len,
            continuous_rows=args.continuous_rows,
        )
    except (OSError, KeyError, ValueError) as exc:
        print(f"import error: {exc}", file=sys.stderr)
        return 2
    print(f"serving-ready predictor dir: {out}\n"
          f"  serve:    python -m kubeflow_tpu.serving.server "
          f"--model-name llama --model-dir {out}\n"
          f"  generate: python -m kubeflow_tpu generate --model-dir {out} "
          f"--prompt '<ids>'")
    return 0


def cmd_import_bert(args) -> int:
    """HF/torch BERT checkpoint -> serving-ready classifier predictor."""
    from kubeflow_tpu.train.convert import import_bert
    from kubeflow_tpu.utils import select_device

    select_device(args.device)
    try:
        out = import_bert(
            args.checkpoint, args.out,
            num_heads=args.num_heads or None,
            num_classes=args.num_classes or None,
            max_len=args.max_len,
        )
    except (OSError, KeyError, ValueError) as exc:
        print(f"import error: {exc}", file=sys.stderr)
        return 2
    print(f"serving-ready predictor dir: {out}")
    return 0


def cmd_profile(args) -> int:
    """Render a step-time/goodput/control-plane breakdown — from a trace
    directory (worker flushes + a platform export) or a live platform's
    /debug/profile endpoint (docs/profiling.md)."""
    from kubeflow_tpu.profiling import (
        ProfileError,
        build_profile,
        load_trace_dir,
        render_text,
    )

    if bool(args.trace_dir) == bool(args.server):
        print("error: pass exactly one of --trace-dir or --server",
              file=sys.stderr)
        return 2
    try:
        if args.server:
            import urllib.request

            url = f"{args.server.rstrip('/')}/debug/profile"
            with urllib.request.urlopen(url, timeout=10) as r:
                prof = json.loads(r.read())
        else:
            prof = build_profile(load_trace_dir(args.trace_dir))
    except ProfileError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    except (OSError, ValueError, KeyError, TypeError) as exc:
        # urllib errors (refused/404) and malformed server payloads (a
        # non-profile JSON body crashing the renderer) land here — one
        # diagnostic line, never a traceback
        print(f"error: {exc!r}", file=sys.stderr)
        return 2
    out = json.dumps(prof, indent=2) + "\n" if args.json \
        else render_text(prof)
    if args.output:
        Path(args.output).write_text(out)
        print(f"wrote {args.output}", file=sys.stderr)
    else:
        print(out, end="")
    return 0


def cmd_slo(args) -> int:
    """Render the SLO burn-rate report + per-request breakdown — from a
    live platform's /debug/slo endpoint, or request-breakdown-only from
    a trace directory (docs/slo.md). Shares the /debug/slo build path
    (monitoring/report), so the two surfaces cannot disagree."""
    from kubeflow_tpu.monitoring import (
        build_slo_report_from_spans,
        render_slo_text,
    )
    from kubeflow_tpu.profiling import ProfileError, load_trace_dir

    if bool(args.trace_dir) == bool(args.server):
        print("error: pass exactly one of --trace-dir or --server",
              file=sys.stderr)
        return 2
    try:
        if args.server:
            import urllib.request

            url = f"{args.server.rstrip('/')}/debug/slo"
            with urllib.request.urlopen(url, timeout=10) as r:
                report = json.loads(r.read())
        else:
            # trace-dir mode has no live TSDB: the report is the request
            # breakdown alone (alerts need a running monitor)
            report = build_slo_report_from_spans(
                load_trace_dir(args.trace_dir))
    except ProfileError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    except (OSError, ValueError, KeyError, TypeError) as exc:
        # urllib errors (refused/404) and malformed server payloads land
        # here — one diagnostic line, never a traceback
        print(f"error: {exc!r}", file=sys.stderr)
        return 2
    out = json.dumps(report, indent=2) + "\n" if args.json \
        else render_slo_text(report)
    if args.output:
        Path(args.output).write_text(out)
        print(f"wrote {args.output}", file=sys.stderr)
    else:
        print(out, end="")
    return 0


def cmd_sched(args) -> int:
    """Render the chip-scheduler report — inventory, claim table,
    per-tenant fair-share accounting, decision counters — from a live
    platform's /debug/sched endpoint (docs/scheduler.md). Shares the
    /debug/sched build path (scheduler/report), so the two surfaces
    cannot disagree about who holds which chips."""
    from kubeflow_tpu.scheduler import render_sched_text

    if not args.server:
        print("error: pass --server (the report needs the live ledger)",
              file=sys.stderr)
        return 2
    try:
        import urllib.request

        url = f"{args.server.rstrip('/')}/debug/sched"
        with urllib.request.urlopen(url, timeout=10) as r:
            report = json.loads(r.read())
    except (OSError, ValueError, KeyError, TypeError) as exc:
        # urllib errors (refused/404) and malformed server payloads land
        # here — one diagnostic line, never a traceback
        print(f"error: {exc!r}", file=sys.stderr)
        return 2
    out = json.dumps(report, indent=2) + "\n" if args.json \
        else render_sched_text(report)
    if args.output:
        Path(args.output).write_text(out)
        print(f"wrote {args.output}", file=sys.stderr)
    else:
        print(out, end="")
    return 0


def cmd_tokenize(args) -> int:
    """Train a BPE tokenizer from a text file (one document per line) and
    write tokenizer.json — pairs with `generate` and gpt-lm predictors."""
    from kubeflow_tpu.train.tokenizer import Tokenizer

    texts = [
        ln.strip() for ln in Path(args.input).read_text().splitlines()
        if ln.strip()
    ]
    if not texts:
        print(f"error: {args.input} has no non-empty lines", file=sys.stderr)
        return 2
    tok = Tokenizer.train(texts, vocab_size=args.vocab_size)
    tok.save(args.output)
    print(f"trained vocab={tok.vocab_size} merges={len(tok.merges)} "
          f"-> {args.output}")
    return 0


# ---------------------------------------------------------------------- main

def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="kubeflow_tpu", description="TPU-native ML platform CLI"
    )
    sub = ap.add_subparsers(dest="command", required=True)

    def add(name, fn, **kwargs):
        p = sub.add_parser(name, **kwargs)
        p.set_defaults(fn=fn)
        return p

    p = add("run", cmd_run, help="submit a TrainJob manifest and wait")
    p.add_argument("-f", "--filename", required=True, help="manifest ('-' = stdin)")
    p.add_argument("--logs", action="store_true", help="print replica logs at the end")
    p.add_argument("--timeout", type=float, default=3600.0)
    p.add_argument("--capacity-chips", type=int, default=8)
    p.add_argument("--log-dir", default=".kubeflow_tpu/pod-logs")

    p = add("validate", cmd_validate, help="admission-check a manifest")
    p.add_argument("-f", "--filename", required=True)

    p = add("render-env", cmd_render_env,
            help="print the synthesized rendezvous env for one replica")
    p.add_argument("-f", "--filename", required=True)
    p.add_argument("--rtype", default="worker")
    p.add_argument("--index", type=int, default=0)

    p = add("mpirun", cmd_mpirun,
            help="mpirun-shaped MPIJob launch: mpirun -np N -- cmd ...")
    p.add_argument("-np", type=int, default=2, help="number of workers")
    p.add_argument("--name", default="mpirun")
    p.add_argument("--timeout", type=float, default=3600.0)
    p.add_argument("--capacity-chips", type=int, default=8)
    p.add_argument("--log-dir", default=".kubeflow_tpu/pod-logs")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="command to run on the launcher (after --)")

    p = add("sweep", cmd_sweep, help="run an Experiment manifest")
    p.add_argument("-f", "--filename", required=True)
    p.add_argument("--timeout", type=float, default=3600.0)
    p.add_argument("--capacity-chips", type=int, default=8)
    p.add_argument("--resume-to", type=int, default=0,
                   help="after completion, resume with this maxTrialCount "
                        "(resumePolicy=LongRunning)")
    p.add_argument("--log-dir", default=".kubeflow_tpu/pod-logs")

    p = add("import-gpt2", cmd_import_gpt2,
            help="convert an HF/torch GPT-2 checkpoint into a "
                 "serving-ready gpt-lm predictor dir")
    p.add_argument("--checkpoint", required=True,
                   help="torch .pt/.bin with a GPT2(LMHead)Model state dict")
    p.add_argument("-o", "--out", required=True)
    p.add_argument("--num-heads", type=int, default=0,
                   help="attention head count (required unless the "
                        "checkpoint carries config.n_head — a bare state "
                        "dict does not determine it)")
    p.add_argument("--max-new-tokens", type=int, default=32)
    p.add_argument("--max-len", type=int, default=None)
    p.add_argument("--prompt-len", type=int, default=16)
    p.add_argument("--vocab-json", default=None,
                   help="HF vocab.json — with --merges-txt, bundles the "
                        "checkpoint's byte-level BPE as tokenizer.json")
    p.add_argument("--merges-txt", default=None)
    p.add_argument("--continuous-rows", type=int, default=0,
                   help="serve through the continuous-batching engine "
                        "with this many decode rows (0 = plain decode)")
    p.add_argument("--device", default="auto", choices=["tpu", "cpu", "auto"])

    p = add("import-llama", cmd_import_llama,
            help="convert an HF/torch Llama/Mistral checkpoint into a "
                 "serving-ready gpt-lm predictor dir (rope+GQA+RMSNorm+"
                 "SwiGLU family)")
    p.add_argument("--checkpoint", required=True,
                   help="torch .pt/.bin with a Llama/MistralForCausalLM "
                        "state dict")
    p.add_argument("-o", "--out", required=True)
    p.add_argument("--num-heads", type=int, default=0,
                   help="attention head count (required unless the "
                        "checkpoint carries config.num_attention_heads; "
                        "num_kv_heads is read off k_proj)")
    p.add_argument("--max-new-tokens", type=int, default=32)
    p.add_argument("--max-len", type=int, default=None)
    p.add_argument("--prompt-len", type=int, default=16)
    p.add_argument("--continuous-rows", type=int, default=0,
                   help="serve through the continuous-batching engine "
                        "with this many decode rows (0 = plain decode)")
    p.add_argument("--device", default="auto", choices=["tpu", "cpu", "auto"])

    p = add("import-bert", cmd_import_bert,
            help="convert an HF/torch BERT checkpoint into a "
                 "serving-ready bert-classifier predictor dir")
    p.add_argument("--checkpoint", required=True)
    p.add_argument("-o", "--out", required=True)
    p.add_argument("--num-heads", type=int, default=0)
    p.add_argument("--num-classes", type=int, default=0,
                   help="required for headless BertModel checkpoints")
    p.add_argument("--max-len", type=int, default=None)
    p.add_argument("--device", default="auto", choices=["tpu", "cpu", "auto"])

    p = add("tokenize", cmd_tokenize,
            help="train a BPE tokenizer from a text file")
    p.add_argument("--input", required=True, help="one document per line")
    p.add_argument("--vocab-size", type=int, default=8192)
    p.add_argument("-o", "--output", default="tokenizer.json")

    p = add("generate", cmd_generate,
            help="generate text/ids from a saved gpt-lm predictor dir")
    p.add_argument("--model-dir", required=True)
    p.add_argument("--prompt", required=True,
                   help="text (tokenizer.json in the dir) or token ids")
    p.add_argument("--device", default="auto", choices=["tpu", "cpu", "auto"])
    p.add_argument("--draft-model-dir", default="",
                   help="speculative decoding: a small gpt-lm predictor "
                        "dir proposing tokens the target verifies. "
                        "Greedy configs emit exactly the target's greedy "
                        "decode; temperature>0 configs run rejection "
                        "sampling (target-distribution-exact)")
    p.add_argument("--gamma", type=int, default=4,
                   help="speculated tokens per round")
    p.add_argument("--seed", type=int, default=0,
                   help="PRNG seed for sampled speculative decoding")

    p = add("profile", cmd_profile,
            help="step-time / goodput / control-plane breakdown from a "
                 "trace dir or a live platform (docs/profiling.md)")
    p.add_argument("--trace-dir", default="",
                   help="directory of trace exports (worker trace-*.json "
                        "flushes + a platform export / spans *.jsonl)")
    p.add_argument("--server", default="",
                   help="live platform URL — fetches /debug/profile")
    p.add_argument("--json", action="store_true",
                   help="emit the profile as JSON instead of the table")
    p.add_argument("-o", "--output", default="",
                   help="write the report to a file instead of stdout")

    p = add("slo", cmd_slo,
            help="SLO burn-rate report + per-request serving breakdown "
                 "from a live platform or a trace dir (docs/slo.md)")
    p.add_argument("--server", default="",
                   help="live platform URL — fetches /debug/slo")
    p.add_argument("--trace-dir", default="",
                   help="directory of trace exports (request breakdown "
                        "only; burn rates need a live monitor)")
    p.add_argument("--json", action="store_true",
                   help="emit the report as JSON instead of the table")
    p.add_argument("-o", "--output", default="",
                   help="write the report to a file instead of stdout")

    p = add("sched", cmd_sched,
            help="chip-scheduler report: inventory, claims, tenant "
                 "shares, preempt/deny counters (docs/scheduler.md)")
    p.add_argument("--server", default="",
                   help="live platform URL — fetches /debug/sched")
    p.add_argument("--json", action="store_true",
                   help="emit the report as JSON instead of the table")
    p.add_argument("-o", "--output", default="",
                   help="write the report to a file instead of stdout")

    p = add("serve", cmd_serve, help="serve an InferenceService until Ctrl-C")
    p.add_argument("-f", "--filename", required=True)
    p.add_argument("--timeout", type=float, default=300.0)
    p.add_argument("--log-dir", default=".kubeflow_tpu/pod-logs")

    p = add("pipeline-compile", cmd_pipeline_compile,
            help="compile a @pipeline function (module:fn) to IR YAML")
    p.add_argument("pipeline")
    p.add_argument("-o", "--output", default="")

    p = add("pipeline-run", cmd_pipeline_run, help="execute compiled IR")
    p.add_argument("-f", "--filename", required=True)
    p.add_argument("--arg", action="append", metavar="KEY=VALUE")
    p.add_argument("--work-dir", default=".kubeflow_tpu/pipelines")
    p.add_argument("--log-dir", default=".kubeflow_tpu/pod-logs")
    p.add_argument("--no-cache", action="store_true")

    p = add("platform", cmd_platform,
            help="run the control plane as a daemon with the REST API")
    p.add_argument("-f", "--kfdef", default="",
                   help="KfDef manifest (kfctl analogue) — overrides the "
                        "flag-based config below")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--capacity-chips", type=int, default=8)
    p.add_argument("--log-dir", default=".kubeflow_tpu/pod-logs")

    p = add("platform-init", cmd_platform_init,
            help="scaffold a kfdef.yaml deployment manifest (kfctl init)")
    p.add_argument("directory", nargs="?", default=".")

    def server_arg(p):
        p.add_argument("--server", default="http://127.0.0.1:8080",
                       help="platform API server URL")
        return p

    p = server_arg(add("apply", cmd_apply, help="create from a manifest (remote)"))
    p.add_argument("-f", "--filename", required=True)

    p = server_arg(add("pipeline-submit", cmd_pipeline_submit,
                       help="submit compiled IR to a remote platform and poll"))
    p.add_argument("-f", "--filename", required=True)
    p.add_argument("--name", default="pipelinerun")
    p.add_argument("--arg", action="append", metavar="KEY=VALUE")
    p.add_argument("-n", "--namespace", default="default")
    p.add_argument("--timeout", type=float, default=3600.0)

    p = server_arg(add("get", cmd_get, help="list/get objects (remote)"))
    p.add_argument("kind")
    p.add_argument("name", nargs="?", default="")
    p.add_argument("-n", "--namespace", default="default")
    p.add_argument("-A", "--all-namespaces", action="store_true")
    p.add_argument("-l", "--selector", default="",
                   help="label selector: k=v | k==v | k!=v, comma-ANDed")

    p = server_arg(add("logs", cmd_logs, help="print a job replica's log (remote)"))
    p.add_argument("name")
    p.add_argument("-f", "--follow", action="store_true",
                   help="stream appended log output until the pod finishes")
    p.add_argument("--rtype", default="worker")
    p.add_argument("--index", type=int, default=0)
    p.add_argument("-n", "--namespace", default="default")

    p = server_arg(add("delete", cmd_delete, help="delete an object (remote)"))
    p.add_argument("kind")
    p.add_argument("name")
    p.add_argument("-n", "--namespace", default="default")

    p = server_arg(add("scale", cmd_scale, help="elastically scale a job (remote)"))
    p.add_argument("name")
    p.add_argument("replicas", type=int)
    p.add_argument("-n", "--namespace", default="default")

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
