"""Profile — multi-tenancy namespaces + quotas.

Reference parity (unverified cites, SURVEY.md §2.7): kubeflow/kubeflow
components/profile-controller (+kfam): a `Profile` CR materializes a
namespace with RBAC and resource quotas. The UX layers (Istio policies,
dashboards) are out of scope (SURVEY.md §7); what this keeps is the
platform-semantic core: profile -> namespace lifecycle, per-namespace chip
quota enforced by the gang scheduler, and a max-jobs admission quota.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kubeflow_tpu.api.common import ObjectMeta
from kubeflow_tpu.controller.base import ControllerBase
from kubeflow_tpu.controller.fakecluster import FakeCluster


@dataclass
class ProfileQuota:
    # cap on simultaneously-bound chips for gangs in this namespace
    chips: int | None = None
    # cap on unfinished jobs admitted in this namespace
    max_jobs: int | None = None


@dataclass
class ProfileSpec:
    owner: str = ""
    quota: ProfileQuota = field(default_factory=ProfileQuota)


@dataclass
class Profile:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ProfileSpec = field(default_factory=ProfileSpec)
    kind: str = "Profile"
    api_version: str = "kubeflow-tpu.org/v1"


@dataclass
class Namespace:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    owner_profile: str = ""
    kind: str = "Namespace"


def namespace_quota(cluster: FakeCluster, namespace: str) -> ProfileQuota | None:
    """The quota governing a namespace (profile name == namespace name),
    or None when the namespace is unmanaged (unlimited)."""
    prof: Profile | None = cluster.get("profiles", f"default/{namespace}")
    return prof.spec.quota if prof is not None else None


def check_job_admission(cluster: FakeCluster, job) -> None:
    """max-jobs quota at admission (ResourceQuota object-count analogue).
    Raises ValueError when the namespace is at its cap."""
    quota = namespace_quota(cluster, job.metadata.namespace)
    if quota is None or quota.max_jobs is None:
        return
    active = [
        j for j in cluster.list("jobs")
        if j.metadata.namespace == job.metadata.namespace
        and not j.status.is_finished
    ]
    if len(active) >= quota.max_jobs:
        raise ValueError(
            f"namespace {job.metadata.namespace!r} is at its quota of "
            f"{quota.max_jobs} active job(s)"
        )


class ProfileController(ControllerBase):
    """Profile -> Namespace lifecycle."""

    WATCH_KINDS = ("profiles",)

    ERROR_EVENT_KIND = "profiles"

    def __init__(self, cluster: FakeCluster, workers: int = 1,
                 resync_period_s: float = 5.0):
        super().__init__(
            cluster, name="profile", workers=workers,
            resync_period_s=resync_period_s,
        )

    def kind_filter(self, etype, kind: str, obj) -> str | None:
        if kind == "profiles":
            return self.cluster._key(obj)
        return None

    def resync_keys(self):
        return [self.cluster._key(p) for p in self.cluster.list("profiles")]

    def reconcile(self, key: str) -> float | None:
        from kubeflow_tpu.controller.kfam import (
            AccessBinding,
            binding_name,
            bindings_for,
        )

        prof: Profile | None = self.cluster.get("profiles", key)
        name = key.split("/", 1)[1]
        ns_key = f"-/{name}"
        if prof is None:
            # profile gone -> release the namespace object and its access
            # bindings (running jobs are not killed; their cleanup stays
            # with their own controllers)
            self.cluster.delete("namespaces", ns_key)
            for b in bindings_for(self.cluster, name):
                self.cluster.delete("bindings", self.cluster._key(b))
            return None
        if self.cluster.get("namespaces", ns_key) is None:
            self.cluster.create(
                "namespaces",
                Namespace(
                    metadata=ObjectMeta(name=name, namespace="-"),
                    owner_profile=prof.metadata.name,
                ),
            )
            self.cluster.record_event(
                "profiles", key, "NamespaceCreated", f"namespace {name} ready"
            )
        # kfam parity: the profile owner holds the admin binding in their
        # namespace (upstream materializes this RoleBinding at profile
        # creation). Owner changes revoke the PREVIOUS owner's
        # reconciler-created binding — admin grants made through kfam are
        # not labeled and survive.
        owner_label = {"kubeflow-tpu.org/owned-by": "profile"}
        for b in bindings_for(self.cluster, name):
            if (b.metadata.labels.get("kubeflow-tpu.org/owned-by")
                    == "profile" and b.user != prof.spec.owner):
                self.cluster.delete("bindings", self.cluster._key(b))
        if prof.spec.owner:
            bname = binding_name(prof.spec.owner, "admin")
            if self.cluster.get("bindings", f"{name}/{bname}") is None:
                self.cluster.create(
                    "bindings",
                    AccessBinding(
                        metadata=ObjectMeta(name=bname, namespace=name,
                                            labels=dict(owner_label)),
                        user=prof.spec.owner, role="admin",
                    ),
                )
        return None
