"""ControllerBase — the shared reconciler scaffolding.

Reference parity: controller-runtime's manager/controller plumbing (informer
-> work queue -> reconcile workers with rate-limited requeue, plus periodic
resync), which every operator in the reference reuses rather than re-
implements (SURVEY.md §2.1 'Common JobController'). Subclasses provide:

  - kind_filter(etype, kind, obj) -> key | None   (what enqueues what)
  - resync_keys() -> iterable[str]                (periodic full resync)
  - reconcile(key) -> float | None                (the business logic)

ConflictError from optimistic-concurrency writes is treated as benign
(requeue, no error event) — the conflicting write's own watch event
re-triggers the key anyway.
"""

from __future__ import annotations

import threading
import time
from typing import Iterable

from kubeflow_tpu.analysis.lockcheck import make_lock
from kubeflow_tpu.controller.fakecluster import (
    ConflictError,
    FakeCluster,
    WatchPoller,
)
from kubeflow_tpu.native import ReconcileDriver, WorkQueue
from kubeflow_tpu.tracing import consume_delivered_context


class ControllerBase:
    #: object kind whose events carry reconcile errors (for record_event)
    ERROR_EVENT_KIND = "jobs"

    def __init__(
        self,
        cluster: FakeCluster,
        name: str,
        workers: int = 1,
        resync_period_s: float = 5.0,
        wq_base_delay_s: float = 0.005,
        wq_max_delay_s: float = 10.0,
    ):
        self.cluster = cluster
        self.name = name
        self.wq = WorkQueue(base_delay_s=wq_base_delay_s, max_delay_s=wq_max_delay_s)
        self.resync_period_s = resync_period_s
        self._stop = threading.Event()
        self._n_workers = workers
        self.metrics: dict[str, int] = {
            "reconcile_total": 0,
            "reconcile_errors_total": 0,
            # a broken watch subscription in the informer loop
            "informer_errors_total": 0,
            # record_event failures while reporting a reconcile error
            "event_record_failures_total": 0,
        }
        # reconcile-duration histogram (controller-runtime parity,
        # SURVEY §5.5). += on these is read-modify-write, NOT atomic:
        # multiple native workers run the Python callback concurrently,
        # so observation and the render-time snapshot take this lock
        self.latency_buckets: tuple[float, ...] = (
            0.001, 0.005, 0.025, 0.1, 0.5, 2.5, 10.0, 60.0)
        self.latency_counts = [0] * (len(self.latency_buckets) + 1)
        self.latency_sum = 0.0
        self._latency_mu = make_lock("base.ControllerBase._latency_mu")
        #: key -> SpanContext of the watch event that (last) enqueued it —
        #: the reconcile span's parent link. Only populated while a tracer
        #: is attached to the cluster; single writer (the informer thread),
        #: readers pop under the GIL.
        self._trigger_ctx: dict[str, object] = {}

    # ------------------------------------------------------ subclass hooks

    def kind_filter(self, etype, kind: str, obj) -> str | None:
        """Map a watch event to a reconcile key (None = ignore)."""
        raise NotImplementedError

    def resync_keys(self) -> Iterable[str]:
        """Keys to re-enqueue every resync period."""
        raise NotImplementedError

    def reconcile(self, key: str) -> float | None:
        """One level-triggered pass; optional requeue delay in seconds."""
        raise NotImplementedError

    def observe_event(self, etype, kind: str, obj) -> None:
        """Optional extra event bookkeeping (e.g. expectations)."""

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        threading.Thread(
            target=self._watch_loop, name=f"{self.name}-informer", daemon=True
        ).start()
        # workers are NATIVE: reconciler.cc owns the thread pool and the
        # forget/requeue/rate-limit/done discipline (SURVEY.md §2.8 item 2 —
        # the reference's worker goroutines are native too); only
        # self.reconcile(key) runs in Python, via the callback below
        self._driver = ReconcileDriver(self.wq, self._n_workers, self._reconcile_cb)
        threading.Thread(
            target=self._resync_loop, name=f"{self.name}-resync", daemon=True
        ).start()

    def stop(self) -> None:
        self._stop.set()
        self.wq.shutdown()
        if getattr(self, "_driver", None) is not None:
            # close (join + free), not just stop: the driver's callback keeps
            # this controller strongly reachable until freed
            self._driver.close()
            self._driver = None

    # ----------------------------------------------------------- internals

    def _observe_latency(self, seconds: float) -> None:
        from kubeflow_tpu.utils.prom import observe

        with self._latency_mu:
            observe(self.latency_buckets, self.latency_counts, seconds)
            self.latency_sum += seconds

    def latency_snapshot(self) -> tuple[list[int], float]:
        """(bucket counts, sum) read consistently for /metrics."""
        with self._latency_mu:
            return list(self.latency_counts), self.latency_sum

    def _watch_loop(self) -> None:
        def count_error():
            self.metrics["informer_errors_total"] += 1

        poller = WatchPoller(self.cluster, timeout=0.2,
                             count_error=count_error)
        while not self._stop.is_set():
            ev = poller.get()
            if ev is None:
                continue
            etype, kind, obj = ev
            ctx = (consume_delivered_context()
                   if self.cluster.tracer is not None else None)
            self.observe_event(etype, kind, obj)
            key = self.kind_filter(etype, kind, obj)
            if key is not None:
                if ctx is not None:
                    if len(self._trigger_ctx) > 4096:  # leak backstop
                        self._trigger_ctx.clear()
                    self._trigger_ctx[key] = ctx
                self.wq.add(key)

    def _resync_loop(self) -> None:
        while not self._stop.wait(self.resync_period_s):
            for key in self.resync_keys():
                self.wq.add(key)

    def _reconcile_cb(self, key_b: bytes, after_ptr) -> int:
        """The Python half of the native worker loop (reconciler.cc):
        business logic + metrics/events only — queue discipline is C++'s.
        Must never raise: ctypes would swallow the exception and report
        rc=0 (success), silently forgetting a failing key.

        With a tracer attached, each pass runs inside a `reconcile` span
        parented to the watch event that enqueued the key (resync passes
        are roots) — everything the pass writes inherits that context."""
        key = key_b.decode()
        tracer = self.cluster.tracer
        if tracer is None:
            return self._reconcile_pass(key, after_ptr, None)
        with tracer.span("reconcile", parent=self._trigger_ctx.pop(key, None),
                         controller=self.name, key=key,
                         # pending keys at pass start: the profiler's
                         # reconcile-serialization signal (a controller
                         # whose depth grows while p99 holds is
                         # queue-bound, not pass-bound)
                         queue_depth=len(self.wq)) as sp:
            return self._reconcile_pass(key, after_ptr, sp)

    def _reconcile_pass(self, key: str, after_ptr, sp) -> int:
        t0 = time.perf_counter()
        try:
            self.metrics["reconcile_total"] += 1
            requeue_after = self.reconcile(key)
            after_ptr[0] = -1.0 if requeue_after is None else float(requeue_after)
            if sp is not None and requeue_after is not None:
                sp.set_attribute("requeue_after_s", round(requeue_after, 4))
            return 0
        except ConflictError:
            if sp is not None:
                sp.set_attribute("outcome", "conflict")
            return 1
        except Exception as exc:  # noqa: BLE001 — reconcile must not die
            self.metrics["reconcile_errors_total"] += 1
            if sp is not None:
                sp.set_attribute("error", f"{type(exc).__name__}: {exc}")
            try:
                self.cluster.record_event(
                    self.ERROR_EVENT_KIND, key, "ReconcileError", str(exc),
                    type="Warning",
                )
            except Exception:  # noqa: BLE001 — reporting must not mask exc
                # countable, not silent: a failing event sink would
                # otherwise hide every reconcile error after the first
                self.metrics["event_record_failures_total"] += 1
            return 2
        finally:
            # one observation on EVERY exit path (_observe_latency cannot
            # raise: pure arithmetic under its own lock)
            self._observe_latency(time.perf_counter() - t0)
