"""ControllerBase — the shared reconciler scaffolding.

Reference parity: controller-runtime's manager/controller plumbing (informer
-> work queue -> reconcile workers with rate-limited requeue, plus periodic
resync), which every operator in the reference reuses rather than re-
implements (SURVEY.md §2.1 'Common JobController'). Subclasses provide:

  - kind_filter(etype, kind, obj) -> key | None   (what enqueues what)
  - resync_keys() -> iterable[str]                (periodic full resync)
  - reconcile(key) -> float | None                (the business logic)

ConflictError from optimistic-concurrency writes is treated as benign
(requeue, no error event) — the conflicting write's own watch event
re-triggers the key anyway.

Worker model (docs/architecture.md "Control-plane scaling"): with
``workers=N`` the controller runs a KEYED pool — N native work queues,
each drained by its own single-worker ReconcileDriver, with
``crc32(key) % N`` routing every add. Distinct objects reconcile
concurrently while any one object's passes stay strictly serialized on
one worker (each queue also keeps the native dedupe/dirty-replay
discipline per key). ``workers=1`` degenerates to exactly the old single
queue + single driver.
"""

from __future__ import annotations

import threading
import time
import zlib
from typing import Iterable

from kubeflow_tpu.analysis.lockcheck import make_lock
from kubeflow_tpu.controller.fakecluster import (
    ConflictError,
    FakeCluster,
    WatchPoller,
)
from kubeflow_tpu.native import RECONCILE_CB, ReconcileDriver, WorkQueue
from kubeflow_tpu.tracing import consume_delivered_context


class KeyedWorkQueuePool:
    """N rate-limited work queues with stable key->queue routing, each
    drained by one native worker: the per-key ordering contract of a
    single-worker controller, at N-way concurrency across keys.

    crc32 (not builtin hash) so the shard a key lands on is stable across
    processes and runs — requeue storms replay identically under seeded
    chaos. API mirrors the single WorkQueue it replaces (add/add_after/
    forget/num_requeues/len/shutdown), so callers don't care which they
    hold."""

    def __init__(self, n_queues: int, base_delay_s: float, max_delay_s: float):
        self.queues = [
            WorkQueue(base_delay_s=base_delay_s, max_delay_s=max_delay_s)
            for _ in range(max(1, n_queues))
        ]
        self._drivers: list[ReconcileDriver] = []

    def _route(self, key: str) -> WorkQueue:
        if len(self.queues) == 1:
            return self.queues[0]
        return self.queues[zlib.crc32(key.encode()) % len(self.queues)]

    # -- WorkQueue-shaped API (key-routed)

    def add(self, key: str) -> None:
        self._route(key).add(key)

    def add_after(self, key: str, delay_s: float) -> None:
        self._route(key).add_after(key, delay_s)

    def add_rate_limited(self, key: str) -> float:
        return self._route(key).add_rate_limited(key)

    def forget(self, key: str) -> None:
        self._route(key).forget(key)

    def num_requeues(self, key: str) -> int:
        return self._route(key).num_requeues(key)

    def __len__(self) -> int:
        return sum(len(q) for q in self.queues)

    def depths(self) -> list[int]:
        """Pending keys per worker queue (kftpu_cplane_worker_queue_depth):
        a skewed profile means hot keys are hashing onto one worker."""
        return [len(q) for q in self.queues]

    def shutdown(self) -> None:
        for q in self.queues:
            q.shutdown()

    @property
    def shutting_down(self) -> bool:
        return all(q.shutting_down for q in self.queues)

    # -- driver lifecycle

    def start_drivers(self, callback) -> None:
        """One single-worker native driver per queue; ONE shared ctypes
        trampoline (the callback object must outlive every driver — each
        ReconcileDriver's finalizer keeps a reference)."""
        cb = callback if isinstance(callback, RECONCILE_CB) \
            else RECONCILE_CB(callback)
        self._drivers = [ReconcileDriver(q, 1, cb) for q in self.queues]

    def close_drivers(self) -> None:
        for d in self._drivers:
            d.close()
        self._drivers = []


class ControllerBase:
    #: object kind whose events carry reconcile errors (for record_event)
    ERROR_EVENT_KIND = "jobs"

    #: kinds this controller's informer subscribes to — a SERVER-SIDE
    #: filter (the native hub never buffers other kinds for it), so a storm
    #: on unrelated kinds costs it nothing. None = the legacy full stream.
    #: kind_filter() remains the authoritative event->key mapper either way.
    WATCH_KINDS: tuple[str, ...] | None = None

    #: optional per-kind label selectors ({kind: {label: value-or-None}}),
    #: pushed into the hub alongside the kind filter: a controller that
    #: only acts on pods carrying its ownership label (JOB_NAME_LABEL
    #: class) stops paying for every other pod's status churn — at 10k
    #: pods that client-side discard was the fan-out ceiling. Takes
    #: precedence over WATCH_KINDS when set (its keys ARE the kinds).
    WATCH_SELECTORS: dict[str, dict | None] | None = None

    def __init__(
        self,
        cluster: FakeCluster,
        name: str,
        workers: int = 1,
        resync_period_s: float = 5.0,
        wq_base_delay_s: float = 0.005,
        wq_max_delay_s: float = 10.0,
    ):
        self.cluster = cluster
        self.name = name
        self.wq = KeyedWorkQueuePool(
            workers, base_delay_s=wq_base_delay_s, max_delay_s=wq_max_delay_s)
        self.resync_period_s = resync_period_s
        self._stop = threading.Event()
        self._n_workers = workers
        self.metrics: dict[str, int] = {
            "reconcile_total": 0,
            "reconcile_errors_total": 0,
            # a broken watch subscription in the informer loop
            "informer_errors_total": 0,
            # record_event failures while reporting a reconcile error
            "event_record_failures_total": 0,
        }
        # reconcile-duration histogram (controller-runtime parity,
        # SURVEY §5.5). += on these is read-modify-write, NOT atomic:
        # multiple native workers run the Python callback concurrently,
        # so observation and the render-time snapshot take this lock
        self.latency_buckets: tuple[float, ...] = (
            0.001, 0.005, 0.025, 0.1, 0.5, 2.5, 10.0, 60.0)
        self.latency_counts = [0] * (len(self.latency_buckets) + 1)
        self.latency_sum = 0.0
        self._latency_mu = make_lock("base.ControllerBase._latency_mu")
        #: key -> SpanContext of the watch event that (last) enqueued it —
        #: the reconcile span's parent link. Only populated while a tracer
        #: is attached to the cluster; single writer (the informer thread),
        #: readers pop under the GIL.
        self._trigger_ctx: dict[str, object] = {}

    # ------------------------------------------------------ subclass hooks

    def kind_filter(self, etype, kind: str, obj) -> str | None:
        """Map a watch event to a reconcile key (None = ignore)."""
        raise NotImplementedError

    def resync_keys(self) -> Iterable[str]:
        """Keys to re-enqueue every resync period."""
        raise NotImplementedError

    def reconcile(self, key: str) -> float | None:
        """One level-triggered pass; optional requeue delay in seconds."""
        raise NotImplementedError

    def observe_event(self, etype, kind: str, obj) -> None:
        """Optional extra event bookkeeping (e.g. expectations)."""

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        threading.Thread(
            target=self._watch_loop, name=f"{self.name}-informer", daemon=True
        ).start()
        # workers are NATIVE: reconciler.cc owns the threads and the
        # forget/requeue/rate-limit/done discipline (SURVEY.md §2.8 item 2 —
        # the reference's worker goroutines are native too); only
        # self.reconcile(key) runs in Python, via the callback below. One
        # driver per pool queue = the keyed-ordering contract.
        self.wq.start_drivers(self._reconcile_cb)
        threading.Thread(
            target=self._resync_loop, name=f"{self.name}-resync", daemon=True
        ).start()

    def stop(self) -> None:
        self._stop.set()
        self.wq.shutdown()
        # close (join + free), not just stop: each driver's callback keeps
        # this controller strongly reachable until freed
        self.wq.close_drivers()

    # ----------------------------------------------------------- internals

    def _observe_latency(self, seconds: float) -> None:
        from kubeflow_tpu.utils.prom import observe

        with self._latency_mu:
            observe(self.latency_buckets, self.latency_counts, seconds)
            self.latency_sum += seconds

    def latency_snapshot(self) -> tuple[list[int], float]:
        """(bucket counts, sum) read consistently for /metrics."""
        with self._latency_mu:
            return list(self.latency_counts), self.latency_sum

    def _watch_loop(self) -> None:
        def count_error():
            self.metrics["informer_errors_total"] += 1

        poller = WatchPoller(self.cluster, timeout=0.2,
                             count_error=count_error,
                             kinds=self.WATCH_KINDS,
                             selectors=self.WATCH_SELECTORS)
        while not self._stop.is_set():
            ev = poller.get()
            if ev is None:
                continue
            etype, kind, obj = ev
            ctx = (consume_delivered_context()
                   if self.cluster.tracer is not None else None)
            self.observe_event(etype, kind, obj)
            key = self.kind_filter(etype, kind, obj)
            if key is not None:
                if ctx is not None:
                    if len(self._trigger_ctx) > 4096:  # leak backstop
                        self._trigger_ctx.clear()
                    self._trigger_ctx[key] = ctx
                self.wq.add(key)

    def _resync_loop(self) -> None:
        while not self._stop.wait(self.resync_period_s):
            for key in self.resync_keys():
                self.wq.add(key)

    def _reconcile_cb(self, key_b: bytes, after_ptr) -> int:
        """The Python half of the native worker loop (reconciler.cc):
        business logic + metrics/events only — queue discipline is C++'s.
        Must never raise: ctypes would swallow the exception and report
        rc=0 (success), silently forgetting a failing key.

        With a tracer attached, each pass runs inside a `reconcile` span
        parented to the watch event that enqueued the key (resync passes
        are roots) — everything the pass writes inherits that context."""
        key = key_b.decode()
        tracer = self.cluster.tracer
        if tracer is None:
            return self._reconcile_pass(key, after_ptr, None)
        with tracer.span("reconcile", parent=self._trigger_ctx.pop(key, None),
                         controller=self.name, key=key,
                         # pending keys at pass start: the profiler's
                         # reconcile-serialization signal (a controller
                         # whose depth grows while p99 holds is
                         # queue-bound, not pass-bound)
                         queue_depth=len(self.wq)) as sp:
            return self._reconcile_pass(key, after_ptr, sp)

    def _reconcile_pass(self, key: str, after_ptr, sp) -> int:
        t0 = time.perf_counter()
        try:
            self.metrics["reconcile_total"] += 1
            requeue_after = self.reconcile(key)
            after_ptr[0] = -1.0 if requeue_after is None else float(requeue_after)
            if sp is not None and requeue_after is not None:
                sp.set_attribute("requeue_after_s", round(requeue_after, 4))
            return 0
        except ConflictError:
            if sp is not None:
                sp.set_attribute("outcome", "conflict")
            return 1
        except Exception as exc:  # noqa: BLE001 — reconcile must not die
            self.metrics["reconcile_errors_total"] += 1
            if sp is not None:
                sp.set_attribute("error", f"{type(exc).__name__}: {exc}")
            try:
                self.cluster.record_event(
                    self.ERROR_EVENT_KIND, key, "ReconcileError", str(exc),
                    type="Warning",
                )
            except Exception:  # noqa: BLE001 — reporting must not mask exc
                # countable, not silent: a failing event sink would
                # otherwise hide every reconcile error after the first
                self.metrics["event_record_failures_total"] += 1
            return 2
        finally:
            # one observation on EVERY exit path (_observe_latency cannot
            # raise: pure arithmetic under its own lock)
            self._observe_latency(time.perf_counter() - t0)
