"""ControllerBase — the shared reconciler scaffolding.

Reference parity: controller-runtime's manager/controller plumbing (informer
-> work queue -> reconcile workers with rate-limited requeue, plus periodic
resync), which every operator in the reference reuses rather than re-
implements (SURVEY.md §2.1 'Common JobController'). Subclasses provide:

  - kind_filter(etype, kind, obj) -> key | None   (what enqueues what)
  - resync_keys() -> iterable[str]                (periodic full resync)
  - reconcile(key) -> float | None                (the business logic)

ConflictError from optimistic-concurrency writes is treated as benign
(requeue, no error event) — the conflicting write's own watch event
re-triggers the key anyway.
"""

from __future__ import annotations

import threading
from typing import Iterable

from kubeflow_tpu.controller.fakecluster import ConflictError, FakeCluster
from kubeflow_tpu.native import WorkQueue


class ControllerBase:
    #: object kind whose events carry reconcile errors (for record_event)
    ERROR_EVENT_KIND = "jobs"

    def __init__(
        self,
        cluster: FakeCluster,
        name: str,
        workers: int = 1,
        resync_period_s: float = 5.0,
        wq_base_delay_s: float = 0.005,
        wq_max_delay_s: float = 10.0,
    ):
        self.cluster = cluster
        self.name = name
        self.wq = WorkQueue(base_delay_s=wq_base_delay_s, max_delay_s=wq_max_delay_s)
        self.resync_period_s = resync_period_s
        self._stop = threading.Event()
        self._n_workers = workers
        self.metrics: dict[str, int] = {
            "reconcile_total": 0,
            "reconcile_errors_total": 0,
        }

    # ------------------------------------------------------ subclass hooks

    def kind_filter(self, etype, kind: str, obj) -> str | None:
        """Map a watch event to a reconcile key (None = ignore)."""
        raise NotImplementedError

    def resync_keys(self) -> Iterable[str]:
        """Keys to re-enqueue every resync period."""
        raise NotImplementedError

    def reconcile(self, key: str) -> float | None:
        """One level-triggered pass; optional requeue delay in seconds."""
        raise NotImplementedError

    def observe_event(self, etype, kind: str, obj) -> None:
        """Optional extra event bookkeeping (e.g. expectations)."""

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        threading.Thread(
            target=self._watch_loop, name=f"{self.name}-informer", daemon=True
        ).start()
        for i in range(self._n_workers):
            threading.Thread(
                target=self._worker_loop, name=f"{self.name}-worker-{i}", daemon=True
            ).start()
        threading.Thread(
            target=self._resync_loop, name=f"{self.name}-resync", daemon=True
        ).start()

    def stop(self) -> None:
        self._stop.set()
        self.wq.shutdown()

    # ----------------------------------------------------------- internals

    def _watch_loop(self) -> None:
        q = self.cluster.watch()
        while not self._stop.is_set():
            try:
                etype, kind, obj = q.get(timeout=0.2)
            except Exception:  # queue.Empty only
                continue
            self.observe_event(etype, kind, obj)
            key = self.kind_filter(etype, kind, obj)
            if key is not None:
                self.wq.add(key)

    def _resync_loop(self) -> None:
        while not self._stop.wait(self.resync_period_s):
            for key in self.resync_keys():
                self.wq.add(key)

    def _worker_loop(self) -> None:
        while True:
            key = self.wq.get(timeout_s=0.5)
            if key is None:
                if self.wq.shutting_down:
                    return
                continue
            try:
                self.metrics["reconcile_total"] += 1
                requeue_after = self.reconcile(key)
                self.wq.forget(key)
                if requeue_after is not None:
                    self.wq.add_after(key, requeue_after)
            except ConflictError:
                self.wq.add_rate_limited(key)
            except Exception as exc:  # noqa: BLE001 — reconcile must not die
                self.metrics["reconcile_errors_total"] += 1
                self.cluster.record_event(
                    self.ERROR_EVENT_KIND, key, "ReconcileError", str(exc),
                    type="Warning",
                )
                self.wq.add_rate_limited(key)
            finally:
                self.wq.done(key)
