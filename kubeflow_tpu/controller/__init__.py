"""Controller layer: reconcilers, gang scheduling, env injection.

Reference parity: training-operator pkg/controller.v1/* (Go reconcilers over
controller-runtime — unverified cites, SURVEY.md §2.1). Here the reconcile
core's hot bookkeeping (work queue, expectations) is native C++
(kubeflow_tpu/native) with Python policy on top.
"""
