"""PodDefault — label-selected pod mutation at admission.

Reference parity (unverified cites, SURVEY.md §2.7): kubeflow/kubeflow
components/admission-webhook — the `PodDefault` CR + mutating webhook that
injects env/volumes/annotations into pods whose labels match the selector.
Here the mutation happens at the moment a controller creates a pod (the
admission point of this control plane).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kubeflow_tpu.api.common import ObjectMeta
from kubeflow_tpu.controller.fakecluster import FakeCluster, Pod


@dataclass
class PodDefaultSpec:
    # pods whose labels contain ALL of these match (matchLabels semantics)
    selector: dict[str, str] = field(default_factory=dict)
    # injected iff the pod doesn't already set the key (user/contract wins)
    env: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    description: str = ""


@dataclass
class PodDefault:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodDefaultSpec = field(default_factory=PodDefaultSpec)
    kind: str = "PodDefault"
    api_version: str = "kubeflow-tpu.org/v1alpha1"


def matches(pd: PodDefault, pod: Pod) -> bool:
    if pd.metadata.namespace != pod.metadata.namespace:
        return False
    sel = pd.spec.selector
    return bool(sel) and all(
        pod.metadata.labels.get(k) == v for k, v in sel.items()
    )


def apply_pod_defaults(cluster: FakeCluster, pod: Pod) -> list[str]:
    """Mutate `pod` in place with every matching PodDefault; returns the
    names applied (recorded as a pod annotation, like the webhook does)."""
    applied: list[str] = []
    for pd in cluster.list("poddefaults"):
        if not matches(pd, pod):
            continue
        for k, v in pd.spec.env.items():
            pod.env.setdefault(k, v)
        for k, v in pd.spec.annotations.items():
            pod.metadata.annotations.setdefault(k, v)
        applied.append(pd.metadata.name)
    if applied:
        pod.metadata.annotations["kubeflow-tpu.org/poddefaults"] = ",".join(
            sorted(applied)
        )
    return applied
