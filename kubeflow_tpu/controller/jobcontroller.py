"""JobController — the generic gang reconciler for every job kind.

Reference parity (unverified cites, SURVEY.md §2.1): the common JobController
(pkg/controller.v1/common/{job_controller.go, job.go#ReconcileJobs,
pod.go#ReconcilePods, expectation.go}) that TFJob/PyTorchJob/... reconcilers
share. Level-triggered: watch events only enqueue keys; reconcile() computes
desired state from scratch each pass. The hot bookkeeping (work queue with
per-key backoff, expectations) is the native C++ core.

TPU gang semantics: a non-elastic SPMD gang cannot lose a process — any
worker failure triggers a whole-gang restart from checkpoint (bounded by
runPolicy.backoffLimit), not a single-pod restart (SURVEY.md §5.3).
"""

from __future__ import annotations

import os
import time

from kubeflow_tpu.api.common import (
    CleanPodPolicy,
    JobConditionType,
    ReplicaStatus,
    RestartPolicy,
    is_retryable_exit_code,
    utcnow as _now_ts,
)
from kubeflow_tpu.api.jobs import SUCCESS_REPLICA, JobKind, TrainJob, REPLICA_WORKER
from kubeflow_tpu.api.common import ObjectMeta
from kubeflow_tpu.controller.base import ControllerBase
from kubeflow_tpu.controller.envcontract import synthesize_env
from kubeflow_tpu.controller.fakecluster import (
    ConflictError,
    EventType,
    FakeCluster,
    Pod,
    PodGroup,
    PodPhase,
)
from kubeflow_tpu.controller.poddefault import apply_pod_defaults
from kubeflow_tpu.health import (
    ENV_HEARTBEAT_FILE,
    HUNG_POD_EXIT_CODE,
    DeadVerdict,
    LivenessConfig,
    LivenessDetector,
    heartbeat_path,
    job_heartbeat_dir,
)
from kubeflow_tpu.native import Expectations
from kubeflow_tpu.runtime.rendezvous import LocalResolver
from kubeflow_tpu.tracing import ENV_TRACE_DIR, ENV_TRACEPARENT, current_context
from kubeflow_tpu.utils.envvars import ENV_COMPILE_CACHE_DIR, ENV_STATE_DIR
from kubeflow_tpu.utils.retry import BackoffPolicy, with_conflict_retry

JOB_NAME_LABEL = "kubeflow-tpu.org/job-name"
REPLICA_TYPE_LABEL = "kubeflow-tpu.org/replica-type"
REPLICA_INDEX_LABEL = "kubeflow-tpu.org/replica-index"
# World size the pod's env contract was synthesized for. SPMD cannot change
# world size live: any mismatch with the current spec forces a whole-gang
# re-mesh (elastic scale event), never an in-place patch.
WORLD_SIZE_LABEL = "kubeflow-tpu.org/world-size"

#: gang-restart requeue schedule (crashloop-backoff analogue): the Nth
#: restart of a job waits ~2x longer before its recreate pass, so a crash
#: storm cannot hot-loop pod churn. Jittered so simultaneous gang restarts
#: (e.g. after a node loss) don't stampede the scheduler in lockstep.
RESTART_BACKOFF = BackoffPolicy(base_s=0.05, max_s=2.0, jitter=0.5)


class JobController(ControllerBase):
    """Reconciles every job in the cluster. Start one per process."""

    # every job, but only pods this controller owns: unlabeled pod
    # storms (serving, notebooks, bare runs) cost it nothing. The keys
    # are also the kind filter (WATCH_SELECTORS subsumes WATCH_KINDS).
    WATCH_SELECTORS = {"jobs": None, "pods": {JOB_NAME_LABEL: None}}

    def __init__(
        self,
        cluster: FakeCluster,
        workers: int = 1,
        resync_period_s: float = 5.0,
        local_rewrite: bool = True,
        liveness: LivenessConfig | None = None,
        heartbeat_dir: str = "",
        compile_cache_dir: str = "",
    ):
        super().__init__(
            cluster, name="job", workers=workers, resync_period_s=resync_period_s
        )
        self.exp = Expectations(ttl_s=30.0)
        self.local_rewrite = local_rewrite
        # liveness layer (docs/health.md): lease/straggler failure detector
        # + where worker heartbeat files live; pods get the per-incarnation
        # path via the env contract (ENV_HEARTBEAT_FILE)
        self.liveness = LivenessDetector(liveness)
        self.heartbeat_dir = heartbeat_dir or os.path.join(
            os.environ.get(ENV_STATE_DIR, ".kubeflow_tpu"), "heartbeats"
        )
        # persistent XLA compile cache shared by EVERY incarnation of every
        # job (entries are content-keyed, so sharing one dir is safe): a
        # gang-restarted worker replays its train-step executables instead
        # of re-tracing+recompiling (utils/compile_cache.py, docs/perf.md)
        self.compile_cache_dir = compile_cache_dir or os.path.join(
            os.environ.get(ENV_STATE_DIR, ".kubeflow_tpu"), "compile-cache"
        )
        self._resolvers: dict[str, LocalResolver] = {}
        # prometheus-style counters (SURVEY.md §5.5)
        self.metrics.update({
            "jobs_created_total": 0,
            "jobs_succeeded_total": 0,
            "jobs_failed_total": 0,
            "jobs_restarted_total": 0,
            "jobs_remeshed_total": 0,
            "pods_created_total": 0,
            "pods_deleted_total": 0,
            # recovery observability (chaos drills assert on these): how many
            # jobs came back from >=1 restart, how many reconcile passes and
            # restarts that recovery consumed — the measurable shape of the
            # gang-restart-from-checkpoint contract
            "jobs_recovered_total": 0,
            "recovery_reconcile_passes_total": 0,
            "recovery_restarts_consumed_total": 0,
        })
        #: per-job reconcile passes spent since its first restart; folded
        #: into recovery_* counters when the job reaches Succeeded
        self._recovery_passes: dict[str, int] = {}

    # -------------------------------------------------------------- informer

    def observe_event(self, etype, kind: str, obj) -> None:
        if kind != "pods":
            return
        job_name = obj.metadata.labels.get(JOB_NAME_LABEL)
        if not job_name:
            return
        key = f"{obj.metadata.namespace}/{job_name}"
        if etype == EventType.ADDED:
            self.exp.creation_observed(key)
        elif etype == EventType.DELETED:
            self.exp.deletion_observed(key)

    def kind_filter(self, etype, kind: str, obj) -> str | None:
        if kind == "jobs":
            return self.cluster._key(obj)
        if kind == "pods":
            job_name = obj.metadata.labels.get(JOB_NAME_LABEL)
            if job_name:
                return f"{obj.metadata.namespace}/{job_name}"
        return None

    def resync_keys(self):
        return [self.cluster._key(j) for j in self.cluster.list("jobs")]

    # ------------------------------------------------------------- reconcile

    def reconcile(self, key: str) -> float | None:
        """One level-triggered pass. Returns optional requeue delay.

        Works on a deep snapshot of the job (read-copy-update): every
        status write goes through cluster.update, which rejects the write
        with ConflictError if a client mutated the spec mid-pass — the pass
        is then simply retried against fresh state. This is the same
        optimistic-concurrency discipline the reference controllers get from
        the k8s apiserver's resourceVersion.
        """
        job: TrainJob | None = self.cluster.get("jobs", key, copy_obj=True)
        if job is None:
            # GC analogue: reap anything that outlived (or raced) a deleted
            # job — a reconcile pass holding a pre-delete snapshot may create
            # pods after delete_job_cascade ran; their create events re-queue
            # this key and land here
            ns, name = key.split("/", 1)
            for p in self.cluster.list(
                "pods",
                lambda p: p.metadata.labels.get(JOB_NAME_LABEL) == name
                and p.metadata.namespace == ns,
            ):
                self.cluster.delete("pods", p.key)
            self.cluster.delete("podgroups", key)
            self.exp.delete(key)
            self.wq.forget(key)
            self._resolvers.pop(key, None)
            self._recovery_passes.pop(key, None)
            self._reap_heartbeats(ns, name)
            return None

        st = job.status
        if st.restart_count and not st.is_finished:
            # recovery in progress: every pass until the terminal condition
            # counts toward the job's convergence cost
            self._recovery_passes[key] = self._recovery_passes.get(key, 0) + 1
        entry_fp = _status_fingerprint(st)
        if not st.conditions:
            # persist-then-emit: a ConflictError before the persist must not
            # have incremented counters or recorded events (replay hazard)
            st.set_condition(JobConditionType.CREATED, "JobCreated")
            job = self.cluster.update("jobs", job)
            st = job.status
            entry_fp = _status_fingerprint(st)
            self.metrics["jobs_created_total"] += 1
            self.cluster.record_event("jobs", key, "JobCreated", "created")

        pods = self._owned_pods(job)

        # -- terminal state: cleanup, TTL
        if st.is_finished:
            return self._cleanup_finished(job, key, pods)

        # -- suspension (runPolicy.suspend)
        if job.spec.run_policy.suspend:
            if pods:
                self._delete_pods(key, pods)
            self._delete_podgroup(job)
            self._resolvers.pop(key, None)
            if not st.has_condition(JobConditionType.SUSPENDED):
                st.set_condition(JobConditionType.SUSPENDED, "JobSuspended")
                self.cluster.update("jobs", job)
            return None
        if st.has_condition(JobConditionType.SUSPENDED):
            st.set_condition(JobConditionType.RESTARTING, "JobResumed")
            self.cluster.update("jobs", job)

        # -- active deadline
        rp = job.spec.run_policy
        if rp.active_deadline_seconds and st.start_time:
            age = time.time() - _parse_ts(st.start_time)
            if age > rp.active_deadline_seconds:
                self._fail(job, key, pods, "DeadlineExceeded",
                           f"active for {age:.0f}s > {rp.active_deadline_seconds}s")
                return None

        # -- stale-cache guard: wait out pending create/deletes
        if not self.exp.satisfied(key):
            return 0.05

        # -- elastic re-mesh: pods built for a different world size must all
        # go; the gang restarts at the new size from checkpoint (slice-
        # granular scaling, SURVEY.md §2.2/§5.3)
        if pods and self._needs_remesh(job, pods):
            st.set_condition(
                JobConditionType.RESTARTING,
                "ElasticRemesh",
                f"re-meshing gang to {job.total_replicas()} replicas",
            )
            self.cluster.update("jobs", job)
            tracer = self.cluster.tracer  # single read: races stop_tracing
            if tracer is not None:
                tracer.event(
                    "job.elastic_remesh", key=key,
                    world_size=job.total_replicas(),
                )
            self._delete_pods(key, pods)
            self._delete_podgroup(job)
            self._resolvers.pop(key, None)
            self.metrics["jobs_remeshed_total"] += 1
            self.cluster.record_event(
                "jobs", key, "ElasticRemesh",
                f"scale -> {job.total_replicas()} replicas (gang re-mesh)",
            )
            return 0.05

        # -- liveness: a hung worker never reaches FAILED on its own — the
        # lease/straggler detector marks it, then the normal gang-restart
        # path below takes over on the requeued pass
        if self.liveness.config.enabled and self._check_liveness(job, key, pods):
            return 0.0

        # -- failure handling (gang semantics)
        failed = [p for p in pods if p.status.phase == PodPhase.FAILED]
        if failed:
            return self._handle_failures(job, key, pods, failed)

        # -- success detection
        if self._is_succeeded(job, pods):
            st.set_condition(JobConditionType.SUCCEEDED, "JobSucceeded")
            st.completion_time = _now_ts()
            self._update_replica_statuses(job, pods)
            self.cluster.update("jobs", job)
            self.metrics["jobs_succeeded_total"] += 1
            if st.restart_count:
                # the job survived faults: record what the recovery cost
                self.metrics["jobs_recovered_total"] += 1
                self.metrics["recovery_restarts_consumed_total"] += st.restart_count
                self.metrics["recovery_reconcile_passes_total"] += (
                    self._recovery_passes.pop(key, 0)
                )
            self.cluster.record_event("jobs", key, "JobSucceeded", "completed")
            return 0.0  # immediate cleanup pass

    # -- pod/podgroup creation
        created = self._reconcile_pods(job, key, pods)

        if st.start_time is None:
            st.start_time = _now_ts()
        running = [p for p in pods if p.status.phase == PodPhase.RUNNING]
        if running and len(running) == job.total_replicas():
            if not st.has_condition(JobConditionType.RUNNING):
                st.set_condition(JobConditionType.RUNNING, "JobRunning")
                self.cluster.record_event("jobs", key, "JobRunning", "all replicas running")
        self._update_replica_statuses(job, pods)
        # only publish a MODIFIED event on real change — an unconditional
        # update would re-enqueue this key via the informer and turn every
        # live job into a self-triggering hot reconcile loop
        if _status_fingerprint(st) != entry_fp:
            st.last_reconcile_time = _now_ts()
            self.cluster.update("jobs", job)
        if created:
            return 0.2
        # lease cadence: while MONITORED workers run (heartbeat file exists
        # — the same opt-in-by-behavior rule the detector applies), re-check
        # liveness a few times per timeout window instead of waiting out the
        # 5s resync, which would make small timeouts undetectable within
        # their own window. Never-beating legacy jobs stay on resync cadence.
        if self.liveness.config.enabled and any(
            p.status.phase == PodPhase.RUNNING
            and (hb := p.env.get(ENV_HEARTBEAT_FILE))
            and os.path.exists(hb)
            for p in pods
        ):
            return self.liveness.config.requeue_delay()
        return None

    # ---------------------------------------------------------- sub-steps

    def _needs_remesh(self, job: TrainJob, pods: list[Pod]) -> bool:
        """True when any live pod's env contract was synthesized for a world
        size other than the spec's current one. Pods predating the label are
        grandfathered; a fully-succeeded gang is left to success detection."""
        if all(p.status.phase == PodPhase.SUCCEEDED for p in pods):
            return False
        want = str(job.total_replicas())
        return any(
            p.metadata.labels.get(WORLD_SIZE_LABEL, want) != want for p in pods
        )

    def _owned_pods(self, job: TrainJob) -> list[Pod]:
        return self.cluster.list(
            "pods",
            lambda p: p.metadata.labels.get(JOB_NAME_LABEL) == job.metadata.name
            and p.metadata.namespace == job.metadata.namespace,
        )

    def _reconcile_pods(self, job: TrainJob, key: str, pods: list[Pod]) -> int:
        existing = {
            (
                p.metadata.labels.get(REPLICA_TYPE_LABEL),
                int(p.metadata.labels.get(REPLICA_INDEX_LABEL, -1)),
            )
            for p in pods
        }
        to_create: list[tuple[str, int]] = []
        for rtype, rs in job.spec.replica_specs.items():
            for i in range(rs.replicas):
                if (rtype, i) not in existing:
                    to_create.append((rtype, i))
        if not to_create:
            return 0

        tracer = self.cluster.tracer
        if tracer is None:
            return self._create_pods(job, key, to_create, None)
        with tracer.span("job.create_pods", key=key, count=len(to_create),
                         world_size=job.total_replicas(),
                         restart=job.status.restart_count):
            return self._create_pods(job, key, to_create, tracer)

    def _create_pods(self, job: TrainJob, key: str,
                     to_create: list[tuple[str, int]], tracer) -> int:
        self._ensure_podgroup(job)
        # The resolver must persist across passes within one gang incarnation
        # (pods created in different passes need identical port maps), but a
        # stale one — built for a different replica set, e.g. after a
        # suspend -> scale -> resume — would leave new hostnames unrewritten.
        resolver = self._resolvers.get(key)
        if resolver is None or _replica_signature(resolver.job) != _replica_signature(job):
            resolver = LocalResolver(job)
            self._resolvers[key] = resolver
            if tracer is not None:
                # the port-map build IS local rendezvous setup: every pod of
                # this incarnation connects through the endpoints fixed here
                tracer.event("job.rendezvous", key=key,
                             world_size=job.total_replicas())
        if job.kind == JobKind.MPI:
            self._materialize_hostfile(job, resolver)
        # trace context rides the env contract into the pods: workers join
        # the creating pass's trace and flush spans to the shared trace_dir
        trace_env: dict[str, str] = {}
        if tracer is not None and tracer.trace_dir:
            trace_env[ENV_TRACE_DIR] = tracer.trace_dir
            ctx = current_context()
            if ctx is not None:
                trace_env[ENV_TRACEPARENT] = ctx.to_header()
        self.exp.expect_creations(key, len(to_create))
        for rtype, i in to_create:
            env = synthesize_env(job, rtype, i)
            if self.local_rewrite:
                env = resolver.rewrite_env(env)
            env.update(trace_env)
            # liveness contract: a per-INCARNATION heartbeat path (the
            # restart count is baked into the name, so a restarted gang is
            # never judged by its predecessor's stale file). setdefault: a
            # user-supplied path wins, like the rest of the env contract.
            env.setdefault(ENV_HEARTBEAT_FILE, heartbeat_path(
                self.heartbeat_dir, job.metadata.namespace,
                job.metadata.name, job.replica_name(rtype, i),
                job.status.restart_count,
            ))
            # restart-warm compile contract: unlike the heartbeat path the
            # cache dir is NOT per-incarnation — surviving the restart is
            # the whole point (the restarted worker's warm_start hits it)
            env.setdefault(ENV_COMPILE_CACHE_DIR, self.compile_cache_dir)
            c = job.spec.replica_specs[rtype].template.container
            # job-level labels (e.g. the experiment label) propagate to pods,
            # mirroring k8s template-label propagation
            labels = {**job.metadata.labels, **job.labels(rtype, i)}
            labels[WORLD_SIZE_LABEL] = str(job.total_replicas())
            pod = Pod(
                metadata=ObjectMeta(
                    name=job.replica_name(rtype, i),
                    namespace=job.metadata.namespace,
                    labels=labels,
                ),
                command=list(c.command) + list(c.args),
                env=env,
                working_dir=c.working_dir,
                scheduler_name=job.spec.replica_specs[rtype].template.scheduler_name,
                group_name=job.metadata.name,
            )
            apply_pod_defaults(self.cluster, pod)  # admission mutation
            self.cluster.create("pods", pod)
            self.metrics["pods_created_total"] += 1
        return len(to_create)

    def _materialize_hostfile(self, job: TrainJob, resolver) -> None:
        """Write the MPI hostfile to its per-job path before any pod starts —
        the ConfigMap-mount analogue (SURVEY.md §2.1 MPIJob row). Pods find
        it via OMPI_MCA_orte_default_hostfile (envcontract.mpi_env)."""
        from pathlib import Path

        from kubeflow_tpu.controller.envcontract import (
            mpi_hostfile,
            mpi_hostfile_path,
        )

        content = mpi_hostfile(job)
        if self.local_rewrite:
            content = resolver.rewrite_text(content)
        path = Path(mpi_hostfile_path(job))
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(content)

    def _ensure_podgroup(self, job: TrainJob) -> None:
        pg_key = f"{job.metadata.namespace}/{job.metadata.name}"
        if self.cluster.get("podgroups", pg_key) is not None:
            return
        sp = job.spec.run_policy.scheduling_policy
        # Clamp to the current total: a stale min_available above the post-
        # scale-down replica count would make the gang unsatisfiable forever.
        total = job.total_replicas()
        from kubeflow_tpu.controller.gang import resolve_priority, topology_chips

        topo = sp.slice_topology if sp else ""
        pg = PodGroup(
            metadata=ObjectMeta(
                name=job.metadata.name, namespace=job.metadata.namespace
            ),
            min_member=(min(sp.min_available, total) if sp and sp.min_available else total),
            queue=sp.queue if sp else "default",
            slice_topology=topo,
            # a multislice job reserves num_slices whole slices
            chips=topology_chips(topo) * max(job.spec.num_slices, 1),
            priority=resolve_priority(sp.priority_class if sp else ""),
        )
        self.cluster.create("podgroups", pg)

    def _check_liveness(self, job: TrainJob, key: str, pods: list[Pod]) -> int:
        """Run the lease/straggler detector over this gang and mark every
        verdict's pod FAILED. Returns how many pods were declared dead —
        the caller requeues immediately so the SAME gang-restart machinery
        that handles crashes handles hangs."""
        declared = 0
        for v in self.liveness.check(pods):
            if self._declare_pod_dead(key, v):
                declared += 1
        return declared

    def _declare_pod_dead(self, key: str, v: DeadVerdict) -> bool:
        """Conflict-retried, incarnation-guarded FAILED write for one
        liveness verdict, inside a health.* span whose context rides the
        pod object (CARRIER_ANNOTATION) — the gang restart parent-links to
        the detection, exactly like it links to a crash's exit span."""
        tracer = self.cluster.tracer
        span_name = (
            "health.lease_expired" if v.reason == "LivenessLeaseExpired"
            else "health.straggler"
        )

        def declare(carrier: str) -> bool:
            def attempt():
                cur = self.cluster.get("pods", v.key, copy_obj=True)
                if cur is None or cur.metadata.uid != v.uid:
                    return None
                if cur.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
                    return None  # raced a real exit: its verdict wins
                cur.status.phase = PodPhase.FAILED
                cur.status.exit_code = HUNG_POD_EXIT_CODE
                cur.status.finish_time = time.time()
                cur.status.message = f"{v.reason}: {v.message}"
                if carrier:
                    from kubeflow_tpu.tracing import CARRIER_ANNOTATION

                    cur.metadata.annotations[CARRIER_ANNOTATION] = carrier
                return self.cluster.update("pods", cur)

            try:
                return with_conflict_retry(attempt) is not None
            except (ConflictError, KeyError):
                return False  # churned away mid-declaration; next pass re-checks

        if tracer is None:
            ok = declare("")
        else:
            with tracer.span(span_name, pod=v.key, uid=v.uid,
                             heartbeat_age_s=round(v.heartbeat_age_s, 3),
                             step=v.step) as sp:
                ctx = sp.context
                ok = declare(ctx.to_header() if ctx is not None else "")
                sp.set_attribute("declared", ok)
        if ok:
            self.liveness.bump("pods_declared_dead_total")
            self.liveness.bump(
                "leases_expired_total"
                if v.reason == "LivenessLeaseExpired"
                else "stragglers_declared_total")
            self.cluster.record_event(
                "pods", v.key, v.reason, v.message, type="Warning")
            self.cluster.record_event(
                "jobs", key, v.reason,
                f"{v.key}: {v.message}", type="Warning")
        return ok

    def _handle_failures(
        self, job: TrainJob, key: str, pods: list[Pod], failed: list[Pod]
    ) -> float | None:
        st = job.status
        rp = job.spec.run_policy
        # Elastic jobs budget restarts via ElasticPolicy.max_restarts
        # (torchelastic PET_MAX_RESTARTS parity); others via backoff_limit.
        limit = (
            rp.elastic_policy.max_restarts
            if rp.elastic_policy is not None
            else rp.backoff_limit
        )
        # Decide retryability from each failed pod's replica restart policy.
        retryable = True
        for p in failed:
            rtype = p.metadata.labels.get(REPLICA_TYPE_LABEL, REPLICA_WORKER)
            rs = job.spec.replica_specs.get(rtype)
            policy = rs.restart_policy if rs else RestartPolicy.NEVER
            if policy == RestartPolicy.NEVER:
                retryable = False
            elif policy == RestartPolicy.EXIT_CODE:
                if not is_retryable_exit_code(p.status.exit_code or 1):
                    retryable = False
        if not retryable or st.restart_count >= limit:
            reason = (
                "BackoffLimitExceeded"
                if retryable
                else "NonRetryableExit"
            )
            self._fail(job, key, pods,
                       reason,
                       f"{len(failed)} replica(s) failed "
                       f"(restarts={st.restart_count}/{limit})")
            return None
        # gang restart: tear down ALL pods, restart from checkpoint.
        # Persist the incremented count BEFORE deleting pods: a conflict here
        # retries cleanly, whereas deleting first and conflicting after would
        # lose the increment and grant a free restart.
        st.restart_count += 1
        st.set_condition(
            JobConditionType.RESTARTING,
            "GangRestart",
            f"restart {st.restart_count}/{limit}",
        )
        self.cluster.update("jobs", job)
        tracer = self.cluster.tracer  # single read: races stop_tracing,
        # and an exception here would retry a pass that ALREADY committed
        # the restart_count increment (double-charging backoff_limit)
        if tracer is not None:
            from kubeflow_tpu.tracing import CARRIER_ANNOTATION, SpanContext

            # parent = the failed pod's exit span (carried on the object),
            # NOT this pass's trigger: multiple watch events coalesce into
            # one pass, but the restart is causally the failure's child
            cause = next(
                (SpanContext.from_header(
                    p.metadata.annotations.get(CARRIER_ANNOTATION, ""))
                 for p in failed
                 if p.metadata.annotations.get(CARRIER_ANNOTATION)),
                None,
            )
            attrs = dict(key=key, restart=st.restart_count, limit=limit,
                         failed=len(failed))
            if cause is not None:
                tracer.event("job.gang_restart", parent=cause, **attrs)
            else:
                tracer.event("job.gang_restart", **attrs)
        self._delete_pods(key, pods)
        self._delete_podgroup(job)
        self.metrics["jobs_restarted_total"] += 1
        self.cluster.record_event(
            "jobs", key, "GangRestart",
            f"worker failure -> gang restart {st.restart_count}",
            type="Warning",
        )
        # Nth restart waits exponentially longer before the recreate pass
        # (shared jittered-backoff policy — no more fixed 50ms hot requeue)
        return RESTART_BACKOFF.delay_for(st.restart_count - 1)

    def _is_succeeded(self, job: TrainJob, pods: list[Pod]) -> bool:
        by = {
            (
                p.metadata.labels.get(REPLICA_TYPE_LABEL),
                int(p.metadata.labels.get(REPLICA_INDEX_LABEL, -1)),
            ): p
            for p in pods
        }
        def all_workers_succeeded() -> bool:
            workers = job.spec.replica_specs.get(REPLICA_WORKER)
            n = workers.replicas if workers else 0
            if n == 0:
                return False
            return all(
                (w := by.get((REPLICA_WORKER, i))) is not None
                and w.status.phase == PodPhase.SUCCEEDED
                for i in range(n)
            )

        if job.kind == JobKind.JAX:
            return all_workers_succeeded()
        success_rtype = SUCCESS_REPLICA[job.kind]
        rs = job.spec.replica_specs.get(success_rtype)
        if rs is None or rs.replicas == 0:
            # present-but-empty decider spec falls back exactly like
            # LocalRunner (runtime/local.py): worker-0 decides — a
            # 0-replica chief never gets a pod, so waiting on it would
            # leave the job unfinishable
            success_rtype = REPLICA_WORKER
        p = by.get((success_rtype, 0))
        decider_done = p is not None and p.status.phase == PodPhase.SUCCEEDED
        if job.spec.success_policy != "AllWorkers":
            return decider_done
        # TFJob successPolicy=AllWorkers: the decider AND every worker
        # replica must complete (passive PS-style replicas excluded)
        return decider_done and all_workers_succeeded()

    def _cleanup_finished(
        self, job: TrainJob, key: str, pods: list[Pod]
    ) -> float | None:
        policy = job.spec.run_policy.clean_pod_policy
        if policy == CleanPodPolicy.ALL:
            doomed = pods
        elif policy == CleanPodPolicy.RUNNING:
            doomed = [
                p for p in pods
                if p.status.phase in (PodPhase.RUNNING, PodPhase.PENDING)
            ]
        else:
            doomed = []
        if doomed:
            self._delete_pods(key, doomed)
        self._delete_podgroup(job)
        ttl = job.spec.run_policy.ttl_seconds_after_finished
        if ttl is not None and job.status.completion_time:
            age = time.time() - _parse_ts(job.status.completion_time)
            if age >= ttl:
                self.cluster.delete("jobs", key)
                self._reap_heartbeats(
                    job.metadata.namespace, job.metadata.name)
                return None
            return ttl - age
        return None

    def _reap_heartbeats(self, namespace: str, name: str) -> None:
        """Remove a deleted job's heartbeat subtree — incarnation files are
        small but unbounded over crashloops, and a stale file must never
        greet a later same-named job (the pid gate would filter it, but the
        disk growth would not filter itself)."""
        import shutil

        shutil.rmtree(
            job_heartbeat_dir(self.heartbeat_dir, namespace, name),
            ignore_errors=True,
        )

    def _fail(
        self, job: TrainJob, key: str, pods: list[Pod], reason: str, msg: str
    ) -> None:
        job.status.set_condition(JobConditionType.FAILED, reason, msg)
        job.status.completion_time = _now_ts()
        self._update_replica_statuses(job, pods)
        self.cluster.update("jobs", job)
        self.metrics["jobs_failed_total"] += 1
        self._recovery_passes.pop(key, None)  # recovery lost, not converged
        self.cluster.record_event("jobs", key, reason, msg, type="Warning")

    def _delete_pods(self, key: str, pods: list[Pod]) -> None:
        if not pods:
            return
        self.exp.expect_deletions(key, len(pods))
        for p in pods:
            self.cluster.delete("pods", p.key)
            self.metrics["pods_deleted_total"] += 1

    def _delete_podgroup(self, job: TrainJob) -> None:
        self.cluster.delete(
            "podgroups", f"{job.metadata.namespace}/{job.metadata.name}"
        )

    def _update_replica_statuses(self, job: TrainJob, pods: list[Pod]) -> None:
        stats: dict[str, ReplicaStatus] = {}
        for rtype in job.spec.replica_specs:
            stats[rtype] = ReplicaStatus(
                selector=f"{JOB_NAME_LABEL}={job.metadata.name},"
                f"{REPLICA_TYPE_LABEL}={rtype}"
            )
        for p in pods:
            rtype = p.metadata.labels.get(REPLICA_TYPE_LABEL)
            if rtype not in stats:
                continue
            ph = p.status.phase
            if ph in (PodPhase.RUNNING, PodPhase.PENDING):
                stats[rtype].active += 1
            elif ph == PodPhase.SUCCEEDED:
                stats[rtype].succeeded += 1
            elif ph == PodPhase.FAILED:
                stats[rtype].failed += 1
        job.status.replica_statuses = stats


def delete_job_cascade(cluster: FakeCluster, name: str, namespace: str = "default") -> None:
    """Tear down a job and everything it owns (pods, podgroup) — the shared
    delete path for the SDK client, sweep engine, and anything else that
    removes jobs out-of-band."""
    key = f"{namespace}/{name}"
    for p in cluster.list(
        "pods",
        lambda p: p.metadata.labels.get(JOB_NAME_LABEL) == name
        and p.metadata.namespace == namespace,
    ):
        cluster.delete("pods", p.key)
    cluster.delete("podgroups", key)
    cluster.delete("jobs", key)


def _replica_signature(job: TrainJob) -> tuple:
    """Identity of a job's rendezvous-relevant shape: if this changes, the
    old incarnation's resolver/port map no longer covers the replica set."""
    return (
        tuple(sorted((rt, rs.replicas) for rt, rs in job.spec.replica_specs.items())),
        job.spec.coordinator_port,
    )


def _status_fingerprint(st) -> tuple:
    """Hashable snapshot of the reconcile-relevant status (excludes
    last_reconcile_time, which must never itself trigger an update)."""
    return (
        tuple((c.type, c.status, c.reason, c.message) for c in st.conditions),
        tuple(
            (rt, rs.active, rs.succeeded, rs.failed)
            for rt, rs in sorted(st.replica_statuses.items())
        ),
        st.start_time,
        st.completion_time,
        st.restart_count,
    )


def _parse_ts(ts: str) -> float:
    import datetime

    return datetime.datetime.strptime(ts, "%Y-%m-%dT%H:%M:%SZ").replace(
        tzinfo=datetime.timezone.utc
    ).timestamp()
