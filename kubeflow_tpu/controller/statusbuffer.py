"""StatusWriteBuffer — leader-combined per-pod status writes.

At 10k pods the kubelet layer's status transitions (bind, Running,
finished) were the control plane's write amplifier: every transition was
its own conflict-retried read-copy-update round trip — two shard-lock
acquisitions, a full deepcopy, and a retry loop racing every other
writer. This buffer coalesces them with a COMBINING scheme (flat-combining
/ group-commit): a writer that arrives while no flush is running becomes
the LEADER and applies everything pending — its own op plus whatever
concurrent writers enqueued — through ``FakeCluster.batch_update`` under
one lock hold; the others just wait for their ack. No dedicated flusher
thread: a solo writer IS its own leader and pays zero cross-thread
handoff (measured: a worker->flusher->worker Event round trip costs more
than the write it carries), while a storm's writers fold into each
other's batches automatically (docs/architecture.md "Control-plane
scaling").

Contract preserved from the per-op path it replaces:

  - **incarnation guard** — an op carries the uid it was aimed at; the
    mutate runs only if the stored pod still IS that incarnation (and may
    itself decline on fresh state by returning False);
  - **ordering** — ops flush in enqueue order, so a writer that stamps
    ``CARRIER_ANNOTATION`` before a phase transition keeps that order;
  - **conflict-retry** — injected ConflictErrors (chaos.on_update) route
    the op through the classic single-op conflict-retried path, so the
    PR-1 drill class still exercises real retry machinery;
  - **causality** — each op captures its writer's SpanContext at enqueue
    and the batch publishes it with the MODIFIED event, so reconcile
    spans parent-link exactly as if the writer had called update()
    itself.

Writers touch only ``pod.status`` and ``pod.metadata.annotations`` —
that's what makes the cheap targeted copy safe; anything else must go
through ``read_modify_write``.
"""

from __future__ import annotations

import copy
import dataclasses
import threading
from typing import Any, Callable

from kubeflow_tpu.analysis.lockcheck import make_lock
from kubeflow_tpu.controller.fakecluster import ConflictError, FakeCluster
from kubeflow_tpu.tracing import current_context
from kubeflow_tpu.utils.retry import with_conflict_retry


def pod_status_copier(pod: Any) -> Any:
    """RCU copy specialized to status writers: fresh status + metadata
    (with its own annotations dict), everything else — command, env,
    labels — shared with the stored object, which nobody mutates in
    place. ~5x cheaper than deepcopy, and the deepcopy inside the write
    lock was the single largest term in the 10k-pod storm profile."""
    meta = dataclasses.replace(
        pod.metadata, annotations=dict(pod.metadata.annotations))
    return dataclasses.replace(
        pod, metadata=meta, status=copy.copy(pod.status))


class _Op:
    __slots__ = ("key", "uid", "mutate", "ctx", "done", "ok", "exc")

    def __init__(self, key: str, uid: str, mutate, ctx):
        self.key = key
        self.uid = uid
        self.mutate = mutate
        self.ctx = ctx
        self.done = threading.Event()
        self.ok = False
        self.exc: BaseException | None = None


class StatusWriteBuffer:
    """Combining group-commit over batch_update: sync-ack writes, one
    shard-lock hold per batch, no background thread."""

    #: a leader this far gone is treated as wedged; the follower reclaims
    #: its op (if still pending) and applies it through the single path
    ACK_TIMEOUT_S = 30.0

    def __init__(self, cluster: FakeCluster, kind: str = "pods",
                 max_batch: int = 256,
                 copier: Callable[[Any], Any] | None = pod_status_copier):
        self.cluster = cluster
        self.kind = kind
        self.max_batch = max_batch
        self.copier = copier
        self._mu = make_lock("statusbuffer.StatusWriteBuffer._mu")
        self._pending: list[_Op] = []
        self._leading = False
        self.metrics: dict[str, int] = {
            "writes_total": 0,
            "flushes_total": 0,
            # writes that shared their flush with at least one other write
            # (the coalescing win the batching exists for)
            "coalesced_writes_total": 0,
            # chaos-injected conflicts routed through the single-op path
            "conflict_fallbacks_total": 0,
            # close()-time batches that failed to apply (teardown races a
            # dying store) — countable, never silent
            "teardown_flush_failures_total": 0,
        }

    # ------------------------------------------------------------- writers

    def write(self, key: str, uid: str, mutate_status) -> bool:
        """Apply ``mutate_status`` to the stored object iff it is still
        incarnation ``uid`` (empty uid = don't guard). True when applied;
        False when the object is gone, replaced, or the mutator declined.
        Raises ConflictError only when the chaos-conflict fallback path
        exhausts its retry budget — same surface as read_modify_write."""
        chaos = self.cluster.chaos
        if chaos is not None:
            try:
                # the same injection point update() honors, fired per
                # logical write: batching must not make injected conflict
                # storms invisible
                chaos.on_update(self.kind, key)
            except ConflictError:
                with self._mu:
                    self.metrics["writes_total"] += 1
                    self.metrics["conflict_fallbacks_total"] += 1
                return self._write_single(key, uid, mutate_status)
        ctx = (current_context()
               if self.cluster.tracer is not None else None)
        op = _Op(key, uid, mutate_status, ctx)
        with self._mu:
            self.metrics["writes_total"] += 1
            self._pending.append(op)
            lead = not self._leading
            if lead:
                self._leading = True
        if not lead:
            # a leader is flushing: it will drain us before it steps down
            if op.done.wait(self.ACK_TIMEOUT_S):
                return self._result(op)
            # wedged leader: reclaim the op if it was never drained and
            # apply it ourselves — applied once, never twice or zero times
            with self._mu:
                mine = op in self._pending
                if mine:
                    self._pending.remove(op)
            if mine:
                return self._write_single(key, uid, mutate_status)
            op.done.wait()  # drained: the ack WILL come
            return self._result(op)
        # leader: drain until nothing is pending — ops enqueued while we
        # flush have no other leader, so stepping down early would strand
        # them until their timeout
        batch: list[_Op] = []
        try:
            while True:
                with self._mu:
                    batch = self._pending[:self.max_batch]
                    del self._pending[:len(batch)]
                    if not batch:
                        self._leading = False
                        break
                    self.metrics["flushes_total"] += 1
                    if len(batch) > 1:
                        self.metrics["coalesced_writes_total"] += len(batch)
                self._flush(batch)
        except BaseException:
            # never leave the buffer leaderless with ops pending, and
            # never abandon an EXTRACTED batch unacked: an async
            # exception landing between drain and _flush would otherwise
            # strand those followers past even their wedge timeout (the
            # ops are no longer in _pending, so reclaim can't find them)
            with self._mu:
                self._leading = False
            for o in batch:
                o.done.set()  # ok stays False: not applied
            raise
        return self._result(op)

    @staticmethod
    def _result(op: _Op) -> bool:
        if op.exc is not None:
            raise op.exc  # the op's own mutator raised (rmw parity)
        return op.ok

    def _write_single(self, key: str, uid: str, mutate_status) -> bool:
        """The classic per-op conflict-retried read-copy-update — the
        fallback that keeps injected conflict storms exercising real
        retry machinery."""

        def attempt():
            obj = self.cluster.get(self.kind, key, copy_obj=True)
            if obj is None or (uid and obj.metadata.uid != uid):
                return None
            if mutate_status(obj) is False:
                return None
            return self.cluster.update(self.kind, obj)

        try:
            return with_conflict_retry(attempt) is not None
        except KeyError:
            return False

    # ------------------------------------------------------------- combine

    def _guard(self, op: _Op):
        def mutate(obj):
            if op.uid and obj.metadata.uid != op.uid:
                return False  # stale incarnation: never stamp the new one
            return op.mutate(obj)

        return mutate

    def _flush(self, batch: list[_Op]) -> None:
        try:
            results = self.cluster.batch_update(
                self.kind,
                [(op.key, self._guard(op), op.ctx) for op in batch],
                copier=self.copier,
            )
            for op, res in zip(batch, results):
                if isinstance(res, BaseException):
                    # the op's own mutator raised: surface it to ITS
                    # writer (read_modify_write parity), not the batch
                    op.exc = res
                else:
                    op.ok = res is not None
        finally:
            # acks on EVERY path: a follower must never hang on our error
            for op in batch:
                op.done.set()

    def close(self) -> None:
        """Apply anything still pending (teardown stragglers)."""
        while True:
            with self._mu:
                batch = self._pending[:self.max_batch]
                del self._pending[:len(batch)]
                if batch:
                    self.metrics["flushes_total"] += 1
            if not batch:
                break
            try:
                self._flush(batch)
            except Exception:  # noqa: BLE001 — teardown must not raise
                with self._mu:
                    self.metrics["teardown_flush_failures_total"] += 1
