"""TensorboardController — Tensorboard CR -> a live TensorBoard process.

Reference parity (unverified cites, SURVEY.md §2.7, §5.1): kubeflow/kubeflow
components/tensorboard-controller — a `Tensorboard` CR materializes a
TensorBoard Deployment over a logdir. Here the deployment is a pod running
`python -m tensorboard.main`, with the same readiness/self-heal treatment
serving predictors get.
"""

from __future__ import annotations

import sys
import urllib.request
from dataclasses import dataclass, field

from kubeflow_tpu.api.common import ObjectMeta
from kubeflow_tpu.controller.base import ControllerBase
from kubeflow_tpu.controller.fakecluster import FakeCluster, Pod, PodPhase
from kubeflow_tpu.runtime.rendezvous import free_port

TB_LABEL = "kubeflow-tpu.org/tensorboard"
PORT_ANNOTATION = "kubeflow-tpu.org/serving-port"


@dataclass
class TensorboardSpec:
    logdir: str = ""


@dataclass
class TensorboardStatus:
    ready: bool = False
    url: str = ""


@dataclass
class Tensorboard:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: TensorboardSpec = field(default_factory=TensorboardSpec)
    status: TensorboardStatus = field(default_factory=TensorboardStatus)
    kind: str = "Tensorboard"
    api_version: str = "kubeflow-tpu.org/v1alpha1"


def _probe(url: str, timeout_s: float = 0.5) -> bool:
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as r:
            return r.status == 200
    except Exception:  # noqa: BLE001 — any failure = not ready
        return False


class TensorboardController(ControllerBase):
    WATCH_SELECTORS = {"tensorboards": None, "pods": {TB_LABEL: None}}
    ERROR_EVENT_KIND = "tensorboards"

    def __init__(self, cluster: FakeCluster, workers: int = 1,
                 resync_period_s: float = 2.0):
        super().__init__(
            cluster, name="tb", workers=workers, resync_period_s=resync_period_s,
        )

    def kind_filter(self, etype, kind: str, obj) -> str | None:
        if kind == "tensorboards":
            return self.cluster._key(obj)
        if kind == "pods":
            name = obj.metadata.labels.get(TB_LABEL)
            if name:
                return f"{obj.metadata.namespace}/{name}"
        return None

    def resync_keys(self):
        return [self.cluster._key(t) for t in self.cluster.list("tensorboards")]

    def reconcile(self, key: str) -> float | None:
        tb: Tensorboard | None = self.cluster.get("tensorboards", key, copy_obj=True)
        ns, _, name = key.partition("/")
        pods = self.cluster.list(
            "pods",
            lambda p: p.metadata.labels.get(TB_LABEL) == name
            and p.metadata.namespace == ns,
        )
        if tb is None:
            for p in pods:
                self.cluster.delete("pods", p.key)
            return None

        # self-heal exited servers
        for p in pods:
            if p.status.phase in (PodPhase.FAILED, PodPhase.SUCCEEDED):
                self.cluster.delete("pods", p.key)
        pods = [p for p in pods if p.status.phase in (PodPhase.PENDING, PodPhase.RUNNING)]
        if not pods:
            self._create_pod(tb)
            return 0.5

        pod = pods[0]
        port = pod.metadata.annotations.get(PORT_ANNOTATION, "")
        url = f"http://127.0.0.1:{port}" if port else ""
        ready = pod.status.phase == PodPhase.RUNNING and bool(url) and _probe(url)
        if (ready, url if ready else "") != (tb.status.ready, tb.status.url):
            tb.status.ready = ready
            tb.status.url = url if ready else ""
            self.cluster.update("tensorboards", tb)
            if ready:
                self.cluster.record_event(
                    "tensorboards", key, "Ready", f"tensorboard at {url}"
                )
        return None if ready else 0.5

    @staticmethod
    def _command(logdir: str, port: int) -> list:
        """Real TensorBoard when its CLI can actually start; otherwise the
        built-in tfevents viewer (controller/tbviewer.py) — same readiness
        contract, same files, zero extra dependencies. TensorBoard's CLI
        needs pkg_resources, which not every image ships (this one doesn't),
        and a Tensorboard CR must still produce a live URL."""
        import importlib.util

        if (
            importlib.util.find_spec("tensorboard") is not None
            and importlib.util.find_spec("pkg_resources") is not None
        ):
            return [
                sys.executable, "-m", "tensorboard.main",
                "--logdir", logdir,
                "--port", str(port),
                "--host", "127.0.0.1",
                "--load_fast", "false",
            ]
        return [
            sys.executable, "-m", "kubeflow_tpu.controller.tbviewer",
            "--logdir", logdir,
            "--port", str(port),
            "--host", "127.0.0.1",
        ]

    def _create_pod(self, tb: Tensorboard) -> None:
        port = free_port()
        pod = Pod(
            metadata=ObjectMeta(
                name=f"{tb.metadata.name}-tensorboard-0",
                namespace=tb.metadata.namespace,
                labels={TB_LABEL: tb.metadata.name},
                annotations={PORT_ANNOTATION: str(port)},
            ),
            command=self._command(tb.spec.logdir, port),
            scheduler_name="default",
        )
        try:
            self.cluster.create("pods", pod)
        except KeyError:
            pass
