"""Notebook + PVCViewer controllers — long-lived dev-server CRs.

Reference parity (unverified cites, SURVEY.md §2.7):
  - kubeflow/kubeflow components/notebook-controller: `Notebook` CR ->
    StatefulSet + Service running a Jupyter/VSCode image. Here the CR runs a
    dev-server process (user-specified command, defaulting to a stdlib HTTP
    file server over the workspace — no Jupyter in this environment) with
    the same readiness probing + self-heal the tensorboard controller has.
  - components/pvcviewer-controller: `PVCViewer` CR -> file-browser
    Deployment over a PVC. Here it serves the volume directory over HTTP.

Both reuse one ServerCRController base: CR -> pod with an injected port,
HTTP-probed readiness, exited-process self-heal, cascade delete.
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, field

from kubeflow_tpu.api.common import ObjectMeta
from kubeflow_tpu.controller.base import ControllerBase
from kubeflow_tpu.controller.fakecluster import FakeCluster, Pod, PodPhase
from kubeflow_tpu.controller.tensorboard import PORT_ANNOTATION, _probe
from kubeflow_tpu.runtime.rendezvous import free_port


@dataclass
class NotebookSpec:
    # dev-server command; "{port}" placeholders are substituted. Empty =
    # stdlib HTTP file server over `workspace` (the offline Jupyter stand-in)
    command: list[str] = field(default_factory=list)
    workspace: str = "."
    env: dict[str, str] = field(default_factory=dict)


@dataclass
class ServerStatus:
    ready: bool = False
    url: str = ""


@dataclass
class Notebook:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NotebookSpec = field(default_factory=NotebookSpec)
    status: ServerStatus = field(default_factory=ServerStatus)
    kind: str = "Notebook"
    api_version: str = "kubeflow-tpu.org/v1beta1"


@dataclass
class PVCViewerSpec:
    pvc: str = "."  # volume directory to browse


@dataclass
class PVCViewer:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PVCViewerSpec = field(default_factory=PVCViewerSpec)
    status: ServerStatus = field(default_factory=ServerStatus)
    kind: str = "PVCViewer"
    api_version: str = "kubeflow-tpu.org/v1alpha1"


class ServerCRController(ControllerBase):
    """Shared reconcile: CR -> one dev-server pod, probed ready, self-healed."""

    #: subclass config
    CR_KIND = ""       # cluster bucket ("notebooks" / "pvcviewers")
    POD_LABEL = ""     # pod -> CR ownership label
    POD_SUFFIX = ""    # pod name suffix

    def __init__(self, cluster: FakeCluster, workers: int = 1,
                 resync_period_s: float = 2.0):
        super().__init__(
            cluster, name=self.CR_KIND, workers=workers,
            resync_period_s=resync_period_s,
        )
        # instance-level: CR_KIND/POD_LABEL are subclass config, not known
        # at class definition time on this shared base (the selector keys
        # are also the kind filter)
        self.WATCH_SELECTORS = {self.CR_KIND: None,
                                "pods": {self.POD_LABEL: None}}

    def command_for(self, cr, port: int) -> tuple[list[str], dict[str, str], str]:
        """(command, env, working_dir) for the server pod."""
        raise NotImplementedError

    def kind_filter(self, etype, kind: str, obj) -> str | None:
        if kind == self.CR_KIND:
            return self.cluster._key(obj)
        if kind == "pods":
            name = obj.metadata.labels.get(self.POD_LABEL)
            if name:
                return f"{obj.metadata.namespace}/{name}"
        return None

    def resync_keys(self):
        return [self.cluster._key(o) for o in self.cluster.list(self.CR_KIND)]

    def reconcile(self, key: str) -> float | None:
        cr = self.cluster.get(self.CR_KIND, key, copy_obj=True)
        ns, _, name = key.partition("/")
        pods = self.cluster.list(
            "pods",
            lambda p: p.metadata.labels.get(self.POD_LABEL) == name
            and p.metadata.namespace == ns,
        )
        if cr is None:
            for p in pods:
                self.cluster.delete("pods", p.key)
            return None

        # self-heal exited servers
        for p in pods:
            if p.status.phase in (PodPhase.FAILED, PodPhase.SUCCEEDED):
                self.cluster.delete("pods", p.key)
        pods = [
            p for p in pods
            if p.status.phase in (PodPhase.PENDING, PodPhase.RUNNING)
        ]
        if not pods:
            self._create_pod(cr)
            return 0.5

        pod = pods[0]
        port = pod.metadata.annotations.get(PORT_ANNOTATION, "")
        url = f"http://127.0.0.1:{port}" if port else ""
        ready = pod.status.phase == PodPhase.RUNNING and bool(url) and _probe(url)
        if (ready, url if ready else "") != (cr.status.ready, cr.status.url):
            cr.status.ready = ready
            cr.status.url = url if ready else ""
            self.cluster.update(self.CR_KIND, cr)
            if ready:
                self.cluster.record_event(
                    self.CR_KIND, key, "Ready", f"{self.POD_SUFFIX} at {url}"
                )
        return None if ready else 0.5

    def _create_pod(self, cr) -> None:
        port = free_port()
        command, env, workdir = self.command_for(cr, port)
        pod = Pod(
            metadata=ObjectMeta(
                name=f"{cr.metadata.name}-{self.POD_SUFFIX}-0",
                namespace=cr.metadata.namespace,
                labels={self.POD_LABEL: cr.metadata.name},
                annotations={PORT_ANNOTATION: str(port)},
            ),
            command=command,
            env=env,
            working_dir=workdir,
            scheduler_name="default",
        )
        try:
            self.cluster.create("pods", pod)
        except KeyError:
            pass


class NotebookController(ServerCRController):
    ERROR_EVENT_KIND = "notebooks"
    CR_KIND = "notebooks"
    POD_LABEL = "kubeflow-tpu.org/notebook"
    POD_SUFFIX = "notebook"

    def command_for(self, cr: Notebook, port: int):
        if cr.spec.command:
            command = [c.replace("{port}", str(port)) for c in cr.spec.command]
        else:
            command = [
                sys.executable, "-m", "http.server", str(port),
                "--bind", "127.0.0.1", "--directory", cr.spec.workspace,
            ]
        env = {"NOTEBOOK_PORT": str(port), **cr.spec.env}
        return command, env, cr.spec.workspace


class PVCViewerController(ServerCRController):
    ERROR_EVENT_KIND = "pvcviewers"
    CR_KIND = "pvcviewers"
    POD_LABEL = "kubeflow-tpu.org/pvcviewer"
    POD_SUFFIX = "pvcviewer"

    def command_for(self, cr: PVCViewer, port: int):
        command = [
            sys.executable, "-m", "http.server", str(port),
            "--bind", "127.0.0.1", "--directory", cr.spec.pvc,
        ]
        return command, {}, ""
