"""FakeCluster — the in-process apiserver/etcd analogue.

Reference parity: controller-runtime's envtest (real apiserver, no kubelet)
+ client-go fake clients (SURVEY.md §4). Here: a versioned object store with
watch streams. Pods ARE eventually executed — by the PodRuntime (podruntime
.py), which is more than envtest does — so e2e tests run real processes.

Objects are plain dataclasses; keys are "ns/name". Watch events are
(event_type, kind, obj) tuples delivered to subscriber queues.

Concurrency model (docs/architecture.md "Control-plane scaling"): the store
is sharded per kind — every CRUD op takes only its kind's lock, so a pod
status storm never serializes against job or podgroup traffic. The
snapshot window, resource-version counter, and event log each have their
own small lock, always acquired INSIDE a shard lock (shard → snap/rv/ev is
the one sanctioned order; shard locks nest only in KINDS order, and only
on the multi-kind relist path). Reads hand out the stored reference under
the lock and deep-copy OUTSIDE it: stored objects are replaced, never
mutated in place (the RCU discipline KFTPU-CONFLICT enforces), so the
reference is a stable snapshot and the expensive copy no longer serializes
every other store op behind it.
"""

from __future__ import annotations

import copy
import enum
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable

from kubeflow_tpu.api.common import ObjectMeta, utcnow as _ts
from kubeflow_tpu.tracing import current_context, set_delivered_context
from kubeflow_tpu.analysis.lockcheck import make_lock, make_rlock
from kubeflow_tpu.utils.retry import (
    POLL_POLICY,
    BackoffPolicy,
    backoff_sleep,
    with_conflict_retry,
)


class EventType(str, enum.Enum):
    ADDED = "ADDED"
    MODIFIED = "MODIFIED"
    DELETED = "DELETED"


class ConflictError(Exception):
    """Optimistic-concurrency failure: the object changed since it was read
    (k8s 409 Conflict analogue). Callers re-read and retry."""


class WatchClosed(Exception):
    """The subscription is dead (closed locally or GONE at the hub): no
    event will EVER arrive again. Distinct from queue.Empty — an idle
    timeout — so informer loops can resubscribe instead of silently
    polling a corpse forever."""


_ETYPE_CODE = {EventType.ADDED: 0, EventType.MODIFIED: 1, EventType.DELETED: 2}


def matches_labels(obj: Any, selector: dict[str, str | None] | None) -> bool:
    """Label selector (k8s `labelSelector=` analogue): each term is an
    equality match, or — when the value is None — a key-presence match."""
    if not selector:
        return True
    meta = getattr(obj, "metadata", None)
    labels = getattr(meta, "labels", None) or {}
    for k, v in selector.items():
        if v is None:
            if k not in labels:
                return False
        elif labels.get(k) != v:
            return False
    return True


class WatchSubscription:
    """queue.Queue-shaped view over one native event-hub subscription.

    get() resolves hub (seq, etype, kind, key) records back to the object
    snapshots the cluster retained; an overflowed (or snapshot-expired)
    subscriber transparently receives a fresh relist — current objects as
    ADDED — exactly how an informer recovers from 'resourceVersion expired'.

    Server-side filtering: ``filters`` ({kind: label-selector-or-None})
    is pushed into the native hub — events outside it are never BUFFERED
    for this stream, so an irrelevant storm can neither overflow it nor
    cost it per-event work; relists only list (and selector-match) the
    covered kinds. Label selectors here are identity markers stamped at
    creation (JOB_NAME_LABEL-class), so an object's match-state never
    changes over its life. A flat ``label_selector`` without kinds is
    applied at resolution time only (nothing to push down)."""

    def __init__(self, cluster: "FakeCluster", sub_id: int,
                 filters: dict[str, dict | None] | None = None,
                 label_selector: dict[str, str | None] | None = None):
        self._cluster = cluster
        self._sub_id = sub_id
        self.filters = dict(filters) if filters else None
        self.label_selector = dict(label_selector) if label_selector else None
        self._pending: deque = deque()
        self._closed = False

    def _matches(self, kind: str, obj: Any) -> bool:
        if self.filters is not None:
            if kind not in self.filters:
                return False
            return matches_labels(obj, self.filters[kind])
        return matches_labels(obj, self.label_selector)

    def _covered_kinds(self) -> tuple[str, ...]:
        """Covered kinds in canonical KINDS order (= shard lock order)."""
        if self.filters is None:
            return self._cluster.KINDS
        return tuple(k for k in self._cluster.KINDS if k in self.filters)

    def _relist(self, locks_held: bool = False) -> None:
        """Queue a fresh relist of the covered kinds.

        Recovery relists (overflow / snapshot-window expiry) take one
        shard lock at a time: the hub keeps buffering live events during
        the walk, so nothing can be missed — an object written between
        two kind listings shows up in its listing, its event, or both
        (at-least-once, the informer relist contract; a brief
        newer-then-older tail replay is possible, as it always was on
        this path — consumers are level-triggered). The INITIAL replay
        calls this with ``locks_held=True`` from watch(), which holds
        every covered shard lock across subscribe+list, so a fresh
        stream starts with the strong no-inversion guarantee."""
        self._pending.clear()
        cluster = self._cluster
        for kind in self._covered_kinds():
            if locks_held:
                objs = list(cluster._objects[kind].values())
            else:
                with cluster._locked(kind):
                    objs = list(cluster._objects[kind].values())
            for obj in objs:
                if self._matches(kind, obj):
                    self._pending.append((EventType.ADDED, kind, obj))

    def get(self, timeout: float | None = None):
        """Next (etype, kind, obj); raises queue.Empty on timeout.

        When the cluster carries a tracer, each delivery also publishes the
        originating write's SpanContext to this thread (tracing
        set_delivered_context) so consumer loops can link their work to the
        event that caused it; relisted events carry none."""
        # None keeps the original non-blocking contract (hub poll 0.0);
        # otherwise a deadline so filtered/expired records consume the
        # remaining budget instead of restarting or abandoning it
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        budget = timeout
        first = True
        while True:
            if self._pending:
                if self._cluster.tracer is not None:
                    set_delivered_context(None)  # relists: no causal write
                return self._pending.popleft()
            if self._closed:
                raise WatchClosed(f"subscription {self._sub_id} closed")
            chaos = self._cluster.chaos
            if chaos is not None and first:
                action = chaos.on_watch_get(self._sub_id)
                if action == "drop":
                    # injected 'watch too old': this stream loses its place
                    # and must recover exactly like a real overflow — full
                    # relist, then keep waiting with the CALLER'S timeout
                    # (an empty store must still block, not instantly
                    # raise queue.Empty)
                    self._relist()
                    first = False
                    continue
                if action:
                    # the sleep IS the injected fault (seeded informer
                    # lag) — jitter/backoff would distort the schedule
                    time.sleep(action)  # kftpu: allow=KFTPU-SLEEP
            first = False
            hub = self._cluster._hub
            rc, seq, etype_code, _kind, _key = hub.poll(
                self._sub_id, 0.0 if budget is None else budget
            )
            if rc == hub.EVENT:
                with self._cluster._snap_mu:
                    snap = self._cluster._snapshots.get(seq)
                    ctx = self._cluster._event_ctx.get(seq)
                if snap is None:  # window expired under extreme lag
                    self._relist()
                    budget = 0.0
                    continue
                if not self._matches(snap[1], snap[2]):
                    # filtered out at resolution: spend what remains of
                    # the caller's budget on the next record
                    if deadline is not None:
                        budget = max(deadline - time.monotonic(), 0.0)
                    continue
                if self._cluster.tracer is not None:
                    set_delivered_context(ctx)
                return snap
            if rc == hub.OVERFLOWED:
                self._relist()
                budget = 0.0
                continue
            if rc == hub.GONE:
                raise WatchClosed(
                    f"subscription {self._sub_id} gone at hub")
            raise queue.Empty  # EMPTY: idle timeout, stream still live

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._cluster._hub.unsubscribe(self._sub_id)


class WatchPoller:
    """The ONE informer get-with-recovery loop body, shared by every watch
    thread (ControllerBase, GangScheduler, PodRuntime — previously three
    hand-rolled copies that drifted).

    get() returns the next (etype, kind, obj) or None when nothing was
    delivered this round, with the failure taxonomy handled uniformly:

      - queue.Empty      -> idle timeout: reset the error backoff, None
      - WatchClosed      -> the stream is DEAD, no event will ever arrive:
                            count it, resubscribe, back off, None
      - anything else    -> broken subscription: count it, back off (an
                            instantly-failing get() must not busy-spin the
                            daemon thread), None — the loop stays alive

    ``count_error`` is the owner's failure counter (a zero-arg callable);
    errors are always counted, never degraded into an idle poll.
    """

    def __init__(self, cluster: "FakeCluster", timeout: float,
                 count_error: Callable[[], None],
                 kinds: tuple[str, ...] | None = None,
                 label_selector: dict[str, str | None] | None = None,
                 selectors: dict[str, dict | None] | None = None):
        self._cluster = cluster
        self._timeout = timeout
        self._count_error = count_error
        self._kinds = tuple(kinds) if kinds else None
        self._label_selector = label_selector
        self._selectors = selectors
        self._attempt = 0
        self.q = cluster.watch(kinds=self._kinds,
                               label_selector=self._label_selector,
                               selectors=self._selectors)

    def get(self):
        try:
            ev = self.q.get(timeout=self._timeout)
        except queue.Empty:
            self._attempt = 0
            return None
        except WatchClosed:
            # a dead subscription can only be replaced — polling it again
            # would be the silent idle-poll-forever wedge
            self._count_error()
            backoff_sleep(POLL_POLICY, self._attempt)
            self._attempt += 1
            self.q = self._cluster.watch(
                kinds=self._kinds, label_selector=self._label_selector,
                selectors=self._selectors)
            return None
        except Exception:  # noqa: BLE001 — the informer must not die
            self._count_error()
            backoff_sleep(POLL_POLICY, self._attempt)
            self._attempt += 1
            return None
        self._attempt = 0
        return ev


class PodPhase(str, enum.Enum):
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


@dataclass
class PodStatus:
    phase: PodPhase = PodPhase.PENDING
    exit_code: int | None = None
    node: str = ""          # set by a scheduler => "bound"
    pid: int | None = None
    message: str = ""
    start_time: float | None = None
    finish_time: float | None = None


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    command: list[str] = field(default_factory=list)
    env: dict[str, str] = field(default_factory=dict)
    working_dir: str = ""
    scheduler_name: str = "default"
    group_name: str = ""    # PodGroup membership (gang annotation analogue)
    restart_policy: str = "Never"  # pod-level: runtime never restarts; the
    # controller owns restart semantics (matches operator behavior)
    status: PodStatus = field(default_factory=PodStatus)

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"


@dataclass
class PodGroup:
    """Gang-scheduling unit (volcano PodGroup analogue)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    min_member: int = 1
    queue: str = "default"
    # PER-SLICE TPU topology (atomic unit, SURVEY.md §2.2); informational —
    # the scheduler charges `chips`.
    slice_topology: str = ""
    # Total chip reservation: topology chips x num_slices, set by the job
    # controller; 0 = charge one chip per pod.
    chips: int = 0
    # Scheduling priority (resolved from SchedulingPolicy.priority_class):
    # higher binds first under contention, and may PREEMPT strictly-lower-
    # priority bound gangs (volcano preempt-action analogue).
    priority: int = 0
    phase: str = "Pending"  # Pending -> Running once gang-bound

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"


@dataclass
class ClusterEvent:
    """k8s Event analogue (observability, SURVEY.md §5.5)."""

    object_key: str
    kind: str
    reason: str
    message: str
    type: str = "Normal"
    timestamp: float = field(default_factory=time.time)


class _ShardGuard:
    """Context manager over an ALREADY-ACQUIRED shard lock (the acquire —
    with contention accounting — happens in FakeCluster._locked)."""

    __slots__ = ("_mu",)

    def __init__(self, mu):
        self._mu = mu

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._mu.release()
        return False


class FakeCluster:
    """Thread-safe object store + watch hub, sharded per kind."""

    KINDS = (
        "jobs", "pods", "podgroups", "experiments", "trials",
        "inferenceservices", "poddefaults", "profiles", "namespaces",
        "tensorboards", "pipelineruns", "notebooks", "pvcviewers",
        "bindings",
    )

    #: per-subscriber buffered events before a forced relist (native hub)
    WATCH_CAPACITY = 4096

    def __init__(self) -> None:
        from kubeflow_tpu.native import EventHub

        # one lock per kind: a pod status storm contends only with pod
        # traffic. Distinct lockcheck names per kind so the relist path's
        # fixed KINDS-order nesting is visible (and checkable) in the
        # acquisition graph instead of collapsing into a self-edge.
        self._shard_mu = {
            k: make_rlock(f"fakecluster.FakeCluster._shard_mu[{k}]")
            for k in self.KINDS
        }
        #: contended acquisitions per kind (bumped under the just-acquired
        #: shard lock) — exported as kftpu_cplane_shard_lock_waits_total
        self._lock_waits: dict[str, int] = {k: 0 for k in self.KINDS}
        self._objects: dict[str, dict[str, Any]] = {k: {} for k in self.KINDS}
        # native informer fan-out (SURVEY.md §2.8 "Go controller machinery"):
        # sequencing + bounded per-subscriber buffers live in C++
        # (native/src/eventhub.cc); object snapshots stay here, keyed by seq,
        # in a window matching the hub capacity so memory is bounded even
        # under a stuck REST watch client
        self._hub = EventHub(self.WATCH_CAPACITY)
        # snapshot window + publish ordering: publish and snapshot-record
        # happen together under _snap_mu, so a subscriber can never poll a
        # seq whose snapshot hasn't landed yet (cross-shard writers would
        # otherwise interleave publish and record)
        self._snap_mu = make_lock("fakecluster.FakeCluster._snap_mu")
        self._snapshots: dict[int, tuple[EventType, str, Any]] = {}
        #: seq -> SpanContext of the write that published the event (only
        #: populated while a tracer is attached; evicted with _snapshots)
        self._event_ctx: dict[int, Any] = {}
        self._snapshot_min = 0
        self._rv_mu = make_lock("fakecluster.FakeCluster._rv_mu")
        self._rv = 0
        self._ev_mu = make_lock("fakecluster.FakeCluster._ev_mu")
        self.events: list[ClusterEvent] = []
        self.capacity_chips = 8  # schedulable "chips" for the gang scheduler
        #: fault-injection attachment point (chaos.ChaosEngine.attach);
        #: None in production — every hook call is gated on it
        self.chaos = None
        #: tracing attachment point (Platform.start_tracing); None = off —
        #: every hook call is gated on it, same discipline as chaos
        self.tracer = None

    def _locked(self, kind: str):
        """The kind's shard lock, with contention accounting: a failed
        try-acquire is a wait another thread imposed — the control-plane
        serialization signal kftpu_cplane_shard_lock_waits_total exports."""
        mu = self._shard_mu[kind]
        if not mu.acquire(blocking=False):
            mu.acquire()
            self._lock_waits[kind] += 1  # under the lock: no lost updates
        return _ShardGuard(mu)

    def _next_rv(self) -> int:
        with self._rv_mu:
            self._rv += 1
            return self._rv

    def lock_wait_counts(self) -> dict[str, int]:
        """Per-kind contended-acquire counts (coarse snapshot)."""
        return dict(self._lock_waits)

    # ------------------------------------------------------------------ CRUD

    def create(self, kind: str, obj: Any) -> Any:
        with self._locked(kind):
            key = self._key(obj)
            if key in self._objects[kind]:
                raise KeyError(f"{kind} {key} already exists")
            if not obj.metadata.uid:
                obj.metadata.uid = f"uid-{self._next_rv()}"
            if not obj.metadata.creation_timestamp:
                obj.metadata.creation_timestamp = _ts()
            obj.metadata.resource_version = self._next_rv()
            self._objects[kind][key] = obj
            self._notify(EventType.ADDED, kind, obj)
            return obj

    def update(self, kind: str, obj: Any) -> Any:
        """Swap in `obj`. Rejects stale writes: obj's resource_version must
        match the stored one (always true when mutating the stored object in
        place; snapshot writers get ConflictError and must re-read)."""
        chaos = self.chaos
        if chaos is not None:
            # outside the shard lock: an injected ConflictError must not be
            # distinguishable from a real one by lock-hold side effects
            chaos.on_update(kind, self._key(obj))
        with self._locked(kind):
            key = self._key(obj)
            stored = self._objects[kind].get(key)
            if stored is None:
                raise KeyError(f"{kind} {key} not found")
            if obj.metadata.resource_version != stored.metadata.resource_version:
                raise ConflictError(
                    f"{kind} {key}: resource_version "
                    f"{obj.metadata.resource_version} != "
                    f"{stored.metadata.resource_version}"
                )
            obj.metadata.resource_version = self._next_rv()
            self._objects[kind][key] = obj
            self._notify(EventType.MODIFIED, kind, obj)
            return obj

    def delete(self, kind: str, key: str) -> Any | None:
        with self._locked(kind):
            obj = self._objects[kind].pop(key, None)
            if obj is not None:
                self._notify(EventType.DELETED, kind, obj)
            return obj

    def batch_update(
        self, kind: str,
        ops: list[tuple[str, Callable[[Any], Any], Any]],
        copier: Callable[[Any], Any] | None = None,
    ) -> list[Any | None]:
        """Apply N read-copy-update mutations under ONE shard lock hold.

        Each op is ``(key, mutate, event_ctx)``: the stored object is
        copied (``copier``, default deepcopy), mutated, versioned, and
        swapped in — semantically N back-to-back read_modify_write calls,
        but with zero conflict retries (the write lock is held across the
        batch) and one lock acquisition total. The coalescing tier above
        this (StatusWriteBuffer) is how per-pod status storms stop
        serializing the store. ``event_ctx`` is the ORIGINATING WRITER'S
        SpanContext, published with the MODIFIED event in place of the
        flusher thread's (none): causal parent links through coalesced
        writes stay exactly what the per-op path would have produced.
        Returns one entry per op: the updated object, or None when the key
        is missing or ``mutate`` returned False (declined on fresh state —
        the incarnation-guard convention `_update_pod_status` already
        uses). A mutator that RAISES fails only its own op — the entry is
        the exception instance, the batch's other ops commit normally
        (read_modify_write parity: each caller sees only its own
        failure).

        ``copier`` exists because status writers touch only
        ``obj.status`` + ``metadata.annotations``: a targeted copy that
        shares the untouched payload (command/env/labels) is several times
        cheaper than deepcopy and just as safe under the store's
        replace-never-mutate discipline. Chaos conflict injection is the
        CALLER'S job (the buffer routes injected conflicts through the
        single-op retry path so drills still exercise it).
        """
        copier = copy.deepcopy if copier is None else copier
        results: list[Any | None] = []
        with self._locked(kind):
            store = self._objects[kind]
            for key, mutate, ctx in ops:
                stored = store.get(key)
                if stored is None:
                    results.append(None)
                    continue
                obj = copier(stored)
                try:
                    declined = mutate(obj) is False
                except Exception as exc:  # noqa: BLE001 — isolate the op
                    # one bad mutator must not abort (or mis-ack) ops that
                    # already committed in this batch; the store is
                    # untouched for THIS op (the copy is discarded)
                    results.append(exc)
                    continue
                if declined:
                    results.append(None)
                    continue
                obj.metadata.resource_version = self._next_rv()
                store[key] = obj
                self._notify(EventType.MODIFIED, kind, obj, ctx=ctx)
                results.append(obj)
        return results

    def read_modify_write(
        self, kind: str, key: str, mutate, retries: int = 10,
        backoff_s: float = 0.02,
    ) -> Any:
        """Optimistic-concurrency update: deep-copied snapshot -> mutate ->
        swap; retried on ConflictError under the shared jittered-backoff
        policy (utils/retry.py). The ONE sanctioned way for clients to
        update stored objects (mutating the live object in place would make
        half-applied changes visible to controllers and defeat conflict
        detection — every hand-rolled copy of this loop has eventually
        dropped the copy)."""

        def attempt():
            obj = self.get(kind, key, copy_obj=True)
            if obj is None:
                raise KeyError(key)
            mutate(obj)
            return self.update(kind, obj)

        policy = BackoffPolicy(
            base_s=backoff_s, max_s=backoff_s * 8, max_attempts=retries
        )
        try:
            return with_conflict_retry(attempt, policy=policy)
        except ConflictError as exc:
            raise ConflictError(
                f"update of {kind}/{key} kept conflicting"
            ) from exc

    def get(self, kind: str, key: str, copy_obj: bool = False) -> Any | None:
        """Fetch by key. copy_obj=True returns a deep snapshot — required by
        any caller that mutates and writes back (read-copy-update), so
        concurrent writers are detected via resource_version instead of
        silently interleaving on a shared live object."""
        with self._locked(kind):
            obj = self._objects[kind].get(key)
        # the copy runs OUTSIDE the lock: stored objects are replaced, not
        # mutated (RCU discipline), so the reference is a stable snapshot
        # and a 30us deepcopy no longer serializes the whole shard
        return copy.deepcopy(obj) if copy_obj and obj is not None else obj

    def list(
        self, kind: str, selector: Callable[[Any], bool] | None = None
    ) -> list[Any]:
        with self._locked(kind):
            objs = list(self._objects[kind].values())
        return [o for o in objs if selector is None or selector(o)]

    # ----------------------------------------------------------------- watch

    def watch(self, replay: bool = True,
              kinds: tuple[str, ...] | None = None,
              label_selector: dict[str, str | None] | None = None,
              selectors: dict[str, dict | None] | None = None,
              ) -> "WatchSubscription":
        """Subscribe to events; optionally replay current objects as
        ADDED (informer initial list+watch semantics).

        ``kinds`` and label selectors filter SERVER-SIDE: the native hub
        never buffers filtered-out events into this subscription, so an
        unrelated storm can neither overflow it nor cost it resolution
        work — at 10k pods the client-side discard this replaces WAS the
        control-plane ceiling. ``label_selector`` ({key: value, or None
        for presence}) applies to every watched kind; ``selectors``
        ({kind: selector-or-None}) sets per-kind selectors (a controller
        typically wants ALL of its own kind but only the pods carrying
        its ownership label). The returned subscription is
        queue.Queue-shaped (.get(timeout=) raising queue.Empty when idle,
        WatchClosed once the stream is dead — closed locally or GONE at
        the hub); a subscriber that falls WATCH_CAPACITY events behind is
        transparently relisted (k8s "watch too old" semantics).
        WatchPoller packages the standard reaction (resubscribe + relist)
        for informer loops.

        Subscribe and the replay listing happen while every covered
        shard lock is held (acquired in KINDS order — the one sanctioned
        shard->shard nesting), so no event can be missed between the
        initial list and the live tail AND the tail can never replay an
        event older than what the listing showed (no deleted-then-
        recreated inversion on a fresh stream). Writers hold exactly one
        shard lock, so this cannot deadlock them."""
        if selectors is not None:
            filters = dict(selectors)
        elif kinds:
            filters = {k: label_selector for k in kinds}
        else:
            filters = None  # full stream; flat selector applies on resolve
        if not replay:
            sub_id = self._hub.subscribe(filters=filters)
            return WatchSubscription(self, sub_id, filters=filters,
                                     label_selector=label_selector)
        covered = (self.KINDS if filters is None
                   else tuple(k for k in self.KINDS if k in filters))
        guards = [self._locked(k) for k in covered]
        try:
            sub_id = self._hub.subscribe(filters=filters)
            sub = WatchSubscription(self, sub_id, filters=filters,
                                    label_selector=label_selector)
            sub._relist(locks_held=True)
        finally:
            for g in reversed(guards):
                g.__exit__(None, None, None)
        return sub

    def unwatch(self, sub: "WatchSubscription") -> None:
        sub.close()

    #: sentinel: _notify should read the calling thread's current span
    _CALLER_CTX = object()

    def _notify(self, etype: EventType, kind: str, obj: Any,
                ctx: Any = _CALLER_CTX) -> None:
        # caller holds the kind's shard lock (all CRUD paths). Publish and
        # snapshot-record are atomic under _snap_mu so no subscriber can
        # poll a seq whose snapshot a cross-shard writer hasn't landed yet
        # (shard -> snap is the sanctioned nesting order). ctx overrides
        # the caller-thread context for batched writes applied on a
        # flusher thread on behalf of the real writer.
        if ctx is FakeCluster._CALLER_CTX:
            ctx = current_context() if self.tracer is not None else None
        with self._snap_mu:
            seq = self._hub.publish(_ETYPE_CODE[etype], kind, self._key(obj),
                                    labels=obj.metadata.labels)
            self._snapshots[seq] = (etype, kind, obj)
            if ctx is not None:
                # the writer's current span becomes the event's causal
                # parent: a reconcile's pod create/update is traceable to
                # whatever the subscriber does with it
                self._event_ctx[seq] = ctx
            floor = seq - 2 * self.WATCH_CAPACITY
            while self._snapshot_min <= floor:
                self._snapshots.pop(self._snapshot_min, None)
                self._event_ctx.pop(self._snapshot_min, None)
                self._snapshot_min += 1

    # ---------------------------------------------------------------- events

    def record_event(
        self, kind: str, key: str, reason: str, message: str, type: str = "Normal"
    ) -> None:
        with self._ev_mu:
            self.events.append(ClusterEvent(key, kind, reason, message, type))

    def events_for(self, key: str) -> list[ClusterEvent]:
        with self._ev_mu:
            return [e for e in self.events if e.object_key == key]

    @staticmethod
    def _key(obj: Any) -> str:
        return f"{obj.metadata.namespace}/{obj.metadata.name}"
