"""FakeCluster — the in-process apiserver/etcd analogue.

Reference parity: controller-runtime's envtest (real apiserver, no kubelet)
+ client-go fake clients (SURVEY.md §4). Here: a versioned object store with
watch streams. Pods ARE eventually executed — by the PodRuntime (podruntime
.py), which is more than envtest does — so e2e tests run real processes.

Objects are plain dataclasses; keys are "ns/name". Watch events are
(event_type, kind, obj) tuples delivered to subscriber queues.
"""

from __future__ import annotations

import copy
import enum
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable

from kubeflow_tpu.api.common import ObjectMeta, utcnow as _ts
from kubeflow_tpu.tracing import current_context, set_delivered_context
from kubeflow_tpu.analysis.lockcheck import make_rlock
from kubeflow_tpu.utils.retry import (
    POLL_POLICY,
    BackoffPolicy,
    backoff_sleep,
    with_conflict_retry,
)


class EventType(str, enum.Enum):
    ADDED = "ADDED"
    MODIFIED = "MODIFIED"
    DELETED = "DELETED"


class ConflictError(Exception):
    """Optimistic-concurrency failure: the object changed since it was read
    (k8s 409 Conflict analogue). Callers re-read and retry."""


class WatchClosed(Exception):
    """The subscription is dead (closed locally or GONE at the hub): no
    event will EVER arrive again. Distinct from queue.Empty — an idle
    timeout — so informer loops can resubscribe instead of silently
    polling a corpse forever."""


_ETYPE_CODE = {EventType.ADDED: 0, EventType.MODIFIED: 1, EventType.DELETED: 2}


class WatchSubscription:
    """queue.Queue-shaped view over one native event-hub subscription.

    get() resolves hub (seq, etype, kind, key) records back to the object
    snapshots the cluster retained; an overflowed (or snapshot-expired)
    subscriber transparently receives a fresh relist — current objects as
    ADDED — exactly how an informer recovers from 'resourceVersion expired'.
    """

    def __init__(self, cluster: "FakeCluster", sub_id: int):
        self._cluster = cluster
        self._sub_id = sub_id
        self._pending: deque = deque()
        self._closed = False

    def _relist_locked(self) -> None:
        """Queue a full relist; caller holds cluster._mu."""
        self._pending.clear()
        for kind in self._cluster.KINDS:
            for obj in self._cluster._objects[kind].values():
                self._pending.append((EventType.ADDED, kind, obj))

    def get(self, timeout: float | None = None):
        """Next (etype, kind, obj); raises queue.Empty on timeout.

        When the cluster carries a tracer, each delivery also publishes the
        originating write's SpanContext to this thread (tracing
        set_delivered_context) so consumer loops can link their work to the
        event that caused it; relisted events carry none."""
        if self._pending:
            if self._cluster.tracer is not None:
                set_delivered_context(None)  # relists have no causal write
            return self._pending.popleft()
        if self._closed:
            raise WatchClosed(f"subscription {self._sub_id} closed")
        chaos = self._cluster.chaos
        if chaos is not None:
            action = chaos.on_watch_get(self._sub_id)
            if action == "drop":
                # injected 'watch too old': this stream loses its place and
                # must recover exactly like a real overflow — full relist.
                # Recurse with the CALLER'S timeout: when the store is empty
                # the relist queues nothing and the caller still deserves a
                # blocking wait, not an instant queue.Empty
                with self._cluster._mu:
                    self._relist_locked()
                return self.get(timeout=timeout)
            if action:
                # the sleep IS the injected fault (seeded informer lag) —
                # jitter/backoff would distort the planned schedule
                time.sleep(action)  # kftpu: allow=KFTPU-SLEEP
        hub = self._cluster._hub
        rc, seq, etype_code, _kind, _key = hub.poll(
            self._sub_id, 0.0 if timeout is None else timeout
        )
        if rc == hub.EVENT:
            with self._cluster._mu:
                snap = self._cluster._snapshots.get(seq)
                ctx = self._cluster._event_ctx.get(seq)
                if snap is None:  # window expired under extreme lag
                    self._relist_locked()
            if snap is not None:
                if self._cluster.tracer is not None:
                    set_delivered_context(ctx)
                return snap
            return self.get(timeout=0.0)
        if rc == hub.OVERFLOWED:
            with self._cluster._mu:
                self._relist_locked()
            return self.get(timeout=0.0)
        if rc == hub.GONE:
            raise WatchClosed(f"subscription {self._sub_id} gone at hub")
        raise queue.Empty  # EMPTY: idle timeout, the stream is still live

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._cluster._hub.unsubscribe(self._sub_id)


class WatchPoller:
    """The ONE informer get-with-recovery loop body, shared by every watch
    thread (ControllerBase, GangScheduler, PodRuntime — previously three
    hand-rolled copies that drifted).

    get() returns the next (etype, kind, obj) or None when nothing was
    delivered this round, with the failure taxonomy handled uniformly:

      - queue.Empty      -> idle timeout: reset the error backoff, None
      - WatchClosed      -> the stream is DEAD, no event will ever arrive:
                            count it, resubscribe, back off, None
      - anything else    -> broken subscription: count it, back off (an
                            instantly-failing get() must not busy-spin the
                            daemon thread), None — the loop stays alive

    ``count_error`` is the owner's failure counter (a zero-arg callable);
    errors are always counted, never degraded into an idle poll.
    """

    def __init__(self, cluster: "FakeCluster", timeout: float,
                 count_error: Callable[[], None]):
        self._cluster = cluster
        self._timeout = timeout
        self._count_error = count_error
        self._attempt = 0
        self.q = cluster.watch()

    def get(self):
        try:
            ev = self.q.get(timeout=self._timeout)
        except queue.Empty:
            self._attempt = 0
            return None
        except WatchClosed:
            # a dead subscription can only be replaced — polling it again
            # would be the silent idle-poll-forever wedge
            self._count_error()
            backoff_sleep(POLL_POLICY, self._attempt)
            self._attempt += 1
            self.q = self._cluster.watch()
            return None
        except Exception:  # noqa: BLE001 — the informer must not die
            self._count_error()
            backoff_sleep(POLL_POLICY, self._attempt)
            self._attempt += 1
            return None
        self._attempt = 0
        return ev


class PodPhase(str, enum.Enum):
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


@dataclass
class PodStatus:
    phase: PodPhase = PodPhase.PENDING
    exit_code: int | None = None
    node: str = ""          # set by a scheduler => "bound"
    pid: int | None = None
    message: str = ""
    start_time: float | None = None
    finish_time: float | None = None


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    command: list[str] = field(default_factory=list)
    env: dict[str, str] = field(default_factory=dict)
    working_dir: str = ""
    scheduler_name: str = "default"
    group_name: str = ""    # PodGroup membership (gang annotation analogue)
    restart_policy: str = "Never"  # pod-level: runtime never restarts; the
    # controller owns restart semantics (matches operator behavior)
    status: PodStatus = field(default_factory=PodStatus)

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"


@dataclass
class PodGroup:
    """Gang-scheduling unit (volcano PodGroup analogue)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    min_member: int = 1
    queue: str = "default"
    # PER-SLICE TPU topology (atomic unit, SURVEY.md §2.2); informational —
    # the scheduler charges `chips`.
    slice_topology: str = ""
    # Total chip reservation: topology chips x num_slices, set by the job
    # controller; 0 = charge one chip per pod.
    chips: int = 0
    # Scheduling priority (resolved from SchedulingPolicy.priority_class):
    # higher binds first under contention, and may PREEMPT strictly-lower-
    # priority bound gangs (volcano preempt-action analogue).
    priority: int = 0
    phase: str = "Pending"  # Pending -> Running once gang-bound

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"


@dataclass
class ClusterEvent:
    """k8s Event analogue (observability, SURVEY.md §5.5)."""

    object_key: str
    kind: str
    reason: str
    message: str
    type: str = "Normal"
    timestamp: float = field(default_factory=time.time)


class FakeCluster:
    """Thread-safe object store + watch hub."""

    KINDS = (
        "jobs", "pods", "podgroups", "experiments", "trials",
        "inferenceservices", "poddefaults", "profiles", "namespaces",
        "tensorboards", "pipelineruns", "notebooks", "pvcviewers",
        "bindings",
    )

    #: per-subscriber buffered events before a forced relist (native hub)
    WATCH_CAPACITY = 4096

    def __init__(self) -> None:
        from kubeflow_tpu.native import EventHub

        self._mu = make_rlock("fakecluster.FakeCluster._mu")
        self._objects: dict[str, dict[str, Any]] = {k: {} for k in self.KINDS}
        # native informer fan-out (SURVEY.md §2.8 "Go controller machinery"):
        # sequencing + bounded per-subscriber buffers live in C++
        # (native/src/eventhub.cc); object snapshots stay here, keyed by seq,
        # in a window matching the hub capacity so memory is bounded even
        # under a stuck REST watch client
        self._hub = EventHub(self.WATCH_CAPACITY)
        self._snapshots: dict[int, tuple[EventType, str, Any]] = {}
        #: seq -> SpanContext of the write that published the event (only
        #: populated while a tracer is attached; evicted with _snapshots)
        self._event_ctx: dict[int, Any] = {}
        self._snapshot_min = 0
        self._rv = 0
        self.events: list[ClusterEvent] = []
        self.capacity_chips = 8  # schedulable "chips" for the gang scheduler
        #: fault-injection attachment point (chaos.ChaosEngine.attach);
        #: None in production — every hook call is gated on it
        self.chaos = None
        #: tracing attachment point (Platform.start_tracing); None = off —
        #: every hook call is gated on it, same discipline as chaos
        self.tracer = None

    # ------------------------------------------------------------------ CRUD

    def create(self, kind: str, obj: Any) -> Any:
        with self._mu:
            key = self._key(obj)
            if key in self._objects[kind]:
                raise KeyError(f"{kind} {key} already exists")
            if not obj.metadata.uid:
                self._rv += 1
                obj.metadata.uid = f"uid-{self._rv}"
            if not obj.metadata.creation_timestamp:
                obj.metadata.creation_timestamp = _ts()
            self._rv += 1
            obj.metadata.resource_version = self._rv
            self._objects[kind][key] = obj
            self._notify(EventType.ADDED, kind, obj)
            return obj

    def update(self, kind: str, obj: Any) -> Any:
        """Swap in `obj`. Rejects stale writes: obj's resource_version must
        match the stored one (always true when mutating the stored object in
        place; snapshot writers get ConflictError and must re-read)."""
        chaos = self.chaos
        if chaos is not None:
            # outside _mu: an injected ConflictError must not be
            # distinguishable from a real one by lock-hold side effects
            chaos.on_update(kind, self._key(obj))
        with self._mu:
            key = self._key(obj)
            stored = self._objects[kind].get(key)
            if stored is None:
                raise KeyError(f"{kind} {key} not found")
            if obj.metadata.resource_version != stored.metadata.resource_version:
                raise ConflictError(
                    f"{kind} {key}: resource_version "
                    f"{obj.metadata.resource_version} != "
                    f"{stored.metadata.resource_version}"
                )
            self._rv += 1
            obj.metadata.resource_version = self._rv
            self._objects[kind][key] = obj
            self._notify(EventType.MODIFIED, kind, obj)
            return obj

    def delete(self, kind: str, key: str) -> Any | None:
        with self._mu:
            obj = self._objects[kind].pop(key, None)
            if obj is not None:
                self._notify(EventType.DELETED, kind, obj)
            return obj

    def read_modify_write(
        self, kind: str, key: str, mutate, retries: int = 10,
        backoff_s: float = 0.02,
    ) -> Any:
        """Optimistic-concurrency update: deep-copied snapshot -> mutate ->
        swap; retried on ConflictError under the shared jittered-backoff
        policy (utils/retry.py). The ONE sanctioned way for clients to
        update stored objects (mutating the live object in place would make
        half-applied changes visible to controllers and defeat conflict
        detection — every hand-rolled copy of this loop has eventually
        dropped the copy)."""

        def attempt():
            obj = self.get(kind, key, copy_obj=True)
            if obj is None:
                raise KeyError(key)
            mutate(obj)
            return self.update(kind, obj)

        policy = BackoffPolicy(
            base_s=backoff_s, max_s=backoff_s * 8, max_attempts=retries
        )
        try:
            return with_conflict_retry(attempt, policy=policy)
        except ConflictError as exc:
            raise ConflictError(
                f"update of {kind}/{key} kept conflicting"
            ) from exc

    def get(self, kind: str, key: str, copy_obj: bool = False) -> Any | None:
        """Fetch by key. copy_obj=True returns a deep snapshot — required by
        any caller that mutates and writes back (read-copy-update), so
        concurrent writers are detected via resource_version instead of
        silently interleaving on a shared live object."""
        with self._mu:
            obj = self._objects[kind].get(key)
            return copy.deepcopy(obj) if copy_obj and obj is not None else obj

    def list(
        self, kind: str, selector: Callable[[Any], bool] | None = None
    ) -> list[Any]:
        with self._mu:
            objs = list(self._objects[kind].values())
        return [o for o in objs if selector is None or selector(o)]

    # ----------------------------------------------------------------- watch

    def watch(self, replay: bool = True) -> "WatchSubscription":
        """Subscribe to all events; optionally replay current objects as
        ADDED (informer initial list+watch semantics). The returned
        subscription is queue.Queue-shaped (.get(timeout=) raising
        queue.Empty when idle, WatchClosed once the stream is dead —
        closed locally or GONE at the hub); a subscriber that falls
        WATCH_CAPACITY events behind is transparently relisted (k8s
        "watch too old" semantics). WatchPoller packages the standard
        reaction (resubscribe + relist) for informer loops."""
        with self._mu:
            # subscribe-then-snapshot under the lock: no event can be missed
            # between the initial list and the live tail
            sub_id = self._hub.subscribe()
            sub = WatchSubscription(self, sub_id)
            if replay:
                sub._relist_locked()
        return sub

    def unwatch(self, sub: "WatchSubscription") -> None:
        sub.close()

    def _notify(self, etype: EventType, kind: str, obj: Any) -> None:
        # caller holds self._mu (all CRUD paths); publish + snapshot are
        # atomic with respect to subscribe-and-relist
        seq = self._hub.publish(_ETYPE_CODE[etype], kind, self._key(obj))
        self._snapshots[seq] = (etype, kind, obj)
        if self.tracer is not None:
            # the writer's current span becomes the event's causal parent:
            # a reconcile's pod create/update is traceable to whatever the
            # subscriber does with it
            ctx = current_context()
            if ctx is not None:
                self._event_ctx[seq] = ctx
        floor = seq - 2 * self.WATCH_CAPACITY
        while self._snapshot_min <= floor:
            self._snapshots.pop(self._snapshot_min, None)
            self._event_ctx.pop(self._snapshot_min, None)
            self._snapshot_min += 1

    # ---------------------------------------------------------------- events

    def record_event(
        self, kind: str, key: str, reason: str, message: str, type: str = "Normal"
    ) -> None:
        with self._mu:
            self.events.append(ClusterEvent(key, kind, reason, message, type))

    def events_for(self, key: str) -> list[ClusterEvent]:
        with self._mu:
            return [e for e in self.events if e.object_key == key]

    @staticmethod
    def _key(obj: Any) -> str:
        return f"{obj.metadata.namespace}/{obj.metadata.name}"
