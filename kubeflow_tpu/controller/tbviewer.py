"""Built-in tfevents viewer — the Tensorboard CR's self-sufficient backend.

The reference's tensorboard-controller launches the real TensorBoard; this
platform prefers it too, but TensorBoard's CLI is not importable in every
image (here: `tensorboard.main` needs pkg_resources, absent from this
venv). A Tensorboard CR must still mean "a live URL showing the training
curves", so this stdlib server renders the SAME tfevents files (read via
the sweep collector's parser, written by train.metrics.TfEventsWriter) as
inline-SVG line charts + JSON endpoints — zero extra dependencies, same
readiness contract. The tensorboard controller falls back to this module
whenever real TensorBoard can't start (mirroring the notebook controller's
stdlib dev-server precedent).

  GET /               HTML: every scalar tag as an SVG line chart
  GET /data/scalars   JSON: {tag: [[step, value], ...]}
"""

from __future__ import annotations

import html
import json
import math
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


_cache: dict[str, tuple[tuple, dict]] = {}


def _series(logdir: str) -> dict[str, list[tuple[int, float]]]:
    """Parsed scalars, cached on a (path, mtime, size) snapshot — the
    readiness probe hits / every resync, and re-parsing a long run's
    tfevents each time would grow without bound. Returns {} (page still
    serves, with a banner) when the tensorboard proto modules the parser
    needs are absent entirely — the CR must not flap on a parse error."""
    import os

    try:
        from kubeflow_tpu.sweep.collector import parse_tfevents_points
    except ImportError:
        return {}
    snap = tuple(
        sorted(
            (p, os.path.getmtime(p), os.path.getsize(p))
            for root, _, fs in os.walk(logdir)
            for f in fs
            if "tfevents" in f and os.path.exists(p := os.path.join(root, f))
        )
    )
    hit = _cache.get(logdir)
    if hit is not None and hit[0] == snap:
        return hit[1]
    try:
        series = parse_tfevents_points(logdir)
    except Exception:  # noqa: BLE001 — a torn write must not 500 the probe
        return hit[1] if hit else {}
    _cache[logdir] = (snap, series)
    return series


def _svg_chart(points: list[tuple[int, float]], w: int = 520, h: int = 160) -> str:
    if not points:
        return "<svg/>"
    points = [p for p in points if math.isfinite(p[1])]
    if not points:
        return "<svg/>"
    xs = [p[0] for p in points]
    ys = [p[1] for p in points]
    x0, x1 = min(xs), max(xs)
    y0, y1 = min(ys), max(ys)
    xr = (x1 - x0) or 1
    yr = (y1 - y0) or 1.0
    pad = 8
    coords = " ".join(
        f"{pad + (x - x0) / xr * (w - 2 * pad):.1f},"
        f"{h - pad - (y - y0) / yr * (h - 2 * pad):.1f}"
        for x, y in points
    )
    return (
        f'<svg viewBox="0 0 {w} {h}" width="{w}" height="{h}" '
        f'style="background:#fafafa;border:1px solid #ddd">'
        f'<polyline fill="none" stroke="#2563eb" stroke-width="1.5" '
        f'points="{coords}"/>'
        f'<text x="{pad}" y="{pad + 4}" font-size="9">{y1:.5g}</text>'
        f'<text x="{pad}" y="{h - 2}" font-size="9">{y0:.5g}</text>'
        f"</svg>"
    )


def make_handler(logdir: str):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # pod logs
            print(f"tbviewer: {fmt % args}", flush=True)

        def _reply(self, code: int, body: bytes, ctype: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 (http.server API)
            if self.path.startswith("/data/scalars"):
                # non-finite floats serialize as null: bare NaN/Infinity
                # tokens are invalid JSON to strict parsers
                data = {
                    tag: [
                        [s, v if math.isfinite(v) else None] for s, v in pts
                    ]
                    for tag, pts in _series(logdir).items()
                }
                self._reply(200, json.dumps(data).encode(), "application/json")
                return
            if self.path in ("/", "/index.html"):
                series = _series(logdir)
                parts = [
                    "<!doctype html><title>kubeflow-tpu tfevents viewer</title>",
                    f"<h2>scalars — {html.escape(logdir)}</h2>",
                ]
                if not series:
                    parts.append("<p>(no tfevents scalars yet — refresh)</p>")
                for tag in sorted(series):
                    parts.append(
                        f"<h4>{html.escape(tag)}</h4>{_svg_chart(series[tag])}"
                    )
                self._reply(200, "\n".join(parts).encode(), "text/html")
                return
            self._reply(404, b"not found", "text/plain")

    return Handler


def main(argv: list[str] | None = None) -> None:
    import argparse

    ap = argparse.ArgumentParser(description="kubeflow-tpu tfevents viewer")
    ap.add_argument("--logdir", required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--host", default="127.0.0.1")
    args = ap.parse_args(argv)
    srv = ThreadingHTTPServer((args.host, args.port), make_handler(args.logdir))
    print(f"tbviewer ready http://{args.host}:{args.port} "
          f"logdir={args.logdir}", flush=True)
    srv.serve_forever()


if __name__ == "__main__":
    main()
