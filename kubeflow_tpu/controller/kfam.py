"""kfam — access management (contributors) for Profile namespaces.

Reference parity (unverified cites, SURVEY.md §2.7): kubeflow/kubeflow
components/access-management exposes the kfam REST API
(`/kfam/v1/bindings`): a Binding grants a user a ClusterRole
(kubeflow-admin/-edit/-view) inside a Profile's namespace, materialized
upstream as RoleBindings + Istio AuthorizationPolicies. The TPU rebuild
keeps the platform-semantic core: bindings are cluster objects reconciled
with the Profile lifecycle, and the apiserver enforces them on namespaced
routes when the caller identifies itself with the upstream
`kubeflow-userid` header. The Istio mesh layer is out of scope
(SURVEY.md §7).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kubeflow_tpu.api.common import ObjectMeta
from kubeflow_tpu.controller.fakecluster import FakeCluster

#: role -> allowed verbs (upstream ClusterRole aggregation, collapsed)
ROLES: dict[str, frozenset] = {
    "admin": frozenset({"get", "list", "watch", "create", "update",
                        "delete", "scale"}),
    "edit": frozenset({"get", "list", "watch", "create", "update",
                       "delete", "scale"}),
    "view": frozenset({"get", "list", "watch"}),
}

#: upstream kfam wire names (roleRef.name) <-> platform role names
_CLUSTERROLE = {"admin": "kubeflow-admin", "edit": "kubeflow-edit",
                "view": "kubeflow-view"}
_FROM_CLUSTERROLE = {v: k for k, v in _CLUSTERROLE.items()}


def binding_name(user: str, role: str) -> str:
    """Deterministic object name, mirroring kfam's user-role RoleBinding
    naming (sanitized: object names are path segments here)."""
    safe = "".join(c if c.isalnum() or c in "-." else "-" for c in user)
    return f"{safe}-{role}".lower()


@dataclass
class AccessBinding:
    """A user's role grant in one namespace (kfam Binding analogue)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    user: str = ""
    role: str = "edit"  # admin | edit | view
    kind: str = "AccessBinding"
    api_version: str = "kubeflow-tpu.org/v1"


def validate_binding(b: AccessBinding) -> None:
    if not b.user:
        raise ValueError("binding must name a user")
    if b.role not in ROLES:
        raise ValueError(
            f"unknown role {b.role!r} (one of {sorted(ROLES)})")
    if not b.metadata.namespace:
        raise ValueError("binding must carry a referredNamespace")


def to_kfam_dict(b: AccessBinding) -> dict:
    """Upstream kfam Binding wire shape."""
    return {
        "user": {"kind": "User", "name": b.user},
        "referredNamespace": b.metadata.namespace,
        "roleRef": {
            "kind": "ClusterRole",
            "name": _CLUSTERROLE.get(b.role, b.role),
        },
    }


def from_kfam_dict(d: dict) -> AccessBinding:
    """Parse the upstream wire shape (roleRef kubeflow-* names accepted
    alongside the bare platform names)."""
    user = (d.get("user") or {}).get("name", "")
    ns = d.get("referredNamespace", "")
    wire_role = (d.get("roleRef") or {}).get("name", "edit")
    role = _FROM_CLUSTERROLE.get(wire_role, wire_role)
    b = AccessBinding(
        metadata=ObjectMeta(name=binding_name(user, role), namespace=ns),
        user=user, role=role,
    )
    validate_binding(b)
    return b


def bindings_for(cluster: FakeCluster, namespace: str) -> list[AccessBinding]:
    return [b for b in cluster.list("bindings")
            if b.metadata.namespace == namespace]


def role_of(cluster: FakeCluster, namespace: str, user: str) -> str | None:
    """A user's effective role in a namespace: profile owner is admin
    (upstream: owner gets the admin RoleBinding), else the strongest
    binding, else None."""
    prof = cluster.get("profiles", f"default/{namespace}")
    if prof is not None and prof.spec.owner and prof.spec.owner == user:
        return "admin"
    best: str | None = None
    order = {"view": 0, "edit": 1, "admin": 2}
    for b in bindings_for(cluster, namespace):
        if b.user == user and (best is None or order[b.role] > order[best]):
            best = b.role
    return best


def can_read(cluster: FakeCluster, namespace: str, user: str) -> bool:
    """Whether `user` may read objects in `namespace` (any role suffices;
    unmanaged namespaces are open)."""
    if cluster.get("profiles", f"default/{namespace}") is None:
        return True
    return role_of(cluster, namespace, user) is not None


def check_access(cluster: FakeCluster, namespace: str, user: str,
                 verb: str) -> None:
    """Raise PermissionError when `user` may not perform `verb` in a
    profile-managed namespace. Unmanaged namespaces are open (no Profile
    -> no kfam authz to enforce, the upstream posture for namespaces
    Kubeflow does not own)."""
    if cluster.get("profiles", f"default/{namespace}") is None:
        return
    role = role_of(cluster, namespace, user)
    if role is None:
        raise PermissionError(
            f"user {user!r} has no role in namespace {namespace!r}")
    if verb not in ROLES[role]:
        raise PermissionError(
            f"user {user!r} role {role!r} does not allow {verb!r} "
            f"in namespace {namespace!r}")
