"""Gang scheduler — the volcano / scheduler-plugins analogue.

All-or-nothing binding: a PodGroup's pods bind only when (a) at least
min_member of them are pending and (b) the cluster has capacity for the
whole gang. On TPU the gang maps to a slice: slice_topology gives the chip
count, and a gang occupies whole slices (SURVEY.md §2.2 gang semantics).
"""

from __future__ import annotations

import copy
import math
import threading

from kubeflow_tpu.analysis.lockcheck import GuardedState, make_lock

from kubeflow_tpu.controller.fakecluster import (
    ConflictError,
    EventType,
    FakeCluster,
    Pod,
    PodGroup,
    PodPhase,
    WatchPoller,
)
from kubeflow_tpu.tracing import NOOP_TRACER, consume_delivered_context
from kubeflow_tpu.utils.retry import with_conflict_retry


def topology_chips(topology: str) -> int:
    """'2x4' -> 8 chips; empty -> 1 chip per pod."""
    if not topology:
        return 0
    return math.prod(int(d) for d in topology.split("x"))


# Well-known priority classes (k8s PriorityClass analogue); numeric strings
# are accepted verbatim so users can define arbitrary levels.
PRIORITY_CLASSES = {
    "": 0,
    "default": 0,
    "low": -1000,
    "high": 1000,
    "system-critical": 2000,
}


def resolve_priority(priority_class: str) -> int:
    if priority_class in PRIORITY_CLASSES:
        return PRIORITY_CLASSES[priority_class]
    try:
        return int(priority_class)
    except ValueError:
        return 0


class GangScheduler:
    def __init__(self, cluster: FakeCluster, chipsched=None):
        self.cluster = cluster
        self.errors = 0  # surfaced so silent failures are still countable
        #: benign optimistic-concurrency losses (an object was replaced
        #: mid-pass; the next event or sweep retries) — counted, never
        #: silently dropped: a storm of these is contention worth seeing
        self.conflicts = 0
        self._stop = threading.Event()
        self._mu = make_lock("gang.GangScheduler._mu")
        # group key -> (group uid, chips held). The uid guards release: a
        # re-meshed job deletes + recreates its podgroup under the SAME key,
        # and the old group's DELETED watch event can arrive after the new
        # group bound — releasing on key alone would drop the replacement's
        # reservation and let other gangs overcommit the chips.
        # GuardedState: every access asserts _mu is held when the lockcheck
        # detector is armed — an unlocked read was the PR-1 wedge's cousin
        # waiting to happen.
        self._guarded = GuardedState(self._mu, bound_chips={})
        # The SHARED chip ledger (scheduler/chipsched.py): capacity math
        # routes through it so training gangs and serving fleets draw
        # from one inventory. A private instance (the default) preserves
        # standalone behavior; client.Platform passes the shared one.
        # Lock order is gang._mu -> chipsched._mu only — the scheduler's
        # evictor callback re-enters us WITHOUT its lock held.
        if chipsched is None:
            from kubeflow_tpu.scheduler.chipsched import ChipScheduler

            chipsched = ChipScheduler(
                capacity_fn=lambda: cluster.capacity_chips,
                tracer_fn=lambda: cluster.tracer)
        self.chipsched = chipsched
        chipsched.evictor = self.evict_for_scheduler

    def start(self) -> None:
        t = threading.Thread(target=self._loop, name="gang-scheduler", daemon=True)
        t.start()

    def stop(self) -> None:
        self._stop.set()

    # ------------------------------------------------------------------ loop

    def _loop(self) -> None:
        def count_error():
            self.errors += 1

        poller = WatchPoller(self.cluster, timeout=0.5,
                             count_error=count_error,
                             kinds=("pods", "podgroups"))
        while not self._stop.is_set():
            ev = poller.get()
            if ev is None:
                # periodic retry: a gang may fit now that capacity freed up
                self._try_schedule_safe()
                continue
            etype, kind, obj = ev
            trigger = (consume_delivered_context()
                       if self.cluster.tracer is not None else None)
            if kind == "podgroups" and etype == EventType.DELETED:
                with self._mu:
                    held = self._guarded.bound_chips.get(obj.key)
                    if held is not None and held[0] == obj.metadata.uid:
                        self._guarded.bound_chips.pop(obj.key)
                        self.chipsched.release(obj.key, uid=obj.metadata.uid)
            if kind in ("pods", "podgroups"):
                self._try_schedule_safe(trigger)

    def _try_schedule_safe(self, trigger=None) -> None:
        try:
            self._try_schedule(trigger)
        except ConflictError:
            self.conflicts += 1  # object replaced mid-pass; next event retries
        except Exception as exc:  # noqa: BLE001 — the scheduler must not die
            self.errors += 1
            self.cluster.record_event(
                "podgroups", "-/gang-scheduler", "SchedulerError",
                f"{type(exc).__name__}: {exc}", type="Warning",
            )

    def _try_schedule(self, trigger=None) -> None:
        # single read (races stop_tracing); the noop fallback keeps every
        # bind site a single with-block instead of traced/untraced twins
        tracer = self.cluster.tracer or NOOP_TRACER
        with self._mu:
            # Priority order: under contention the highest-priority gang
            # admits first; FIFO (creation time) breaks ties so equal-
            # priority gangs can never starve each other.
            groups = sorted(
                self.cluster.list("podgroups"),
                key=lambda g: (
                    -g.priority, g.metadata.creation_timestamp, g.key
                ),
            )
            for pg in groups:
                if pg.phase == "Running":
                    # an admitted gang may still grow members (min_member can
                    # be below the replica total): bind late arrivals so they
                    # are never stranded pending behind an already-bound gang
                    late = [
                        p for p in self._members(pg)
                        if p.status.phase == PodPhase.PENDING and not p.status.node
                    ]
                    if late:
                        # chip-reserved gangs already hold their whole slices;
                        # count-sized gangs need capacity for the extras.
                        # Reservation is recomputed from members actually
                        # covered (bound + late) so a member whose bind failed
                        # and retries here is never charged twice.
                        entry = self._guarded.bound_chips.get(pg.key)
                        held = (
                            entry[1]
                            if entry and entry[0] == pg.metadata.uid
                            else 0
                        )
                        if pg.chips:
                            # chips gangs hold their whole reservation; if
                            # the entry vanished (never for a bound gang in
                            # practice), recharge the full amount
                            extra = 0 if held else pg.chips
                        else:
                            bound = sum(
                                1 for p in self._members(pg) if p.status.node
                            )
                            extra = max(0, bound + len(late) - held)
                        if extra and self._ns_quota_blocked(pg, extra):
                            continue
                        if extra and not self._ledger_add(pg, extra):
                            self.cluster.record_event(
                                "podgroups", pg.key, "Unschedulable",
                                f"late members need {extra} chips, "
                                f"{self.chipsched.free_chips()} free",
                                type="Warning",
                            )
                            continue
                        # reserve before binding: a failed pod update must
                        # never leave bound pods holding uncounted chips
                        self._guarded.bound_chips[pg.key] = (
                            pg.metadata.uid, held + extra
                        )
                        with tracer.span(
                            "gang.bind", parent=trigger, group=pg.key,
                            uid=pg.metadata.uid, members=len(late),
                            chips=extra, late=True,
                        ):
                            self._bind(late, prefix="slice-0-host-late")
                    continue
                members = self._members(pg)
                pending = [
                    p for p in members
                    if p.status.phase == PodPhase.PENDING and not p.status.node
                ]
                if len(pending) < pg.min_member:
                    continue
                chips_needed = pg.chips or len(pending)
                # per-namespace chip quota FIRST (Profile, SURVEY.md §2.7):
                # a quota-blocked gang can never use preempted chips, so it
                # must not be allowed to evict anyone
                if self._ns_quota_blocked(pg, chips_needed):
                    continue
                # admission routes through the SHARED ledger: serving
                # replica claims count against the same inventory, and
                # the grant records the slice placement
                grant = self._ledger_claim(pg, chips_needed)
                if not grant.ok:
                    # volcano preempt-action analogue: a higher-priority gang
                    # may evict strictly-lower-priority bound gangs (their
                    # jobs gang-restart from checkpoint once capacity frees).
                    # Only a CAPACITY deny escalates — a quota/frozen deny
                    # could never use the preempted chips.
                    if grant.reason == "capacity":
                        if self._try_preempt(
                            pg, chips_needed - self.chipsched.free_chips()
                        ):
                            grant = self._ledger_claim(pg, chips_needed)
                    if not grant.ok:
                        self.cluster.record_event(
                            "podgroups", pg.key, "Unschedulable",
                            f"gang needs {chips_needed} chips, "
                            f"{self.chipsched.free_chips()} free",
                            type="Warning",
                        )
                        continue
                # All-or-nothing ADMISSION: reserve chips + flip the group to
                # Running first; then bind members. If a member bind fails
                # mid-loop (pod replaced concurrently), the reservation is
                # already counted and the survivors are picked up by the
                # late-member path above — never an uncounted half-gang.
                self._guarded.bound_chips[pg.key] = (pg.metadata.uid, chips_needed)
                # copy-before-mutate: a rejected write must leave the STORED
                # group untouched (phase still Pending) so the next sweep
                # re-admits it cleanly instead of seeing a half-flipped state
                admitted = copy.deepcopy(pg)
                admitted.phase = "Running"
                try:
                    self.cluster.update("podgroups", admitted)
                except (ConflictError, KeyError):
                    # group replaced/deleted/contended under us: release and
                    # move on; the periodic sweep retries admission
                    self._guarded.bound_chips.pop(pg.key, None)
                    self.chipsched.release(pg.key, uid=pg.metadata.uid)
                    continue
                with tracer.span(
                    "gang.bind", parent=trigger, group=pg.key,
                    uid=pg.metadata.uid, members=len(pending),
                    chips=chips_needed,
                ):
                    self._bind(pending, prefix="slice-0-host")
                self.cluster.record_event(
                    "podgroups", pg.key, "Scheduled",
                    f"gang of {len(pending)} bound ({chips_needed} chips)",
                )

    def _try_preempt(self, pg: PodGroup, need: int) -> bool:
        """Evict bound gangs with priority strictly below pg's until `need`
        chips are released. Victims: lowest priority first, then newest
        (least sunk work). Eviction = unbind (delete pods, reset the group
        to Pending, release the reservation); the owning job controller
        recreates the pods and the gang re-admits when capacity allows —
        the same checkpoint-restart path a worker loss takes. Caller holds
        _mu. Returns True if enough was (or already were) released."""
        if need <= 0:
            return True
        victims = []
        available = 0
        for other in self.cluster.list("podgroups"):
            entry = self._guarded.bound_chips.get(other.key)
            if entry is None or entry[0] != other.metadata.uid:
                continue
            if other.priority >= pg.priority:
                continue
            victims.append(other)
            available += entry[1]
        if available < need:
            # preemption cannot succeed: evicting anyway would thrash
            # lower-priority jobs through pointless restarts every pass
            return False
        # lowest priority first; NEWEST first within a level (least sunk
        # work lost) — two stable sorts
        victims.sort(key=lambda o: o.metadata.creation_timestamp, reverse=True)
        victims.sort(key=lambda o: o.priority)
        released = 0
        for victim in victims:
            if released >= need:
                break
            entry = self._guarded.bound_chips.pop(victim.key, None)
            if entry is None:
                continue
            released += entry[1]
            self.chipsched.release(victim.key, uid=entry[0])
            tracer = self.cluster.tracer  # single read: races stop_tracing
            if tracer is not None:
                tracer.event(
                    "gang.preempt", victim=victim.key, chips=entry[1],
                    by=pg.key,
                )
            evicted = copy.deepcopy(victim)  # never half-flip the stored one
            evicted.phase = "Pending"
            try:
                self.cluster.update("podgroups", evicted)
            except (ConflictError, KeyError):
                # reservation already released; the sweep re-admits
                self.conflicts += 1
            for p in self._members(victim):
                try:
                    self.cluster.delete("pods", p.key)
                except KeyError:
                    pass
            self.cluster.record_event(
                "podgroups", victim.key, "Preempted",
                f"evicted ({entry[1]} chips) for higher-priority gang "
                f"{pg.key} (priority {pg.priority} > {victim.priority})",
                type="Warning",
            )
            self.cluster.record_event(
                "jobs", victim.key, "Preempted",
                f"gang preempted by {pg.key}; will gang-restart when "
                f"capacity frees",
                type="Warning",
            )
        return released >= need

    # ---------------------------------------------------- the shared ledger

    def _ledger_claim(self, pg: PodGroup, chips: int):
        """Admission-path claim against the shared inventory. Tenant is
        the gang's namespace; the gang does its OWN preemption (below),
        so the ledger never evicts on a gang's behalf."""
        return self.chipsched.claim_gang(
            pg.key, pg.metadata.uid, chips, priority=pg.priority,
            tenant=pg.metadata.namespace, preempt=False)

    def _ledger_add(self, pg: PodGroup, extra: int) -> bool:
        """Late-member growth: extend the held claim, or recharge a
        vanished one (a bound chips-gang whose entry was lost)."""
        if self.chipsched.held(pg.key):
            return self.chipsched.grow_gang(pg.key, pg.metadata.uid, extra)
        return self._ledger_claim(pg, extra).ok

    def evict_for_scheduler(self, key: str, uid: str, chips: int,
                            carrier: str, by: str = "") -> bool:
        """Scheduler-driven preemption (a serving claim evicted this
        gang). Unlike gang-vs-gang preemption — which deletes pods and
        lets the owner recreate them — the victims' pods are marked
        FAILED with the PREEMPTED exit class (retryable) and the
        ``sched.preempt`` span context as their exit carrier, so the
        job controller's gang-restart path owns the teardown: the
        ``job.gang_restart`` event parent-links to the preemption,
        backoff rides RESTART_BACKOFF, and the compile-cache warm
        resume composes unchanged (docs/scheduler.md). Called by the
        ChipScheduler WITHOUT its lock held."""
        import time as _time

        from kubeflow_tpu.api.common import PREEMPTED_EXIT_CODE
        from kubeflow_tpu.tracing import CARRIER_ANNOTATION

        with self._mu:
            held = self._guarded.bound_chips.get(key)
            if held is None or held[0] != uid:
                return False
            self._guarded.bound_chips.pop(key)
        pg = self.cluster.get("podgroups", key)
        if pg is not None and pg.metadata.uid == uid:
            evicted = copy.deepcopy(pg)  # never half-flip the stored one
            evicted.phase = "Pending"
            try:
                self.cluster.update("podgroups", evicted)
            except (ConflictError, KeyError):
                self.conflicts += 1
            members = self._members(pg)
        else:
            members = []
        for p in members:
            if p.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
                continue

            def attempt(pkey=p.key, puid=p.metadata.uid):
                cur = self.cluster.get("pods", pkey, copy_obj=True)
                if cur is None or cur.metadata.uid != puid:
                    return None
                if cur.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
                    return None  # raced a real exit: its verdict wins
                cur.status.phase = PodPhase.FAILED
                cur.status.exit_code = PREEMPTED_EXIT_CODE
                cur.status.finish_time = _time.time()
                cur.status.message = f"Preempted: chips reclaimed for {by}"
                if carrier:
                    cur.metadata.annotations[CARRIER_ANNOTATION] = carrier
                return self.cluster.update("pods", cur)

            try:
                with_conflict_retry(attempt)
            except (ConflictError, KeyError):
                self.conflicts += 1
        self.cluster.record_event(
            "podgroups", key, "Preempted",
            f"evicted ({chips} chips) by chip scheduler for {by}; "
            f"gang-restarts when capacity frees", type="Warning",
        )
        self.cluster.record_event(
            "jobs", key, "Preempted",
            f"gang preempted by scheduler claim {by}; will gang-restart",
            type="Warning",
        )
        return True

    # ------------------------------------------------------- capacity views

    def free_chips(self) -> int:
        """Chips free in the SHARED ledger — not held by any bound gang
        OR serving replica claim (autoscaler input)."""
        return self.chipsched.free_chips()

    def pending_demand_chips(self, exclude_keys: set[str] | None = None) -> int:
        """Total chips wanted by gangs that are ready (>= min_member pending
        members), not yet bound, and SATISFIABLE — the capacity pressure an
        autoscaler should yield to. Gangs that can never bind (bigger than
        total capacity, or namespace-quota-blocked) are excluded: shrinking
        for them would pin the yielder at min forever while chips sit idle.
        `exclude_keys` masks a job's own group(s). Pods are grouped in one
        list pass (this is called from every autoscaled job's reconcile)."""
        with self._mu:
            holdings = dict(self._guarded.bound_chips)
        return self._pending_demand(holdings, exclude_keys)

    def demand_and_free(
        self, exclude_keys: set[str] | None = None
    ) -> tuple[int, int]:
        """ONE consistent (pending demand, free chips) snapshot — the
        fix for the paired-read race: pending_demand_chips() then
        free_chips() as two calls lets a bind land in between, counting
        the same gang's chips in BOTH numbers (demand at read one, used
        at read two) and over-growing the autoscaler's target. Here the
        holdings snapshot and the free count come from one pass, and a
        pending group that ALREADY holds a ledger reservation (the
        reserve->flip-Running admission window) is skipped from demand
        and counted as double-count-avoided chips."""
        with self._mu:
            holdings = dict(self._guarded.bound_chips)
            free = self.chipsched.free_chips()
        avoided = [0]
        demand = self._pending_demand(holdings, exclude_keys, avoided)
        self.chipsched.note_double_count_avoided(avoided[0])
        return demand, free

    def _pending_demand(self, holdings: dict,
                        exclude_keys: set[str] | None,
                        avoided: list | None = None) -> int:
        demand = 0
        bound = {k: uid for k, (uid, _) in holdings.items()}
        pending_by_group: dict[str, int] = {}
        for p in self.cluster.list("pods"):
            if p.group_name and p.status.phase == PodPhase.PENDING and not p.status.node:
                gk = f"{p.metadata.namespace}/{p.group_name}"
                pending_by_group[gk] = pending_by_group.get(gk, 0) + 1
        for pg in self.cluster.list("podgroups"):
            if pg.phase == "Running" or bound.get(pg.key) == pg.metadata.uid:
                if (avoided is not None and pg.phase != "Running"
                        and bound.get(pg.key) == pg.metadata.uid):
                    # reserved but not yet flipped Running: the old
                    # paired reads would have double-counted these chips
                    avoided[0] += holdings[pg.key][1]
                continue
            if exclude_keys and pg.key in exclude_keys:
                continue
            pending = pending_by_group.get(pg.key, 0)
            if pending < pg.min_member:
                continue
            chips = pg.chips or pending
            if chips > self.cluster.capacity_chips:
                continue  # can never bind on this cluster
            if self._ns_quota_would_block(pg, chips, holdings):
                continue  # quota, not capacity, is the blocker
            demand += chips
        return demand

    def _bind(self, pods: list[Pod], prefix: str) -> None:
        """Bind each pod, tolerating concurrent replacement of individuals
        (the group's reservation is already held by the caller).

        Conflict-retried copy-on-write, NOT in-place mutation: setting
        .node on the live stored object and then losing the update to a
        ConflictError leaves the store showing a bound pod that no watch
        event ever announced — the runtime never launches it and the
        late-member path (which keys on `not status.node`) never rebinds
        it, wedging the gang forever."""
        for i, p in enumerate(pods):
            node = f"{prefix}-{i}"

            def attempt(key=p.key, uid=p.metadata.uid, node=node):
                cur = self.cluster.get("pods", key, copy_obj=True)
                if cur is None or cur.metadata.uid != uid:
                    return None  # replaced; late path rebinds the new one
                if cur.status.node or cur.status.phase != PodPhase.PENDING:
                    return None  # already bound/advanced elsewhere
                cur.status.node = node
                return self.cluster.update("pods", cur)

            try:
                with_conflict_retry(attempt)
            except (ConflictError, KeyError):
                self.conflicts += 1
                continue  # kept conflicting; the periodic sweep rebinds it

    def _ns_quota_would_block(
        self, pg: PodGroup, chips_needed: int, holdings: dict
    ) -> bool:
        """Pure quota check (no event) — shared by admission (which holds
        _mu and passes the live dict) and the demand view (which passes a
        locked snapshot, since _bound_chips must not be read unlocked)."""
        from kubeflow_tpu.controller.profile import namespace_quota

        ns = pg.metadata.namespace
        quota = namespace_quota(self.cluster, ns)
        if quota is None or quota.chips is None:
            return False
        ns_used = sum(
            c for k, (_, c) in holdings.items() if k.split("/", 1)[0] == ns
        )
        return ns_used + chips_needed > quota.chips

    def _ns_quota_blocked(self, pg: PodGroup, chips_needed: int) -> bool:
        """Admission-path quota check (caller holds _mu); records the event."""
        from kubeflow_tpu.controller.profile import namespace_quota

        if not self._ns_quota_would_block(pg, chips_needed, self._guarded.bound_chips):
            return False
        quota = namespace_quota(self.cluster, pg.metadata.namespace)
        ns_used = sum(
            c for k, (_, c) in self._guarded.bound_chips.items()
            if k.split("/", 1)[0] == pg.metadata.namespace
        )
        self.cluster.record_event(
            "podgroups", pg.key, "QuotaExceeded",
            f"namespace {pg.metadata.namespace} quota {quota.chips} chips, "
            f"{quota.chips - ns_used} free",
            type="Warning",
        )
        return True

    def _members(self, pg: PodGroup) -> list[Pod]:
        return self.cluster.list(
            "pods",
            lambda p: p.group_name == pg.metadata.name
            and p.metadata.namespace == pg.metadata.namespace,
        )

