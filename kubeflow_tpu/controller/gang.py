"""Gang scheduler — the volcano / scheduler-plugins analogue.

All-or-nothing binding: a PodGroup's pods bind only when (a) at least
min_member of them are pending and (b) the cluster has capacity for the
whole gang. On TPU the gang maps to a slice: slice_topology gives the chip
count, and a gang occupies whole slices (SURVEY.md §2.2 gang semantics).
"""

from __future__ import annotations

import math
import threading

from kubeflow_tpu.controller.fakecluster import (
    EventType,
    FakeCluster,
    Pod,
    PodGroup,
    PodPhase,
)


def topology_chips(topology: str) -> int:
    """'2x4' -> 8 chips; empty -> 1 chip per pod."""
    if not topology:
        return 0
    return math.prod(int(d) for d in topology.split("x"))


class GangScheduler:
    def __init__(self, cluster: FakeCluster):
        self.cluster = cluster
        self._stop = threading.Event()
        self._mu = threading.Lock()
        self._bound_chips: dict[str, int] = {}  # group key -> chips held

    def start(self) -> None:
        t = threading.Thread(target=self._loop, name="gang-scheduler", daemon=True)
        t.start()

    def stop(self) -> None:
        self._stop.set()

    # ------------------------------------------------------------------ loop

    def _loop(self) -> None:
        q = self.cluster.watch()
        while not self._stop.is_set():
            try:
                etype, kind, obj = q.get(timeout=0.5)
            except Exception:
                # periodic retry: a gang may fit now that capacity freed up
                self._try_schedule()
                continue
            if kind == "podgroups" and etype == EventType.DELETED:
                with self._mu:
                    self._bound_chips.pop(obj.key, None)
            if kind in ("pods", "podgroups"):
                self._try_schedule()

    def _try_schedule(self) -> None:
        with self._mu:
            groups = self.cluster.list("podgroups")
            for pg in groups:
                if pg.phase == "Running":
                    # an admitted gang may still grow members (min_member can
                    # be below the replica total): bind late arrivals so they
                    # are never stranded pending behind an already-bound gang
                    late = [
                        p for p in self._members(pg)
                        if p.status.phase == PodPhase.PENDING and not p.status.node
                    ]
                    if late:
                        # chip-reserved gangs already hold their whole slices;
                        # count-sized gangs need capacity for the extras
                        extra = 0 if pg.chips else len(late)
                        used = sum(self._bound_chips.values())
                        if used + extra > self.cluster.capacity_chips:
                            self.cluster.record_event(
                                "podgroups", pg.key, "Unschedulable",
                                f"late members need {extra} chips, "
                                f"{self.cluster.capacity_chips - used} free",
                                type="Warning",
                            )
                            continue
                        for i, p in enumerate(late):
                            p.status.node = f"slice-0-host-late-{i}"
                            self.cluster.update("pods", p)
                        self._bound_chips[pg.key] = (
                            self._bound_chips.get(pg.key, 0) + extra
                        )
                    continue
                members = self._members(pg)
                pending = [
                    p for p in members
                    if p.status.phase == PodPhase.PENDING and not p.status.node
                ]
                if len(pending) < pg.min_member:
                    continue
                chips_needed = pg.chips or len(pending)
                used = sum(self._bound_chips.values())
                if used + chips_needed > self.cluster.capacity_chips:
                    self.cluster.record_event(
                        "podgroups", pg.key, "Unschedulable",
                        f"gang needs {chips_needed} chips, "
                        f"{self.cluster.capacity_chips - used} free",
                        type="Warning",
                    )
                    continue
                # all-or-nothing bind
                for i, p in enumerate(pending):
                    p.status.node = f"slice-0-host-{i}"
                    self.cluster.update("pods", p)
                self._bound_chips[pg.key] = chips_needed
                pg.phase = "Running"
                self.cluster.update("podgroups", pg)
                self.cluster.record_event(
                    "podgroups", pg.key, "Scheduled",
                    f"gang of {len(pending)} bound ({chips_needed} chips)",
                )

    def _members(self, pg: PodGroup) -> list[Pod]:
        return self.cluster.list(
            "pods",
            lambda p: p.group_name == pg.metadata.name
            and p.metadata.namespace == pg.metadata.namespace,
        )

    def release(self, group_key: str) -> None:
        with self._mu:
            self._bound_chips.pop(group_key, None)
