"""Rendezvous env-contract synthesis — layer L3, 'the distributed glue'.

This is the moment the platform earns its keep: materializing the ~6 env vars
+ stable DNS names that let N freshly-started processes find each other.

Reference parity (unverified cites, SURVEY.md §2.1/§3.1):
  - TFJob:      pkg/controller.v1/tensorflow/tfjob_controller.go#SetClusterSpec
                (TF_CONFIG JSON {cluster:{worker:[...],ps:[...]},task:{type,index}})
  - PyTorchJob: pkg/controller.v1/pytorch/envvar.go#SetPodEnv
                (MASTER_ADDR/MASTER_PORT/WORLD_SIZE/RANK, elastic PET_*)
  - MPIJob:     pkg/controller.v1/mpi (hostfile ConfigMap)
  - XGBoost/Paddle: DMLC_* / PADDLE_* env families

TPU-native contract (the flagship JAXJob): `jax.distributed.initialize` needs
coordinator address + process count + process id; GKE TPU adds TPU_WORKER_ID,
TPU_WORKER_HOSTNAMES, and for multislice MEGASCALE_* (SURVEY.md §3 note).

Everything here is a pure function of (job, rtype, index) -> env dict, so the
whole contract is unit-testable byte-for-byte with no cluster — the
reference's own highest-value test pattern (SURVEY.md §4).
"""

from __future__ import annotations

import json
import os

from kubeflow_tpu.utils.envvars import ENV_PROFILE_DIR, ENV_STATE_DIR
from kubeflow_tpu.api.jobs import (
    DEFAULT_PORTS,
    JobKind,
    REPLICA_CHIEF,
    REPLICA_LAUNCHER,
    REPLICA_MASTER,
    REPLICA_PS,
    REPLICA_SCHEDULER,
    REPLICA_SERVER,
    REPLICA_WORKER,
    REPLICA_EVALUATOR,
    TrainJob,
)

# Order TF_CONFIG cluster roles are emitted in (stable ordering matters for
# golden tests and for ps/worker index semantics).
_TF_ROLE_ORDER = [REPLICA_CHIEF, REPLICA_MASTER, REPLICA_WORKER, REPLICA_PS, REPLICA_EVALUATOR]


def _has(job: TrainJob, rtype: str) -> bool:
    """A replica group 'exists' only with replicas > 0 (a zero-replica spec
    must not become a rendezvous target)."""
    rs = job.spec.replica_specs.get(rtype)
    return rs is not None and rs.replicas > 0


def job_port(job: TrainJob, rtype: str | None = None) -> int:
    """Rendezvous port for one replica group: that group's own declared
    container port wins over the per-framework default (the reference
    controllers read each replica's named container port)."""
    if rtype is not None:
        rs = job.spec.replica_specs.get(rtype)
        if rs is not None and rs.template.container.ports:
            return next(iter(rs.template.container.ports.values()))
    return DEFAULT_PORTS[job.kind]


def replica_addresses(job: TrainJob, rtype: str, port: int | None = None) -> list[str]:
    """host:port list for one replica group — the headless-Service DNS contract."""
    if port is None:
        port = job_port(job, rtype)
    rs = job.spec.replica_specs.get(rtype)
    if rs is None:
        return []
    return [f"{job.replica_hostname(rtype, i)}:{port}" for i in range(rs.replicas)]


# ---------------------------------------------------------------- JAX (flagship)

def jax_env(job: TrainJob, rtype: str, index: int) -> dict[str, str]:
    """Env for one JAXJob worker process.

    Process 0 hosts the jax.distributed coordination service; every process
    gets the same coordinator address + its own process id. The GKE TPU var
    shapes (TPU_WORKER_ID/TPU_WORKER_HOSTNAMES/MEGASCALE_*) are emitted too so
    the same synthesis would be correct on a real TPU nodepool.
    """
    port = job.spec.coordinator_port
    workers = job.spec.replica_specs[REPLICA_WORKER].replicas
    coord = f"{job.replica_hostname(REPLICA_WORKER, 0)}:{port}"
    hostnames = ",".join(
        job.replica_hostname(REPLICA_WORKER, i) for i in range(workers)
    )
    env = {
        "JAX_COORDINATOR_ADDRESS": coord,
        "JAX_NUM_PROCESSES": str(workers),
        "JAX_PROCESS_ID": str(index),
        # GKE TPU-shaped vars (jax.distributed auto-detects these on Cloud TPU)
        "TPU_WORKER_ID": str(index),
        "TPU_WORKER_HOSTNAMES": hostnames,
    }
    if job.spec.num_slices > 1:
        # validate_job enforces workers % num_slices == 0 (equal-sized slices).
        per_slice = workers // job.spec.num_slices
        env["MEGASCALE_COORDINATOR_ADDRESS"] = coord
        env["MEGASCALE_NUM_SLICES"] = str(job.spec.num_slices)
        env["MEGASCALE_SLICE_ID"] = str(index // per_slice)
    if job.spec.profile_dir:
        # per-process subdir so N workers' traces never collide
        env[ENV_PROFILE_DIR] = f"{job.spec.profile_dir}/process-{index}"
    return env


# ---------------------------------------------------------------------- TFJob

def tf_config(job: TrainJob, rtype: str, index: int, port: int | None = None) -> str:
    """TF_CONFIG JSON for one replica (SetClusterSpec parity). Each role's
    addresses carry that role's own port."""
    cluster: dict[str, list[str]] = {}
    for role in _TF_ROLE_ORDER:
        addrs = replica_addresses(job, role, port)
        if addrs:
            cluster[role] = addrs
    payload = {
        "cluster": cluster,
        "task": {"type": rtype, "index": index},
        "environment": "cloud",
    }
    return json.dumps(payload, separators=(",", ":"), sort_keys=False)


def tf_env(job: TrainJob, rtype: str, index: int) -> dict[str, str]:
    return {"TF_CONFIG": tf_config(job, rtype, index)}


# ------------------------------------------------------------------ PyTorchJob

def pytorch_env(job: TrainJob, rtype: str, index: int) -> dict[str, str]:
    """MASTER_ADDR/PORT, WORLD_SIZE, RANK (+ PET_* when elastic).

    Rank convention mirrors envvar.go: master is rank 0; worker i is rank i+1
    when a master replica exists, else rank i.
    """
    has_master = _has(job, REPLICA_MASTER)
    master_rtype = REPLICA_MASTER if has_master else REPLICA_WORKER
    port = job_port(job, master_rtype)
    master_host = job.replica_hostname(master_rtype, 0)
    world = job.total_replicas()
    if rtype == REPLICA_MASTER:
        rank = 0
    else:
        rank = index + 1 if has_master else index

    env = {
        "MASTER_ADDR": master_host,
        "MASTER_PORT": str(port),
        "WORLD_SIZE": str(world),
        "RANK": str(rank),
    }
    ep = job.spec.run_policy.elastic_policy
    if ep is not None:
        env.update(
            {
                "PET_RDZV_BACKEND": ep.rdzv_backend,
                "PET_RDZV_ENDPOINT": f"{master_host}:{port}",
                "PET_MIN_NNODES": str(ep.min_replicas),
                "PET_MAX_NNODES": str(ep.max_replicas),
                "PET_NNODES": f"{ep.min_replicas}:{ep.max_replicas}",
                "PET_NPROC_PER_NODE": str(ep.nproc_per_node),
                "PET_MAX_RESTARTS": str(ep.max_restarts),
            }
        )
    return env


# --------------------------------------------------------------------- MPIJob

def mpi_hostfile(job: TrainJob, slots_per_worker: int = 1) -> str:
    """Hostfile content (the ConfigMap the MPI controller mounts)."""
    rs = job.spec.replica_specs.get(REPLICA_WORKER)
    n = rs.replicas if rs else 0
    return "".join(
        f"{job.replica_hostname(REPLICA_WORKER, i)} slots={slots_per_worker}\n"
        for i in range(n)
    )


def mpi_hostfile_path(job: TrainJob) -> str:
    """Where the job controller materializes the hostfile (the ConfigMap-
    mount analogue): a per-job path every pod can read. Override the root
    with KFTPU_STATE_DIR."""
    root = os.environ.get(ENV_STATE_DIR, ".kubeflow_tpu")
    return os.path.abspath(
        os.path.join(
            root, "mpi", job.metadata.namespace, job.metadata.name, "hostfile"
        )
    )


def mpi_env(job: TrainJob, rtype: str, index: int) -> dict[str, str]:
    rs = job.spec.replica_specs.get(REPLICA_WORKER)
    n = rs.replicas if rs else 0
    env = {
        "OMPI_MCA_orte_keep_fqdn_hostnames": "true",
        # the controller writes this file before any pod starts
        # (jobcontroller._materialize_hostfile)
        "OMPI_MCA_orte_default_hostfile": mpi_hostfile_path(job),
    }
    if rtype == REPLICA_LAUNCHER:
        env["OMPI_MCA_orte_set_default_slots"] = "1"
        env["MPI_NUM_WORKERS"] = str(n)
    return env


# --------------------------------------------------------------------- MXJob

def mxnet_env(job: TrainJob, rtype: str, index: int) -> dict[str, str]:
    """DMLC_* family (reference pkg/controller.v1/mxnet — SURVEY.md §2.1
    XGBoost/Paddle/MXNet row): every process learns the scheduler's address,
    its own role, and the server/worker counts."""
    sched_host = job.replica_hostname(REPLICA_SCHEDULER, 0)
    port = job_port(job, REPLICA_SCHEDULER)
    servers = job.spec.replica_specs.get(REPLICA_SERVER)
    workers = job.spec.replica_specs.get(REPLICA_WORKER)
    return {
        "DMLC_ROLE": rtype,
        "DMLC_PS_ROOT_URI": sched_host,
        "DMLC_PS_ROOT_PORT": str(port),
        "DMLC_NUM_SERVER": str(servers.replicas if servers else 0),
        "DMLC_NUM_WORKER": str(workers.replicas if workers else 0),
        "DMLC_USE_KUBERNETES": "1",
    }


# ------------------------------------------------------------ XGBoost / Paddle

def xgboost_env(job: TrainJob, rtype: str, index: int) -> dict[str, str]:
    """Rabit tracker env (DMLC_* family)."""
    has_master = _has(job, REPLICA_MASTER)
    master_rtype = REPLICA_MASTER if has_master else REPLICA_WORKER
    port = job_port(job, master_rtype)
    master_host = job.replica_hostname(master_rtype, 0)
    workers = job.spec.replica_specs.get(REPLICA_WORKER)
    n_workers = workers.replicas if workers else 0
    if rtype == REPLICA_MASTER:
        rank = 0
    else:
        rank = index + 1 if has_master else index
    return {
        "MASTER_HOST": master_host,
        "MASTER_PORT": str(port),
        "WORLD_SIZE": str(job.total_replicas()),
        "RANK": str(rank),
        "WORKER_HOSTS": ",".join(a.rsplit(":", 1)[0] for a in replica_addresses(job, REPLICA_WORKER, port)),
        "WORKER_PORT": str(port),
        "DMLC_TRACKER_URI": master_host,
        "DMLC_TRACKER_PORT": str(port),
        "DMLC_NUM_WORKER": str(n_workers),
    }


def paddle_env(job: TrainJob, rtype: str, index: int) -> dict[str, str]:
    all_eps = replica_addresses(job, REPLICA_MASTER) + replica_addresses(
        job, REPLICA_WORKER
    )
    rank = 0 if rtype == REPLICA_MASTER else index + (
        1 if _has(job, REPLICA_MASTER) else 0
    )
    return {
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(job.total_replicas()),
        "PADDLE_TRAINER_ENDPOINTS": ",".join(all_eps),
        "PADDLE_CURRENT_ENDPOINT": all_eps[rank] if rank < len(all_eps) else "",
    }


# ------------------------------------------------------------------- dispatch

_SYNTH = {
    JobKind.JAX: jax_env,
    JobKind.TF: tf_env,
    JobKind.PYTORCH: pytorch_env,
    JobKind.MPI: mpi_env,
    JobKind.XGBOOST: xgboost_env,
    JobKind.PADDLE: paddle_env,
    JobKind.MXNET: mxnet_env,
}


def synthesize_env(job: TrainJob, rtype: str, index: int) -> dict[str, str]:
    """Full env for one replica process: framework contract + identity labels.

    User-specified container env wins over synthesized env, matching the
    reference controllers' append-if-absent behavior.
    """
    env = dict(_SYNTH[job.kind](job, rtype, index))
    env.setdefault("JOB_NAME", job.metadata.name)
    env.setdefault("REPLICA_TYPE", rtype)
    env.setdefault("REPLICA_INDEX", str(index))
    user_env = job.spec.replica_specs[rtype].template.container.env
    env.update(user_env)
    return env
