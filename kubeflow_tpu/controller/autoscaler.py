"""Capacity autoscaler for elastic training jobs — the HPA analogue.

Reference parity: the pytorch operator creates a HorizontalPodAutoscaler for
elastic PyTorchJobs (training-operator pkg/controller.v1/pytorch/hpa.go —
SURVEY.md §2.1 PyTorchJob row), scaling workers between ElasticPolicy
min/max on external metrics. A TPU platform has a better native signal than
pod CPU: **chip capacity**. This controller scales opted-in elastic jobs

  - UP toward max_replicas while idle chips exist and nothing else wants
    them (finish faster when the cluster is quiet),
  - DOWN toward min_replicas when other gangs are ready but Unschedulable
    (yield capacity instead of starving the queue),

always in whole-worker (= whole-slice) steps through the same
`apply_elastic_scale` mutation the SDK uses, so every invariant (elastic
bounds, slice granularity, min_available clamping) holds. Each scale lands
as a gang re-mesh driven by the job controller; a stabilization window
(cooldown, HPA's stabilizationWindowSeconds analogue) keeps re-mesh churn
bounded — scaling is expensive on TPU (checkpoint-restore), so the window
defaults high.

Opt-in via the job annotation `kubeflow-tpu.org/autoscale: "capacity"`.
"""

from __future__ import annotations

import time

from kubeflow_tpu.api.jobs import (
    LAST_SCALE_ANNOTATION,
    REPLICA_WORKER,
    apply_elastic_scale,
)
from kubeflow_tpu.controller.base import ControllerBase
from kubeflow_tpu.controller.fakecluster import EventType, FakeCluster
from kubeflow_tpu.controller.gang import GangScheduler, topology_chips

AUTOSCALE_ANNOTATION = "kubeflow-tpu.org/autoscale"
POLICY_CAPACITY = "capacity"


class TrainingAutoscaler(ControllerBase):
    """Scales elastic, annotation-opted-in jobs on chip capacity."""

    WATCH_KINDS = ("jobs", "podgroups")

    def __init__(
        self,
        cluster: FakeCluster,
        scheduler: GangScheduler,
        cooldown_s: float = 30.0,
        **kw,
    ):
        super().__init__(cluster, "training-autoscaler", **kw)
        self.scheduler = scheduler
        self.cooldown_s = cooldown_s
        self.metrics.update({
            "autoscaler_scale_ups_total": 0,
            "autoscaler_scale_downs_total": 0,
        })

    # ------------------------------------------------------------- hooks

    def kind_filter(self, etype, kind: str, obj) -> str | None:
        if kind == "jobs" and etype != EventType.DELETED:
            if self._opted_in(obj):
                return f"{obj.namespace}/{obj.name}"
            return None
        # capacity changes can unblock any autoscaled job — but only fan out
        # on events that actually move capacity or demand: group created
        # (new demand), deleted (chips freed), or bound (phase flipped to
        # Running). Member-churn MODIFIED events on still-pending groups are
        # the bulk of bind-storm traffic and change neither.
        if kind == "podgroups" and (
            etype in (EventType.ADDED, EventType.DELETED)
            or getattr(obj, "phase", None) == "Running"
        ):
            for key in self.resync_keys():
                self.wq.add(key)
        return None

    def resync_keys(self):
        return [
            f"{j.namespace}/{j.name}"
            for j in self.cluster.list("jobs")
            if self._opted_in(j)
        ]

    @staticmethod
    def _opted_in(job) -> bool:
        return (
            job.metadata.annotations.get(AUTOSCALE_ANNOTATION) == POLICY_CAPACITY
            and job.spec.run_policy.elastic_policy is not None
        )

    # --------------------------------------------------------- reconcile

    def reconcile(self, key: str) -> float | None:
        job = self.cluster.get("jobs", key, copy_obj=True)
        if job is None or job.status.is_finished or not self._opted_in(job):
            return None
        if job.spec.run_policy.suspend:
            return None
        ep = job.spec.run_policy.elastic_policy
        workers = job.spec.replica_specs.get(REPLICA_WORKER)
        if workers is None:
            return None
        replicas = workers.replicas

        # stabilization window: a re-mesh is a checkpoint-restore cycle;
        # never thrash
        last = float(job.metadata.annotations.get(LAST_SCALE_ANNOTATION, 0))
        remaining = self.cooldown_s - (time.time() - last)
        if remaining > 0:
            return remaining

        sp = job.spec.run_policy.scheduling_policy
        if sp is not None and sp.slice_topology and job.spec.num_slices <= 1:
            # fixed-chip job: the podgroup reserves topology_chips regardless
            # of worker count (chips = topo x num_slices), so scaling workers
            # frees/claims nothing — the capacity policy cannot help, only
            # burn re-meshes. Chips scale with workers only for count-sized
            # gangs (1 chip/worker) and multi-slice jobs (whole slices).
            return None

        chips_per_worker = self._chips_per_worker(job, replicas)
        own_groups = {f"{job.namespace}/{job.name}"}
        # ONE snapshot for both numbers: the old paired reads (demand
        # then free) let a concurrent bind count the same gang's chips
        # in both, over-growing the target — the shared ledger's
        # demand_and_free closes that window and counts what it avoided
        demand, free = self.scheduler.demand_and_free(exclude_keys=own_groups)
        rs = job.status.replica_statuses.get(REPLICA_WORKER)
        if rs is not None and (rs.succeeded > 0 or rs.failed > 0):
            # completing or recovering: pods EXITED — any scale would re-mesh
            # (restart) a job that is finishing or that the job controller is
            # already handling. Leave it alone.
            return None
        # The bound/unbound signal is the job's PODGROUP phase, not replica
        # statuses: rs.active counts PENDING pods too, so a created-but-
        # unbound gang looks "fully active" while its chips still read as
        # free — growing on that signal wedges the job above capacity.
        pg = self.cluster.get("podgroups", key)
        gang_bound = pg is not None and pg.phase == "Running"

        target = replicas
        unmet = demand - free  # queued demand the free pool cannot absorb
        if unmet > 0 and replicas > ep.min_replicas:
            # yield only what the free pool can't cover (a rival that fits in
            # idle chips binds untouched — a re-mesh for it would be waste),
            # never below min; one step per cooldown window keeps it damped
            give = -(-unmet // chips_per_worker)  # ceil
            target = max(ep.min_replicas, replicas - give)
        elif not gang_bound:
            # own gang unbound (mid-re-mesh or starved) — its chips are not
            # charged, so they read as "free"; growing here would claim chips
            # the gang itself needs. If idle chips (minus whatever queued
            # gangs will take) cover the whole gang, just wait for the bind;
            # if not, the chips were taken — shrink to the largest size that
            # can actually bind.
            effective_free = max(0, free - demand)
            if effective_free < replicas * chips_per_worker:
                fits = effective_free // chips_per_worker
                target = max(ep.min_replicas, min(replicas, fits))
        elif demand == 0 and free >= chips_per_worker and replicas < ep.max_replicas:
            # steady state (gang bound), idle capacity, nothing queued: grow
            target = min(ep.max_replicas, replicas + free // chips_per_worker)
        target = self._slice_align(job, replicas, target)
        if not (ep.min_replicas <= target <= ep.max_replicas):
            target = replicas  # alignment left no valid size; stay put
        if target == replicas:
            return None

        # optimistic concurrency on the ORIGINAL snapshot: if anything (user
        # scale, job controller) wrote the job after we read it, the update
        # conflicts and the native driver requeues — never apply a decision
        # computed from a stale view onto a newer object. apply_elastic_scale
        # stamps the stabilization window (shared with manual scale_job).
        apply_elastic_scale(job, target)
        self.cluster.update("jobs", job)
        direction = "up" if target > replicas else "down"
        self.metrics[f"autoscaler_scale_{direction}s_total"] += 1
        self.cluster.record_event(
            "jobs", key, "Autoscaled",
            f"capacity autoscaler: {replicas} -> {target} workers "
            f"(free={free} demand={demand} chips/worker={chips_per_worker})",
        )
        return self.cooldown_s

    @staticmethod
    def _chips_per_worker(job, replicas: int) -> int:
        sp = job.spec.run_policy.scheduling_policy
        if sp is not None and sp.slice_topology:
            total = topology_chips(sp.slice_topology) * max(1, job.spec.num_slices)
            return max(1, total // max(1, replicas))
        return 1

    @staticmethod
    def _slice_align(job, replicas: int, target: int) -> int:
        """Round a target to whole-slice worker multiples (toward `replicas`
        staying conservative: down when growing, up when shrinking), and
        clamp to >= one slice — apply_elastic_scale rejects non-multiples."""
        if job.spec.num_slices <= 1 or target == replicas:
            return target
        per_slice = replicas // job.spec.num_slices
        if per_slice <= 0:
            return replicas
        if target > replicas:
            aligned = (target // per_slice) * per_slice
        else:
            aligned = -(-target // per_slice) * per_slice  # ceil
        return max(per_slice, aligned)
