"""PodRuntime — the kubelet analogue: bound pods become real subprocesses.

Also hosts the default (non-gang) scheduler and the fault injector used by
failure-handling tests (SURVEY.md §5.3: the reference has no built-in fault
injection; its e2e tests kill pods manually — here it's first-class).
"""

from __future__ import annotations

import os
import signal
import subprocess
import threading
import time
from pathlib import Path

from kubeflow_tpu.controller.fakecluster import (
    ConflictError,
    EventType,
    FakeCluster,
    Pod,
    PodPhase,
    WatchPoller,
)
from kubeflow_tpu.controller.statusbuffer import StatusWriteBuffer
from kubeflow_tpu.health import ENV_HEARTBEAT_FILE, read_heartbeat
from kubeflow_tpu.tracing import (
    CARRIER_ANNOTATION,
    consume_delivered_context,
    current_context,
)
from kubeflow_tpu.analysis.lockcheck import make_lock
try:  # resolved ONCE in the parent: the post-fork child must not import or
    # allocate (another thread may hold the import/malloc lock at fork time)
    import ctypes as _ctypes

    _LIBC = _ctypes.CDLL("libc.so.6", use_errno=True)
except Exception:  # noqa: BLE001 — non-Linux/no-libc degrades to stop()/atexit
    _LIBC = None


def _die_with_parent(runtime_pid: int) -> None:
    """Child-side preexec: SIGKILL this pod if the runtime process dies.

    Teardown hygiene (VERDICT r2 weak #7): atexit/stop() cannot run when the
    hosting process is SIGTERM/SIGKILLed (an aborted pytest run was observed
    leaking a serving.server pod across sessions), but the kernel delivers
    PR_SET_PDEATHSIG regardless of how the parent died. Only pre-bound libc
    calls and raw syscalls happen here — fork-safe by construction. The
    post-prctl getppid check closes the race where the runtime dies between
    fork() and prctl(): the reparented child sees a different parent and
    exits instead of leaking unarmed.
    """
    if _LIBC is not None:
        _LIBC.prctl(1, signal.SIGKILL, 0, 0, 0)  # 1 = PR_SET_PDEATHSIG
        if os.getppid() != runtime_pid:
            os._exit(1)


class PodRuntime:
    """Watches pods; launches bound ones as subprocesses; reaps exits."""

    def __init__(
        self,
        cluster: FakeCluster,
        log_dir: str = ".kubeflow_tpu/pod-logs",
        inherit_env: bool = True,
        bind_pending_default: bool = True,
    ):
        self.cluster = cluster
        self.log_dir = Path(log_dir)
        self.inherit_env = inherit_env
        self.bind_pending_default = bind_pending_default
        self.errors = 0  # surfaced so silent failures are still countable
        #: events dropped because they raced a gang restart (stale
        #: incarnation / conflicting write) — benign, but countable so a
        #: storm of them is visible instead of silently absorbed
        self.stale_event_drops = 0
        #: coalescing group-commit for pod status transitions: N
        #: concurrent bind/Running/finished writes fold into one locked
        #: flush (docs/architecture.md "Control-plane scaling")
        self.status_writes = StatusWriteBuffer(cluster, kind="pods")
        #: fault-injection attachment point (chaos.ChaosEngine.attach)
        self.chaos = None
        self._procs: dict[str, tuple[str, subprocess.Popen]] = {}
        self._mu = make_lock("podruntime.PodRuntime._mu")
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # tracing side tables (only populated while cluster.tracer is set):
        # launch-span / kill-injection contexts keyed by (pod key, uid) —
        # the uid guard matters during gang restarts, where the old
        # incarnation's reaper runs concurrently with the NEW incarnation's
        # launch under the same key and must not steal its context
        self._launch_ctx: dict[tuple[str, str], object] = {}
        self._kill_ctx: dict[tuple[str, str], object] = {}
        # liveness side table: heartbeat file per live incarnation (from the
        # pod env contract), so the kubelet layer can surface per-pod
        # heartbeat age (kftpu_health_heartbeat_age_seconds)
        self._hb_paths: dict[tuple[str, str], str] = {}

    # ---------------------------------------------------------------- lifecycle

    def start(self) -> None:
        self.log_dir.mkdir(parents=True, exist_ok=True)
        # unconditional teardown on orderly interpreter exit; PDEATHSIG on
        # the pods covers disorderly ones (see _die_with_parent). Registered
        # per start() and unregistered in stop() so stopped runtimes are not
        # pinned alive for the interpreter lifetime.
        import atexit

        atexit.register(self.stop)
        t = threading.Thread(target=self._watch_loop, name="pod-runtime", daemon=True)
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        import atexit

        atexit.unregister(self.stop)
        self._stop.set()
        # drain coalesced status writes before killing pods: a buffered
        # "finished" transition must not be lost to teardown
        self.status_writes.close()
        with self._mu:
            procs = [proc for _, proc in self._procs.values()]
        for p in procs:
            # kill the whole session (pods may fork workers), like _kill does
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                try:
                    p.kill()
                except ProcessLookupError:
                    pass

    # ---------------------------------------------------------------- watching

    def _watch_loop(self) -> None:
        def count_error():
            self.errors += 1

        poller = WatchPoller(self.cluster, timeout=0.2,
                             count_error=count_error, kinds=("pods",))
        while not self._stop.is_set():
            ev = poller.get()
            if ev is None:
                continue
            etype, kind, obj = ev
            if kind != "pods":
                continue
            trigger = (consume_delivered_context()
                       if self.cluster.tracer is not None else None)
            try:
                self._handle_pod_event(etype, obj, trigger)
            except ConflictError:
                # stale event for a replaced incarnation — droppable, but
                # never silently: a storm of these means a controller is
                # fighting the runtime over pod status
                self.stale_event_drops += 1
                continue
            except Exception as exc:  # noqa: BLE001 — the kubelet must not die
                self.errors += 1
                self.cluster.record_event(
                    "pods", obj.key, "PodRuntimeError",
                    f"{type(exc).__name__}: {exc}", type="Warning",
                )

    def _handle_pod_event(self, etype: EventType, pod: Pod,
                          trigger=None) -> None:
        if etype == EventType.DELETED:
            tracer = self.cluster.tracer
            if tracer is not None and pod.key in self._procs:
                # parent = whatever deleted the pod (gang restart teardown,
                # cascade delete) — the kill is visible in that span's tree
                tracer.event("pod.kill", parent=trigger, pod=pod.key,
                             uid=pod.metadata.uid)
            self._kill(pod.key)
            return
        # Events deliver the object as of notify time; after a delete+
        # recreate (gang re-mesh) under the same name, the store holds a NEW
        # incarnation — act only on the current one.
        current = self.cluster.get("pods", pod.key)
        if current is None or current.metadata.uid != pod.metadata.uid:
            return
        pod = current
        if pod.status.phase == PodPhase.PENDING:
            if not pod.status.node and (
                pod.scheduler_name == "default" and self.bind_pending_default
            ):
                def bind(p):
                    if p.status.node or p.status.phase != PodPhase.PENDING:
                        return False  # someone else bound/advanced it
                    p.status.node = "local-node"

                # conflict-safe: a dropped bind would orphan the pod forever
                # (no resync re-delivers pod events)
                self._update_pod_status(pod.key, pod.metadata.uid, bind)
            elif pod.status.node:
                self._launch(pod, trigger)

    def _update_pod_status(self, key: str, uid: str, mutate_status) -> bool:
        """Coalesced status write gated on the pod incarnation: the
        kubelet must never lose a status transition to a concurrent writer
        (a silently dropped ConflictError here strands the pod — and with
        it the whole gang — in its previous phase), and must never stamp a
        NEW incarnation with the old one's verdict. Returns False when the
        pod is gone or replaced. N transitions landing together (a gang's
        worth of Running writes, a reap wave) fold into one locked flush
        via StatusWriteBuffer; injected conflicts still exercise the
        single-op retry path."""
        try:
            return self.status_writes.write(key, uid, mutate_status)
        except (ConflictError, KeyError):
            # retry budget exhausted under a genuine storm, or deleted
            # mid-write: surfaced as a countable runtime error, not a hang
            self.errors += 1
            self.cluster.record_event(
                "pods", key, "PodStatusWriteLost",
                "status write kept conflicting", type="Warning",
            )
            return False

    # ---------------------------------------------------------------- execution

    def _launch(self, pod: Pod, trigger=None) -> None:
        tracer = self.cluster.tracer
        if tracer is None:
            return self._launch_pod(pod)
        # the span covers injected startup stalls + spawn + the Running
        # status write, parented to the bind/reconcile event that caused it;
        # its context is kept so pod.exit can link back to this incarnation
        with tracer.span("pod.launch", parent=trigger, pod=pod.key,
                         uid=pod.metadata.uid, node=pod.status.node) as sp:
            with self._mu:  # _kill sweeps these tables under the lock
                self._launch_ctx[(pod.key, pod.metadata.uid)] = sp.context
            return self._launch_pod(pod)

    def _launch_pod(self, pod: Pod) -> None:
        if self.chaos is not None:
            # injected startup stall (slow image pull / TPU slice allocation)
            # happens before the runtime lock — it delays THIS pod's spawn,
            # not the reaping of every other pod
            self.chaos.on_pod_launch(pod)
        with self._mu:
            held = self._procs.get(pod.key)
            if held is not None:
                held_uid, held_proc = held
                if held_uid == pod.metadata.uid:
                    return  # already running this incarnation
                # same name, new incarnation (gang restart): the old process
                # must die before the new one starts
                try:
                    os.killpg(held_proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
            log_path = self.log_path(pod.metadata.name, pod.metadata.namespace)
            log_path.parent.mkdir(parents=True, exist_ok=True)
            env = dict(os.environ) if self.inherit_env else {}
            env.update(pod.env)
            if self.chaos is not None:
                # cross-process fault carriers (e.g. seeded heartbeat-write
                # drops) ride the env into the worker
                env.update(self.chaos.pod_env(pod))
            command = list(pod.command)
            if command and command[0] in ("python", "python3"):
                # symbolic interpreter: manifests and remote clients say
                # "python" (or the k8s-idiomatic "python3"); the SERVER
                # resolves it to its own interpreter (client-side
                # sys.executable may not exist here)
                import sys as _sys

                command[0] = _sys.executable
            try:
                with open(log_path, "wb") as logf:  # child dups the fd
                    proc = subprocess.Popen(
                        command,
                        env=env,
                        stdout=logf,
                        stderr=subprocess.STDOUT,
                        cwd=pod.working_dir or None,
                        start_new_session=True,  # isolate signals per pod
                        preexec_fn=lambda pid=os.getpid(): _die_with_parent(pid),
                    )
            except OSError as exc:
                def spawn_failed(p, msg=str(exc)):
                    p.status.phase = PodPhase.FAILED
                    p.status.exit_code = 127
                    p.status.message = msg

                self._update_pod_status(
                    pod.key, pod.metadata.uid, spawn_failed
                )
                return
            self._procs[pod.key] = (pod.metadata.uid, proc)
            hb_path = pod.env.get(ENV_HEARTBEAT_FILE, "")
            if hb_path:
                self._hb_paths[(pod.key, pod.metadata.uid)] = hb_path

        def running(p, pid=proc.pid):
            p.status.phase = PodPhase.RUNNING
            p.status.pid = pid
            p.status.start_time = time.time()

        if not self._update_pod_status(pod.key, pod.metadata.uid, running):
            # the pod was deleted/replaced while we were spawning its
            # process: the process must not outlive its (gone) pod
            self._kill(pod.key)
            return
        threading.Thread(
            target=self._reap, args=(pod.key, pod.metadata.uid, proc), daemon=True
        ).start()

    def _reap(self, key: str, uid: str, proc: subprocess.Popen) -> None:
        code = proc.wait()
        if code < 0:
            # signal death normalizes to the k8s/shell 128+signum convention,
            # which is what is_retryable_exit_code speaks (SIGKILL -> 137:
            # retryable infrastructure loss; plain exit(1) stays permanent)
            code = 128 - code
        with self._mu:
            held = self._procs.get(key)
            if held is not None and held[1] is proc:
                self._procs.pop(key, None)
            self._hb_paths.pop((key, uid), None)

        def finished(p):
            if p.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
                return False  # verdict already recorded (injected failure)
            p.status.exit_code = code
            p.status.finish_time = time.time()
            p.status.phase = (
                PodPhase.SUCCEEDED if code == 0 else PodPhase.FAILED
            )

        # conflict-retried: losing this write would leave a completed pod
        # Running forever and the owning job unfinishable
        tracer = self.cluster.tracer
        if tracer is None:
            self._update_pod_status(key, uid, finished)
            return
        # parent-link the exit to what ended the incarnation — an injected
        # kill when one was recorded, else the launch — and run the status
        # write INSIDE the span so its MODIFIED watch event carries this
        # context: kill -> exit -> (watch) -> reconcile is one chain
        # pop BOTH side-table entries (a short-circuiting `or` of pops
        # would leak the launch ctx of every killed incarnation), then
        # prefer the kill as the more causal parent; locked so _kill's
        # table sweep never iterates a dict resizing under it
        with self._mu:
            kill_ctx = self._kill_ctx.pop((key, uid), None)
            launch_ctx = self._launch_ctx.pop((key, uid), None)
        parent = kill_ctx or launch_ctx
        with tracer.span("pod.exit", parent=parent, pod=key, uid=uid,
                         exit_code=code) as sp:
            # a tracer disarmed mid-flight yields the noop span, whose
            # context is None — then there is simply no carrier to stamp
            ctx = sp.context
            carrier = ctx.to_header() if ctx is not None else ""

            def finished_with_carrier(p):
                if finished(p) is False:
                    return False
                # the exit's span context travels ON the object: whatever
                # controller acts on this failure later (the gang-restart
                # decision) can parent-link to it, immune to watch-delivery
                # coalescing races
                if carrier:
                    p.metadata.annotations[CARRIER_ANNOTATION] = carrier

            self._update_pod_status(key, uid, finished_with_carrier)

    def _kill(self, key: str) -> None:
        with self._mu:
            # drop side-table entries for EVERY incarnation of this key (the
            # dicts are small: bounded by live pods plus in-flight reaps);
            # under the lock — a reaper popping concurrently would resize
            # the dict mid-iteration
            for table in (self._launch_ctx, self._kill_ctx, self._hb_paths):
                for k in [k for k in table if k[0] == key]:
                    table.pop(k, None)
            held = self._procs.pop(key, None)
        if held is not None:
            _, proc = held
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                try:
                    proc.kill()
                except ProcessLookupError:
                    pass

    # -------------------------------------------------------------- liveness

    def heartbeat_ages(self, now: float | None = None) -> dict[tuple[str, str], float]:
        """Per-incarnation heartbeat age in seconds for every live pod that
        has heartbeat at least once — the kubelet-side liveness surface
        (exported as kftpu_health_heartbeat_age_seconds). Pods that never
        beat are absent: they are unmonitored, not stale."""
        now = time.time() if now is None else now
        with self._mu:
            entries = list(self._hb_paths.items())
        out: dict[tuple[str, str], float] = {}
        for (key, uid), path in entries:
            hb = read_heartbeat(path)
            if hb is not None:
                out[(key, uid)] = max(now - hb.ts, 0.0)
        return out

    # ---------------------------------------------------------------- faults

    def inject_kill(self, key: str, sig: int = signal.SIGKILL) -> bool:
        """Fault injector: kill a running pod's process (worker-loss drill)."""
        with self._mu:
            held = self._procs.get(key)
        if held is None:
            return False
        if self.cluster.tracer is not None:
            # remember the injector's span so the reaped exit links to it
            # (the chaos engine fires kills inside an annotated span);
            # keyed to the incarnation actually being killed
            ctx = current_context()
            if ctx is not None:
                with self._mu:  # _kill sweeps these tables under the lock
                    self._kill_ctx[(key, held[0])] = ctx
        _, proc = held
        try:
            os.killpg(proc.pid, sig)
        except (ProcessLookupError, PermissionError):
            proc.send_signal(sig)
        return True

    def log_path(self, pod_name: str, namespace: str = "default") -> Path:
        # namespaced so same-named pods in two namespaces never share (and
        # truncate) one log file — sweeps parse these for objective values
        return self.log_dir / namespace / f"{pod_name}.log"
