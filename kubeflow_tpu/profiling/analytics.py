"""Trace analytics — turning flight-recorder spans into answers.

PR 2 gave the platform raw spans (tracing/); this module is the layer that
computes from them:

  - a per-step time breakdown: for every `train.step` (or `train.chunk`)
    span, the step CYCLE is the wall-clock from the end of the previous
    step to the end of this one. Inside the cycle, `data_load` is the
    host-side fetch time (train.data_load spans), `checkpoint` the
    checkpoint.save/restore time, `compute` the step span's own duration,
    and `stall` is DEFINED as the remainder — so the four phases sum to
    the cycle wall-time exactly and unattributed time is visible instead
    of silently vanishing (the MLPerf-tuning loop of 1909.09756 runs on
    exactly this accounting);
  - goodput per job incarnation: productive step time vs rendezvous /
    checkpoint / restart overhead, attributed to the causal chain the
    cross-process parent links carry (chaos kill -> pod exit -> gang
    restart -> create -> first post-restore step);
  - control-plane latency: reconcile-duration and watch-delivery
    percentiles per controller, derived from the EXISTING reconcile /
    http.request spans — no new instrumentation (2011.03641: at fleet
    scale the control plane, not the chips, caps concurrency).

Everything operates on plain span dicts (tracing/core.Span.to_dict):
{"name", "trace", "span", "parent", "ts", "dur", "pid", "tid", "attrs"}.
"""

from __future__ import annotations

#: span names that delimit a training step cycle
STEP_NAMES = ("train.step", "train.chunk")
#: host-side input-pipeline spans accounted inside a cycle
DATA_NAMES = ("train.data_load",)
#: checkpoint I/O spans accounted inside a cycle
CKPT_NAMES = ("checkpoint.save", "checkpoint.restore")
#: gradient-communication spans accounted inside a cycle: host-visible
#: time spent waiting on gradient collectives that did NOT overlap the
#: backward pass (the grad_overlap cpu-proxy workload emits these; on
#: hardware a step with full comm/compute overlap shows ~zero here)
COMM_NAMES = ("train.comm",)
#: span names that only the PLATFORM process emits — used to tell a
#: platform-bearing trace apart from a workers-only flush directory
PLATFORM_SPAN_NAMES = frozenset((
    "reconcile", "http.request", "http.watch", "gang.bind", "gang.preempt",
    "job.create_pods", "job.rendezvous", "job.gang_restart",
    "pod.launch", "pod.exit", "pod.kill",
))

#: shared histogram buckets for the kftpu_prof_* families (seconds)
PROF_BUCKETS: tuple[float, ...] = (0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0)

#: the serving request root span (serving/fleet/router, serving/continuous)
REQUEST_ROOT = "request"
#: request child-span name -> breakdown phase it is charged to
REQUEST_PHASE_NAMES = {
    "request.admission": "admission",
    "engine.queue_wait": "queue",
    "engine.prefill_chunk": "prefill",
    "engine.decode": "decode",
}
#: the phases of a request cycle, in charge order (stall = remainder)
REQUEST_PHASES = ("admission", "queue", "prefill", "decode", "stall")


def percentile(sorted_values: list[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list (0 when empty).
    Nearest-rank (not interpolated) so a percentile is always a value that
    actually occurred — the honest form for latency reporting."""
    if not sorted_values:
        return 0.0
    idx = max(0, min(len(sorted_values) - 1,
                     int(round(q * (len(sorted_values) - 1)))))
    return sorted_values[idx]


def _end(s: dict) -> float:
    return s["ts"] + s["dur"]


# ------------------------------------------------------ step-time breakdown


def step_breakdown(spans: list[dict]) -> list[dict]:
    """Per-step phase accounting, one dict per step cycle.

    Steps are grouped per worker process (pid): the cycle window runs from
    the end of the worker's previous step (or its first span's start, for
    the first step) to the end of this step. A phase span is charged to the
    cycle its END falls inside — fetch/save work is sequential with the
    step dispatch on the worker thread, so windows partition the phases.
    Each returned dict satisfies
    ``data_load + compute + checkpoint + comm + stall == wall`` (stall is
    the remainder, floored at 0 against float noise). ``comm`` counts
    `train.comm` spans — gradient-collective time left ON the critical
    path; a fully overlapped step charges ~nothing here (ROADMAP item 5's
    comm/compute-overlap front, gated by the grad_overlap workload).

    data_load itself splits sum-exactly into ``data_wait + data_assemble
    == data_load``: when the async host loader stamps a ``wait_s`` attr
    (queue-blocked time — what the critical path actually paid),
    data_wait is that portion (clamped to the span) and data_assemble the
    in-span remainder; spans without the attr (the inline loader) are all
    assemble — the split shows how much host work the background thread
    moved OFF the critical path.
    """
    by_pid: dict[int, list[dict]] = {}
    for s in spans:
        by_pid.setdefault(s.get("pid", 0), []).append(s)
    out: list[dict] = []
    for pid in sorted(by_pid):
        ss = sorted(by_pid[pid], key=lambda s: s["ts"])
        steps = [s for s in ss if s["name"] in STEP_NAMES]
        if not steps:
            continue
        data = sorted((s for s in ss if s["name"] in DATA_NAMES),
                      key=_end)
        ckpt = sorted((s for s in ss if s["name"] in CKPT_NAMES),
                      key=_end)
        comm = sorted((s for s in ss if s["name"] in COMM_NAMES),
                      key=_end)
        prev_end = ss[0]["ts"]
        for st in sorted(steps, key=_end):
            end = _end(st)
            # a degenerate window (clock step between processes) still
            # charges at least the step's own duration
            wall = max(end - prev_end, st["dur"])
            in_window = lambda s: prev_end < _end(s) <= end  # noqa: E731
            d = wait = 0.0
            for s in data:
                if in_window(s):
                    d += s["dur"]
                    # wait is clamped to the span so the split can never
                    # exceed what the cycle was actually charged
                    wait += min(float(s["attrs"].get("wait_s", 0.0)),
                                s["dur"])
            c = sum(s["dur"] for s in ckpt if in_window(s))
            cm = sum(s["dur"] for s in comm if in_window(s))
            compute = st["dur"]
            stall = max(wall - compute - d - c - cm, 0.0)
            out.append({
                "pid": pid,
                "step": st["attrs"].get("step"),
                "ts": st["ts"],
                "wall": wall,
                "data_load": d,
                "data_wait": wait,
                "data_assemble": d - wait,
                "compute": compute,
                "checkpoint": c,
                "comm": cm,
                "stall": stall,
            })
            prev_end = end
    return out


def aggregate_steps(steps: list[dict]) -> dict:
    """Totals + per-step distribution over step_breakdown() output."""
    phases = ("data_load", "compute", "checkpoint", "comm", "stall")
    totals = {p: sum(s[p] for s in steps) for p in phases}
    wall = sum(s["wall"] for s in steps)
    walls = sorted(s["wall"] for s in steps)
    data = totals["data_load"]
    wait = sum(s["data_wait"] for s in steps)
    return {
        "count": len(steps),
        "wall_s": round(wall, 6),
        "phases_s": {p: round(v, 6) for p, v in totals.items()},
        # the async-loader split of data_load (wait + assemble == load):
        # assemble is host work still ON the critical path — the number
        # the AsyncLoader exists to drive toward zero
        "data_load_split": {
            "queue_wait_s": round(wait, 6),
            "assemble_s": round(data - wait, 6),
        },
        "fractions": {
            p: (round(v / wall, 4) if wall else 0.0)
            for p, v in totals.items()
        },
        "per_step": {
            "mean_s": round(wall / len(steps), 6) if steps else 0.0,
            "p50_s": round(percentile(walls, 0.50), 6),
            "p99_s": round(percentile(walls, 0.99), 6),
        },
    }


# ------------------------------------------------------- goodput accounting


def goodput(spans: list[dict], steps: list[dict] | None = None) -> dict:
    """Productive step time vs overhead, per job incarnation.

    Incarnations are keyed by `job.create_pods` spans (their `restart`
    attribute); worker spans parent-link to the create span that launched
    them via the pod-env traceparent, so attribution needs no name
    heuristics. Without any create span (an in-process training run) all
    steps form one implicit incarnation. The window is the whole span
    snapshot's extent; goodput = productive / window.
    """
    if steps is None:
        steps = step_breakdown(spans)
    if not spans:
        return {"window_s": 0.0, "productive_s": 0.0, "overhead_s": 0.0,
                "restart_overhead_s": 0.0, "goodput": 0.0,
                "incarnations": []}
    t0 = min(s["ts"] for s in spans)
    t1 = max(_end(s) for s in spans)
    window = max(t1 - t0, 0.0)

    creates = sorted((s for s in spans if s["name"] == "job.create_pods"),
                     key=lambda s: s["ts"])
    by_parent: dict[str, list[dict]] = {}
    for s in spans:
        by_parent.setdefault(s.get("parent", ""), []).append(s)

    def _overheads(children: list[dict]) -> tuple[float, float]:
        rdv = sum(s["dur"] for s in children
                  if s["name"] in ("rendezvous", "runtime.rendezvous"))
        ck = sum(s["dur"] for s in children if s["name"] in CKPT_NAMES)
        return rdv, ck

    incarnations: list[dict] = []
    if creates:
        for c in creates:
            kids = by_parent.get(c["span"], [])
            kid_steps = [s for s in kids if s["name"] in STEP_NAMES]
            rdv, ck = _overheads(kids)
            incarnations.append({
                "restart": c["attrs"].get("restart", 0),
                "steps": len(kid_steps),
                "productive_s": round(sum(s["dur"] for s in kid_steps), 6),
                "rendezvous_s": round(rdv, 6),
                "checkpoint_s": round(ck, 6),
            })
    else:
        rdv, ck = _overheads(spans)
        incarnations.append({
            "restart": 0,
            "steps": len(steps),
            "productive_s": round(sum(s["compute"] for s in steps), 6),
            "rendezvous_s": round(rdv, 6),
            "checkpoint_s": round(ck, 6),
        })
    productive = sum(i["productive_s"] for i in incarnations)
    overhead = sum(i["rendezvous_s"] + i["checkpoint_s"]
                   for i in incarnations)
    # restart overhead: wall-clock each recovery chain spent between the
    # root cause (the kill) and the first post-restore step
    chains = restart_chains(spans)
    restart_s = sum(ch["overhead_s"] for ch in chains)
    # total overhead must not double-count: the restarted incarnation's
    # rendezvous lies INSIDE its restart window (it precedes the first
    # post-restore step by definition), so subtract it from the window's
    # contribution — overhead can then never exceed elapsed wall-clock
    by_restart = {i["restart"]: i for i in incarnations}
    non_overlap_restart = sum(
        max(ch["overhead_s"]
            - by_restart.get(ch["restart"], {}).get("rendezvous_s", 0.0),
            0.0)
        for ch in chains
    )
    return {
        "window_s": round(window, 6),
        "productive_s": round(productive, 6),
        "overhead_s": round(overhead + non_overlap_restart, 6),
        "restart_overhead_s": round(restart_s, 6),
        "goodput": round(productive / window, 4) if window else 0.0,
        "incarnations": incarnations,
    }


# ------------------------------------------------- control-plane analytics


def control_plane_stats(spans: list[dict]) -> dict:
    """Reconcile + watch-delivery percentiles per controller, and
    http.request latency — all from the spans PR 2 already emits.

    Watch-delivery latency is the gap between the END of the span whose
    write published the triggering event (the reconcile span's parent,
    when it is still in the snapshot) and the reconcile pass starting.
    """
    by_id = {s["span"]: s for s in spans}
    recs: dict[str, list[dict]] = {}
    for s in spans:
        if s["name"] != "reconcile":
            continue
        recs.setdefault(str(s["attrs"].get("controller", "?")), []).append(s)
    out: dict = {"reconcile": {}, "http": {}}
    for ctrl in sorted(recs):
        group = recs[ctrl]
        durs = sorted(s["dur"] for s in group)
        delays = []
        depths = [s["attrs"]["queue_depth"] for s in group
                  if "queue_depth" in s["attrs"]]
        for s in group:
            parent = by_id.get(s.get("parent", ""))
            if parent is not None:
                delays.append(max(s["ts"] - _end(parent), 0.0))
        delays.sort()
        out["reconcile"][ctrl] = {
            "count": len(group),
            "p50_s": round(percentile(durs, 0.50), 6),
            "p90_s": round(percentile(durs, 0.90), 6),
            "p99_s": round(percentile(durs, 0.99), 6),
            "watch_delay_p50_s": round(percentile(delays, 0.50), 6),
            "watch_delay_p99_s": round(percentile(delays, 0.99), 6),
            "watch_delay_samples": len(delays),
            "mean_queue_depth": (
                round(sum(depths) / len(depths), 2) if depths else 0.0),
        }
    https = sorted(s["dur"] for s in spans if s["name"] == "http.request")
    if https:
        out["http"] = {
            "count": len(https),
            "p50_s": round(percentile(https, 0.50), 6),
            "p99_s": round(percentile(https, 0.99), 6),
        }
    return out


# ---------------------------------------------- restart causal attribution


def ancestry(spans: list[dict], leaf: dict) -> list[dict]:
    """The parent chain of `leaf`, root first, leaf last — following the
    cross-process links the carriers threaded through. Stops at a parent
    that fell off the ring (renders as a root, same as the text tree)."""
    by_id = {s["span"]: s for s in spans}
    chain = [leaf]
    seen = {leaf["span"]}
    cur = leaf
    while True:
        parent = by_id.get(cur.get("parent", ""))
        if parent is None or parent["span"] in seen:
            break
        chain.append(parent)
        seen.add(parent["span"])
        cur = parent
    chain.reverse()
    return chain


def _resolve_chains(spans: list[dict]) -> list[dict]:
    """The shared restart-chain resolution both restart_chains() (the
    numeric summary) and restart_shape() (the golden text) render from —
    one matching rule, so a fix to it can never leave the two surfaces
    disagreeing. Each record carries the actual span dicts:
    {"rs", "up", "create", "kids", "steps", "rendezvous", "first_step"}.

    A restart decision is matched to its `job.create_pods` span by the
    restart counter AND the job key (both spans carry `key`): two jobs
    restarting concurrently both have restart=1, and counter-only
    matching would attribute one job's recovery to the other's pods.
    """
    by_parent: dict[str, list[dict]] = {}
    for s in spans:
        by_parent.setdefault(s.get("parent", ""), []).append(s)
    creates = sorted((s for s in spans if s["name"] == "job.create_pods"),
                     key=lambda s: s["ts"])
    out = []
    for rs in sorted((s for s in spans if s["name"] == "job.gang_restart"),
                     key=lambda s: s["ts"]):
        restart = rs["attrs"].get("restart")
        key = rs["attrs"].get("key")
        create = next(
            (c for c in creates
             if c["attrs"].get("restart") == restart
             and (key is None or c["attrs"].get("key") in (None, key))),
            None,
        )
        kids = by_parent.get(create["span"], []) if create else []
        kid_steps = sorted((s for s in kids if s["name"] in STEP_NAMES),
                           key=lambda s: s["ts"])
        out.append({
            "rs": rs,
            "up": ancestry(spans, rs),
            "create": create,
            "kids": kids,
            "steps": kid_steps,
            "rendezvous": [s for s in kids if s["name"] in
                           ("rendezvous", "runtime.rendezvous")],
            "first_step": kid_steps[0] if kid_steps else None,
        })
    return out


def restart_chains(spans: list[dict]) -> list[dict]:
    """One record per gang restart: the upward causal chain (e.g. chaos
    kill -> pod exit -> restart decision), the matching restart
    incarnation's create/rendezvous/step spans, the wall-clock overhead
    from the chain root to the first post-restore step, and whether the
    whole path is monotonic in wall-clock.

    overhead_s splits sum-exactly into ``compile_s + restore_s +
    rendezvous_s + schedule_s``: compile is the incarnation's
    train.compile span(s) (the re-trace+recompile cost the restart-warm
    cache exists to erase), restore its checkpoint.restore, rendezvous
    its gang bring-up, and schedule the remainder — the control-plane
    path from the root cause through pod exit, restart decision, create,
    bind, and process start (each floored at 0 against clock skew)."""
    chains = []
    for r in _resolve_chains(spans):
        up, create, first_step = r["up"], r["create"], r["first_step"]
        path = up + ([create] if create else []) \
            + ([first_step] if first_step else [])
        stamps = [s["ts"] for s in path]
        overhead = (round(max(first_step["ts"] - up[0]["ts"], 0.0), 6)
                    if first_step and up else 0.0)
        # phase spans of THIS incarnation that precede its first step:
        # only time inside the overhead window can be attributed to it
        pre = [s for s in r["kids"]
               if first_step is None or s["ts"] < first_step["ts"]]
        compile_s = min(sum(s["dur"] for s in pre
                            if s["name"] == "train.compile"), overhead)
        restore_s = min(sum(s["dur"] for s in pre
                            if s["name"] == "checkpoint.restore"),
                        max(overhead - compile_s, 0.0))
        rdv_s = min(sum(s["dur"] for s in r["rendezvous"]
                        if first_step is None
                        or s["ts"] < first_step["ts"]),
                    max(overhead - compile_s - restore_s, 0.0))
        compile_s = round(compile_s, 6)
        restore_s = round(restore_s, 6)
        rdv_s = round(rdv_s, 6)
        chains.append({
            "restart": r["rs"]["attrs"].get("restart"),
            "chain": [s["name"] for s in path],
            "root": up[0]["name"] if up else "",
            "overhead_s": overhead,
            "compile_s": compile_s,
            "restore_s": restore_s,
            "rendezvous_s": rdv_s,
            "schedule_s": max(round(
                overhead - compile_s - restore_s - rdv_s, 6), 0.0),
            "rendezvous": len(r["rendezvous"]),
            "steps": len(r["steps"]),
            "monotonic": stamps == sorted(stamps),
        })
    return chains


def restart_shape(spans: list[dict]) -> str:
    """Canonical, golden-pinnable text form of every restart chain: span
    NAMES and PARENTAGE only (no ids, no times), repeated worker spans
    collapsed to `name xN`, plus a monotonicity verdict — so a structural
    regression in the causal links (a dropped carrier, a reparented
    restart) diffs loudly while timing noise never does."""
    lines: list[str] = []
    for rec, r in zip(restart_chains(spans), _resolve_chains(spans)):
        for depth, s in enumerate(r["up"]):
            extra = ""
            if s["name"] == "pod.exit":
                extra = f" exit_code={s['attrs'].get('exit_code')}"
            elif s["name"] == "job.gang_restart":
                extra = f" restart={s['attrs'].get('restart')}"
            lines.append("  " * depth + s["name"] + extra)
        if r["create"] is not None:
            lines.append(
                "job.create_pods restart="
                f"{r['create']['attrs'].get('restart')}")
            # WORKER children only: platform spans can legitimately race
            # onto either parent (a pod.launch parents to the bind OR the
            # create depending on watch-delivery order), and the shape pin
            # must never flake on a benign race
            counts: dict[str, int] = {}
            for s in r["kids"]:
                if s["name"] not in PLATFORM_SPAN_NAMES:
                    counts[s["name"]] = counts.get(s["name"], 0) + 1
            for name in sorted(counts):
                lines.append(f"  {name} x{counts[name]}")
        lines.append("order: " + ("monotonic" if rec["monotonic"]
                                  else "OUT-OF-ORDER"))
    return "\n".join(lines) + ("\n" if lines else "")


# ------------------------------------------------- serving request breakdown


def request_breakdown(spans: list[dict]) -> list[dict]:
    """Per-request phase accounting — the serving analogue of
    step_breakdown, one dict per `request` root span.

    The request's wall time is its root span's duration (fleet submit →
    done, requeues included). Child spans are charged to their phase
    (REQUEST_PHASE_NAMES: the admission decision, engine queue wait,
    prefill chunks, decode windows — a requeued request's second attempt
    contributes additional queue/prefill/decode time under the SAME
    root) and ``stall`` is DEFINED as the remainder, so

        admission + queue + prefill + decode + stall == wall

    holds EXACTLY on every row (the acceptance pin,
    tests/test_slo.py). Phase charges are clamped in time order so a
    child that overruns the root (clock noise at the requeue seam) can
    never drive stall negative. Rows also carry the reuse ledger
    (reused/computed prefill tokens off the chunk spans' attrs) and the
    request's identity attrs.
    """
    by_parent: dict[str, list[dict]] = {}
    for s in spans:
        if s["name"] in REQUEST_PHASE_NAMES:
            by_parent.setdefault(s.get("parent", ""), []).append(s)
    out: list[dict] = []
    for root in sorted((s for s in spans if s["name"] == REQUEST_ROOT),
                       key=lambda s: s["ts"]):
        wall = root["dur"]
        phases = {p: 0.0 for p in REQUEST_PHASES}
        computed = reused = 0
        remaining = wall
        for child in sorted(by_parent.get(root["span"], []),
                            key=lambda s: s["ts"]):
            phase = REQUEST_PHASE_NAMES[child["name"]]
            charge = min(child["dur"], remaining)
            phases[phase] += charge
            remaining -= charge
            if child["name"] == "engine.prefill_chunk":
                computed += int(child["attrs"].get("tokens_computed", 0))
                reused += int(child["attrs"].get("tokens_reused", 0))
        phases["stall"] = max(remaining, 0.0)
        out.append({
            "request_id": root["attrs"].get("request_id", ""),
            "trace": root["trace"],
            "ts": root["ts"],
            "wall": wall,
            **phases,
            "outcome": root["attrs"].get("outcome", ""),
            "attempts": root["attrs"].get("attempts", 1),
            "tokens": root["attrs"].get("tokens", 0),
            "prefill_tokens_computed": computed,
            "prefill_tokens_reused": reused,
        })
    return out


def aggregate_requests(reqs: list[dict]) -> dict:
    """Totals + distribution over request_breakdown() output — the
    shape /debug/slo, the slo CLI, and the kftpu_request_* families
    render (monitoring/report.py)."""
    walls = sorted(r["wall"] for r in reqs)
    wall = sum(walls)
    totals = {p: sum(r[p] for r in reqs) for p in REQUEST_PHASES}
    by_outcome: dict[str, int] = {}
    for r in reqs:
        key = r["outcome"] or "unknown"
        by_outcome[key] = by_outcome.get(key, 0) + 1
    return {
        "count": len(reqs),
        "wall_s": round(wall, 6),
        "by_outcome": by_outcome,
        "phases_s": {p: round(v, 6) for p, v in totals.items()},
        "fractions": {
            p: (round(v / wall, 4) if wall else 0.0)
            for p, v in totals.items()
        },
        "wall": {
            "mean_s": round(wall / len(reqs), 6) if reqs else 0.0,
            "p50_s": round(percentile(walls, 0.50), 6),
            "p99_s": round(percentile(walls, 0.99), 6),
        },
        "prefill_tokens_computed": sum(
            r["prefill_tokens_computed"] for r in reqs),
        "prefill_tokens_reused": sum(
            r["prefill_tokens_reused"] for r in reqs),
    }


def request_shape(spans: list[dict]) -> str:
    """Canonical, golden-pinnable text form of the serving request
    traces (the restart_shape analogue): every `request` root with its
    outcome/attempts and collapsed child-span counts, then every
    replica-kill event with the requeues parent-linked to it — names
    and parentage only, no ids or times, so a structural regression (a
    dropped carrier, a requeue orphaned from its kill) diffs loudly
    while timing noise never does."""
    by_parent: dict[str, list[dict]] = {}
    for s in spans:
        by_parent.setdefault(s.get("parent", ""), []).append(s)

    def kid_counts(span_id: str) -> list[str]:
        counts: dict[str, int] = {}
        for s in by_parent.get(span_id, []):
            counts[s["name"]] = counts.get(s["name"], 0) + 1
        return [f"  {name} x{counts[name]}" for name in sorted(counts)]

    lines: list[str] = []
    for root in sorted((s for s in spans if s["name"] == REQUEST_ROOT),
                       key=lambda s: s["ts"]):
        lines.append(
            f"request outcome={root['attrs'].get('outcome')} "
            f"attempts={root['attrs'].get('attempts', 1)}")
        lines.extend(kid_counts(root["span"]))
    for kill in sorted(
            (s for s in spans if s["name"] == "fleet.replica_kill"),
            key=lambda s: s["ts"]):
        lines.append(
            f"fleet.replica_kill replica={kill['attrs'].get('replica')}")
        lines.extend(kid_counts(kill["span"]))
    return "\n".join(lines) + ("\n" if lines else "")


def scaler_shape(spans: list[dict]) -> str:
    """Canonical, golden-pinnable text form of the autoscaler's decision
    traces (the request_shape analogue for serving/fleet/scaler.py):
    every `scaler.evaluate` event in time order with its decision and
    demand, then the scale/drain/kill/hang events parent-linked to it as
    collapsed `name xN` counts — names and parentage only, no ids or
    times, so a decision that loses its causal link to the burn
    evaluation that triggered it (the attributability contract) diffs
    loudly while timing noise never does."""
    by_parent: dict[str, list[dict]] = {}
    for s in spans:
        by_parent.setdefault(s.get("parent", ""), []).append(s)
    lines: list[str] = []
    for ev in sorted((s for s in spans if s["name"] == "scaler.evaluate"),
                     key=lambda s: s["ts"]):
        lines.append(
            f"scaler.evaluate decision={ev['attrs'].get('decision')} "
            f"demand={ev['attrs'].get('demand')}")
        counts: dict[str, int] = {}
        for s in by_parent.get(ev["span"], []):
            counts[s["name"]] = counts.get(s["name"], 0) + 1
        for name in sorted(counts):
            lines.append(f"  {name} x{counts[name]}")
    return "\n".join(lines) + ("\n" if lines else "")
